"""Layer 3b: cross-artifact drift censuses (D-rules).

The repo's gated inventories — the telemetry name families, perf_gate's
key sets, the CLI knob surface — were each maintained BY HAND next to
the code that feeds them, and PRs 12-14 all shipped drift: counters
documented but never emitted, emitted keys (``predict_b32_*``) that no
gate read, knobs documented only in prose.  These rules run the
censuses from the graftlint driver so drift fails the pre-merge gate:

- **D1 telemetry-inventory** — the counter/route/span/wire-site names
  the package source actually emits (``telemetry.count``/
  ``count_route``/``span``/``collective_span``/``record_collective``
  string literals, plus telemetry.py's internal ``_counters[...]``
  writes) vs the machine-readable family inventory in ``telemetry.py``
  (``COUNTER_FAMILIES``/``SPAN_FAMILIES``/``WIRE_SITE_FAMILIES``).
  Undocumented usage AND stale documentation are both findings; names
  with runtime-computed suffixes census as ``prefix*`` patterns, and
  fully-dynamic wire sites (variable labels built by the learners) live
  in ``DYNAMIC_WIRE_SITES``, documented but exempt from the stale
  check the static census cannot decide.
- **D2 perf-gate-coverage** — every key in perf_gate's ``RATE_KEYS``/
  ``LATENCY_KEYS``/``ABSOLUTE_ZERO_KEYS``/``ABSOLUTE_TRUE_KEYS`` must
  be emitted by ``bench.py``/``__graft_entry__.py`` or present in a
  recorded ``BENCH_r*``/``MULTICHIP_r*`` round (a stale gate key
  silently gates nothing); and, the other direction, every bench.py
  emission whose name SHAPE marks it gateable (``*_per_sec`` rates,
  ``*_spread`` noise bands, ``*_p99_us`` tails, ``*_recompiles``/
  ``*_misscored`` zero contracts, ``*_restore_exact`` truth contracts)
  must be wired into the matching gate set or carried on the
  documented informational allowlist below.
- **D3 config-knob-inventory** — every parameter a ``*Config.set``
  reads must have an entry in cli.py's machine-readable
  ``KNOB_INVENTORY`` and a reject/fatal path (a typed loud getter, a
  ``log.check``/``log.fatal`` in its parse block, or an explicit
  allowlist justification for free-form/externally-validated values);
  and every dataclass field must be reachable from ``set`` or on the
  internal-field allowlist — a field nobody can set, or a knob nobody
  documented, is drift.

All three operate on SOURCE TEXT handed in by the driver (plus the
stdlib-importable telemetry/hatches inventories), so the layer runs
without JAX like layers 1 and 3a, and tests can feed synthetic
artifact sets to prove each census live.
"""
from __future__ import annotations

import ast
import json
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .ast_rules import _annotate_parents, _attr_chain, _terminal_name
from .findings import Finding

# ----------------------------------------------------------------- D1

# telemetry-name emitting calls: api kind -> (terminal call name, arg
# index of the NAME)
_TELEMETRY_CALLS = {
    "count": ("counter", 0),
    "count_route": ("counter", 1),     # arg 0 is the route group
    "span": ("span", 0),
    "collective_span": ("wire", 0),
    "record_collective": ("wire", 0),
}


def _names_of(arg: ast.AST) -> List[Tuple[str, bool]]:
    """The ``(name, is_prefix)`` resolutions of a telemetry-name
    argument: a plain string constant, both arms of an either/or
    (``"a" if cond else "b"``), the constant head of a ``"x/" + suffix``
    concatenation or an f-string.  Empty when fully dynamic."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return [(arg.value, False)]
    if isinstance(arg, ast.IfExp):
        return _names_of(arg.body) + _names_of(arg.orelse)
    if (isinstance(arg, ast.BinOp) and isinstance(arg.op, ast.Add)
            and isinstance(arg.left, ast.Constant)
            and isinstance(arg.left.value, str)):
        return [(arg.left.value, True)]
    if (isinstance(arg, ast.JoinedStr) and arg.values
            and isinstance(arg.values[0], ast.Constant)
            and isinstance(arg.values[0].value, str)):
        return [(arg.values[0].value, True)]
    return []


def collect_telemetry_usage(files: Dict[str, str]
                            ) -> Dict[Tuple[str, str, bool],
                                      List[Tuple[str, int]]]:
    """Census the package source for telemetry name emissions.

    Returns ``{(kind, name, is_prefix): [(path, line), ...]}`` where
    ``kind`` is counter/span/wire.  ``telemetry.py``'s own internal
    ``_counters[<const>]`` writes census as counters (the compile
    listener's jit/* keys have no public call site)."""
    usage: Dict[Tuple[str, str, bool], List[Tuple[str, int]]] = {}

    def add(kind: str, name: str, prefix: bool, path: str, line: int):
        usage.setdefault((kind, name, prefix), []).append((path, line))

    for path in sorted(files):
        tree = ast.parse(files[path], filename=path)
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                spec = _TELEMETRY_CALLS.get(_terminal_name(node.func))
                if spec is None:
                    continue
                # the receiver must be the telemetry module (or its _tl
                # alias) — a bare str.count()/dict.get() must not census
                chain = _attr_chain(node.func)
                if len(chain) < 2 or not ("telemetry" in chain[-2]
                                          or chain[-2] == "_tl"):
                    continue
                kind, idx = spec
                if len(node.args) <= idx:
                    continue
                for name, is_prefix in _names_of(node.args[idx]):
                    add(kind, name, is_prefix, path, node.lineno)
            elif (isinstance(node, ast.Subscript)
                    and _attr_chain(node.value) == ["_counters"]
                    and path.endswith("telemetry.py")):
                for name, is_prefix in _names_of(node.slice):
                    add("counter", name, is_prefix, path, node.lineno)
    return usage


def _matches(name: str, is_prefix: bool, entries: Iterable[str]) -> bool:
    """Does a censused name fall under any inventory entry?  Entries
    ending in ``*`` are prefix families."""
    for entry in entries:
        if entry.endswith("*"):
            head = entry[:-1]
            if name.startswith(head) or (is_prefix
                                         and head.startswith(name)):
                return True
        elif not is_prefix and name == entry:
            return True
        elif is_prefix and entry.startswith(name):
            return True
    return False


def check_telemetry_inventory(files: Dict[str, str],
                              inventories: Optional[dict] = None,
                              telemetry_path: str =
                              "lightgbm_tpu/telemetry.py"
                              ) -> List[Finding]:
    """D1: code census vs the documented families, both directions."""
    if inventories is None:
        from .. import telemetry
        inventories = {
            "counter": telemetry.COUNTER_FAMILIES,
            "span": telemetry.SPAN_FAMILIES,
            "wire": telemetry.WIRE_SITE_FAMILIES,
            "dynamic": telemetry.DYNAMIC_WIRE_SITES,
        }
    usage = collect_telemetry_usage(files)
    findings: List[Finding] = []
    for (kind, name, is_prefix), sites in sorted(usage.items()):
        entries = tuple(inventories.get(kind, ())) + tuple(
            inventories.get("dynamic", ()) if kind == "wire" else ())
        if not _matches(name, is_prefix, entries):
            path, line = sites[0]
            findings.append(Finding(
                "D1", path, line, kind,
                name + ("*" if is_prefix else ""),
                "telemetry %s name emitted by code but missing from the "
                "documented %s family inventory (telemetry.py) — the "
                "one-source-of-truth doc has drifted" % (kind, kind)))
    # stale documentation: a documented STATIC family entry no code emits
    tel_src = files.get(telemetry_path, "")
    for kind in ("counter", "span", "wire"):
        used = [(n, p) for (k, n, p) in usage if k == kind]
        for entry in inventories.get(kind, ()):
            if entry.endswith("*"):
                head = entry[:-1]
                live = any(n.startswith(head) or n == head.rstrip("/")
                           for n, _p in used)
            else:
                live = any((not p and n == entry)
                           or (p and entry.startswith(n))
                           for n, p in used)
            if not live:
                findings.append(Finding(
                    "D1", telemetry_path,
                    _line_of(tel_src, entry), kind, entry,
                    "documented telemetry %s family entry that no code "
                    "emits — stale documentation gates nothing" % kind))
    return findings


def _line_of(src: str, needle: str) -> int:
    for i, line in enumerate(src.splitlines(), 1):
        if '"%s"' % needle in line or "'%s'" % needle in line:
            return i
    return 0


# ----------------------------------------------------------------- D2

# bench.py emissions that LOOK gateable but are deliberately
# informational — each with the written reason (the D-rule analogue of
# the baseline's justification strings; graftlint reports any entry
# here that stops matching an emission as stale)
D2_INFORMATIONAL = {
    "cuda_anchor_iters_per_sec":
        "the CUDA anchor is the comparison DENOMINATOR, not a lane of "
        "ours — vs_cuda gates the ratio",
    "ingest_sync_rows_per_sec":
        "depth-0 A/B reference of the gated ingest_rows_per_sec lane",
    "ingest_serial_rows_per_sec":
        "same-record serial reference lane of the parallel-parse "
        "must-GROW check (ISSUE 18) — perf_gate consumes it as the "
        "workers-lane baseline, not as its own trend series",
    "predict_scan_b65536_rows_per_sec":
        "legacy per-tree-replay A/B reference the bfs-vs-scan ratio "
        "prices; the BFS lanes are gated",
    "serve_offered_rows_per_sec":
        "the open-loop load generator's OFFERED rate (an input, not an "
        "outcome); serve_rows_per_sec gates the sustained rate",
    "ckpt_on_iters_per_sec":
        "component of the gated ckpt_overhead_pct difference",
    "ckpt_off_iters_per_sec":
        "component of the gated ckpt_overhead_pct difference",
    "repeats_dropped":
        "bench-harness bookkeeping (outlier repeats), not a serving "
        "contract",
    "ckpt_dropped":
        "latest-wins snapshot replacement is the async writer's "
        "DESIGNED backpressure, not a loss",
    "trace_wall_p99_us":
        "the flight-recorder ring's exact per-event p99, a cross-check "
        "of the sketch-computed serve_p99_us LATENCY lane (agreement is "
        "asserted in-bench within bucket resolution)",
}

# name shapes that mark a bench emission gateable, and the perf_gate
# set that must carry it
_D2_MORPHOLOGY = (
    (("_rows_per_sec", "_iters_per_sec"), "rate"),
    (("_spread",), "spread"),
    (("_p99_us",), "latency"),
    (("_recompiles", "_dropped", "_misscored"), "zero"),
    (("_restore_exact",), "true"),
)


def _string_constants(src: str) -> Set[str]:
    return {n.value for n in ast.walk(ast.parse(src))
            if isinstance(n, ast.Constant) and isinstance(n.value, str)}


def recorded_round_keys(paths_to_json: Dict[str, str]) -> Set[str]:
    """Every top-level key of the recorded rounds (``parsed`` unwrapped),
    so historical keys keep their gates even if bench.py moved on."""
    keys: Set[str] = set()
    for _path, text in paths_to_json.items():
        try:
            data = json.loads(text)
        except ValueError:
            continue
        if not isinstance(data, dict):
            continue
        keys.update(data)
        if isinstance(data.get("parsed"), dict):
            keys.update(data["parsed"])
    return keys


def check_perf_gate_coverage(gate_sets: dict, bench_src: str,
                             entry_src: str = "",
                             recorded_keys: Optional[Set[str]] = None,
                             gate_path: str = "scripts/perf_gate.py",
                             bench_path: str = "bench.py",
                             informational: Optional[Dict[str, str]] =
                             None) -> List[Finding]:
    """D2 both directions.  ``gate_sets`` carries perf_gate's four key
    collections (the driver imports the real module; tests hand in
    synthetic ones and their own ``informational`` allowlist)."""
    informational = (D2_INFORMATIONAL if informational is None
                     else informational)
    recorded = recorded_keys or set()
    emitted = _string_constants(bench_src)
    emitted_anywhere = emitted | (_string_constants(entry_src)
                                  if entry_src else set()) | recorded
    rate = tuple(gate_sets.get("RATE_KEYS", ()))
    latency = tuple(gate_sets.get("LATENCY_KEYS", ()))
    zero = tuple(gate_sets.get("ABSOLUTE_ZERO_KEYS", ()))
    true_ = tuple(gate_sets.get("ABSOLUTE_TRUE_KEYS", ()))
    findings: List[Finding] = []

    gate_src = gate_sets.get("_source", "")
    all_gate_keys = ([k for k, _s in rate] + [k for k, _s in latency]
                     + [k for k, _d in zero] + [k for k, _d in true_]
                     + [s for _k, s in rate] + [s for _k, s in latency])
    for key in sorted(set(all_gate_keys)):
        if key not in emitted_anywhere:
            findings.append(Finding(
                "D2", gate_path, _line_of(gate_src, key), "perf_gate",
                key,
                "gate key emitted by neither bench.py/__graft_entry__.py "
                "nor any recorded BENCH_r*/MULTICHIP_r* round — a stale "
                "gate key silently gates nothing"))

    gated = {
        "rate": {k for k, _s in rate},
        "spread": {s for _k, s in rate} | {s for _k, s in latency},
        "latency": {k for k, _s in latency},
        "zero": {k for k, _d in zero},
        "true": {k for k, _d in true_},
    }
    for key in sorted(emitted):
        if key.startswith("_"):
            continue          # a bare suffix literal used to BUILD keys
        for suffixes, kind in _D2_MORPHOLOGY:
            if not any(key.endswith(sfx) and key != sfx
                       for sfx in suffixes):
                continue
            if key in gated[kind] or key in informational:
                continue
            findings.append(Finding(
                "D2", bench_path, _line_of(bench_src, key), "bench",
                key,
                "bench.py emits a %s-shaped key that perf_gate's %s set "
                "does not read and the informational allowlist does not "
                "justify — the lane is measured but ungated"
                % (kind, {"rate": "RATE_KEYS", "spread":
                          "RATE_KEYS/LATENCY_KEYS spread",
                          "latency": "LATENCY_KEYS",
                          "zero": "ABSOLUTE_ZERO_KEYS",
                          "true": "ABSOLUTE_TRUE_KEYS"}[kind])))
            break
    # an informational-allowlist entry matching no emission is itself
    # stale (same contract as the baseline's stale-suppression report)
    for key in sorted(informational):
        if key not in emitted_anywhere:
            findings.append(Finding(
                "D2", bench_path, 0, "bench", key,
                "D2_INFORMATIONAL allowlist entry matches no emitted or "
                "recorded key — remove or re-justify"))
    return findings


# ----------------------------------------------------------------- D3

# free-form / externally-validated knobs: parse-time validation is
# impossible or lives in the component the value selects — each entry
# carries the written justification (printed into the finding when a
# knob drifts ONTO this list without one)
D3_FREEFORM = {
    "data": "required input path; the loader fatals on a missing/"
            "unreadable file (parser.create_parser)",
    "valid_data": "comma list of paths; each load fatals like data",
    "output_model": "output path; open() failure surfaces at write",
    "input_model": "model path; GBDT.from_model_file fatals on junk",
    "output_result": "output path; open() failure surfaces at write",
    "input_init_score": "side-file path; loader fatals on junk",
    "profile_dir": "output directory for jax.profiler traces",
    "metrics_out": "JSONL sink path; telemetry disables the sink loudly "
                   "on open failure (never crashes training)",
    "checkpoint_dir": "directory; write_checkpoint creates it and "
                      "surfaces OSError loudly",
    "label_column": "reference column-selector syntax, resolved (and "
                    "rejected) by io.metadata at load",
    "weight_column": "reference column-selector syntax (as label_column)",
    "group_column": "reference column-selector syntax (as label_column)",
    "ignore_column": "reference column-selector syntax (as label_column)",
    "machine_list_file": "reference-parity option; the TPU bootstrap "
                         "reads env hatches instead",
    "objective": "resolved by objectives.create_objective, which fatals "
                 "on an unknown type",
    "metric": "resolved by metrics.create_metric (unknown names warn "
              "per reference behavior)",
    "predict_buckets": "validated eagerly by predict_bucket_list() "
                       "right after the parse (log.fatal on junk)",
    "label_gain": "parsed by config._parse_label_gain, which log.fatals "
                  "on a malformed double list",
    "device_type": "free-form device selector resolved against "
                   "jax.devices(); mesh construction rejects unknowns",
}

# Config dataclass fields with no knob path BY DESIGN
D3_INTERNAL = {
    "is_parallel": "derived in _check_param_conflict from num_machines/"
                   "tree_learner",
    "is_parallel_find_bin": "derived in _check_param_conflict",
    "tree_config": "nested config dataclass",
    "network_config": "nested config dataclass",
    "io_config": "nested config dataclass",
    "boosting_config": "nested config dataclass",
    "objective_config": "nested config dataclass",
    "metric_config": "nested config dataclass",
}

_TYPED_GETTERS = {"_get_int", "_get_float", "_get_bool"}


def _set_methods(tree: ast.AST):
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef) or not cls.name.endswith(
                "Config"):
            continue
        for item in cls.body:
            if isinstance(item, ast.FunctionDef) and item.name == "set":
                yield cls, item


def _has_loud_call(node: ast.AST) -> bool:
    return any(isinstance(n, ast.Call)
               and _terminal_name(n.func) in ("check", "fatal")
               and _attr_chain(n.func)[:1] == ["log"]
               for n in ast.walk(node))


def collect_knob_census(config_src: str,
                        config_path: str = "lightgbm_tpu/config.py"):
    """Parse config.py: the knob surface (param names read in ``set``
    methods, with how each is validated) and the per-class field sets.

    Returns (params, fields) where ``params`` maps name ->
    {"line", "validated": bool} and ``fields`` maps (class, field) ->
    {"line", "assigned": bool}."""
    tree = ast.parse(config_src, filename=config_path)
    parents = _annotate_parents(tree)
    params: Dict[str, dict] = {}

    def note(name: str, line: int, validated: bool):
        rec = params.setdefault(name, {"line": line, "validated": False})
        rec["validated"] = rec["validated"] or validated

    for _cls, fn in _set_methods(tree):
        # `if "name" in params:` blocks — validated when the If carries a
        # log.check/log.fatal anywhere (body or orelse)
        for node in ast.walk(fn):
            if isinstance(node, ast.If):
                test = node.test
                if (isinstance(test, ast.Compare)
                        and isinstance(test.left, ast.Constant)
                        and isinstance(test.left.value, str)
                        and len(test.ops) == 1
                        and isinstance(test.ops[0], ast.In)
                        and _terminal_name(test.comparators[0])
                        == "params"):
                    note(test.left.value, node.lineno,
                         _has_loud_call(node))
            elif isinstance(node, ast.Call):
                name = _terminal_name(node.func)
                if (name in _TYPED_GETTERS and len(node.args) >= 2
                        and isinstance(node.args[1], ast.Constant)):
                    note(node.args[1].value, node.lineno, True)
                elif (name == "_get_str" and len(node.args) >= 2
                        and isinstance(node.args[1], ast.Constant)):
                    note(node.args[1].value, node.lineno, False)
            elif (isinstance(node, ast.Subscript)
                    and _terminal_name(node.value) == "params"
                    and isinstance(node.slice, ast.Constant)
                    and isinstance(node.slice.value, str)):
                # bare params["x"] read outside an if-in block it already
                # censused — only note, validation decided elsewhere
                note(node.slice.value, node.lineno, False)

    fields: Dict[Tuple[str, str], dict] = {}
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef) or not cls.name.endswith(
                "Config"):
            continue
        assigned = {
            t.attr
            for n in ast.walk(cls)
            if isinstance(n, ast.Assign)
            for t in n.targets
            if isinstance(t, ast.Attribute)
            and _attr_chain(t)[:1] == ["self"]
        }
        for item in cls.body:
            if isinstance(item, ast.AnnAssign) and isinstance(
                    item.target, ast.Name):
                fields[(cls.name, item.target.id)] = {
                    "line": item.lineno,
                    "assigned": item.target.id in assigned,
                }
    return params, fields


def parse_knob_inventory(cli_src: str) -> Dict[str, str]:
    """The ``KNOB_INVENTORY`` dict literal in cli.py (name -> one-line
    description), parsed without importing the module (cli pulls JAX)."""
    tree = ast.parse(cli_src)
    for node in tree.body:
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name)
                        and t.id == "KNOB_INVENTORY"
                        for t in node.targets)
                and isinstance(node.value, ast.Dict)):
            out = {}
            for k, v in zip(node.value.keys, node.value.values):
                if isinstance(k, ast.Constant) and isinstance(
                        v, ast.Constant):
                    out[k.value] = v.value
            return out
    return {}


def check_knob_inventory(config_src: str, cli_src: str,
                         config_path: str = "lightgbm_tpu/config.py",
                         cli_path: str = "lightgbm_tpu/cli.py",
                         freeform: Optional[Dict[str, str]] = None,
                         internal: Optional[Dict[str, str]] = None
                         ) -> List[Finding]:
    """D3: the knob surface vs cli.py's KNOB_INVENTORY + reject paths."""
    freeform = D3_FREEFORM if freeform is None else freeform
    internal = D3_INTERNAL if internal is None else internal
    params, fields = collect_knob_census(config_src, config_path)
    inventory = parse_knob_inventory(cli_src)
    findings: List[Finding] = []
    if not inventory:
        findings.append(Finding(
            "D3", cli_path, 0, "cli", "KNOB_INVENTORY",
            "cli.py has no parseable KNOB_INVENTORY dict literal — the "
            "machine-readable knob inventory is gone"))
        return findings
    for name, rec in sorted(params.items()):
        if name not in inventory:
            findings.append(Finding(
                "D3", config_path, rec["line"], "set", name,
                "config knob read in a *Config.set but missing from "
                "cli.py's KNOB_INVENTORY — undocumented surface"))
        if not rec["validated"] and name not in freeform:
            findings.append(Finding(
                "D3", config_path, rec["line"], "set", name,
                "config knob with neither a typed loud getter, a "
                "log.check/log.fatal in its parse block, nor a "
                "D3_FREEFORM justification — malformed values pass "
                "silently"))
    for name in sorted(inventory):
        if name not in params:
            findings.append(Finding(
                "D3", cli_path, _line_of(cli_src, name), "cli", name,
                "KNOB_INVENTORY entry that no *Config.set reads — "
                "stale knob documentation"))
    for (cls, field), rec in sorted(fields.items()):
        if not rec["assigned"] and field not in internal:
            findings.append(Finding(
                "D3", config_path, rec["line"], cls, field,
                "Config dataclass field that no set()/derivation path "
                "ever assigns and the internal allowlist does not "
                "justify — unreachable configuration"))
    return findings
