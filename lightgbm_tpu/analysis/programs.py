"""Canonical small-schema programs for the jaxpr layer.

One home for the traced-program inventory the J-rules run over: the
serial grow policies, the int8 histogram exchange, the serving BFS walk,
and the (2,2)-mesh parallel learners (data / hybrid / voting) — the same
program family ``__graft_entry__.dryrun_multichip`` exercises, at a
schema small enough that every trace stays inside the tier-1 budget
(``jax.make_jaxpr`` only TRACES; nothing compiles or executes).

Each entry is ``(name, fn, args, axis_env, meta)`` where ``meta`` carries
the GLOBAL feature/bin widths the J1 narrowing check judges against.
Parallel programs are built from the learners' own shard closures
(``learner._grow_fn`` — the exact seam construction production training
uses) wrapped in ``shard_map`` over the learner's own mesh, so the
census is of the REAL programs, not a re-implementation.
"""
from __future__ import annotations

import functools
from typing import List, NamedTuple, Optional

# small-schema constants: big enough that every seam exists (multiple
# splits, multiple features per owned block), small enough to trace in
# milliseconds
F, N, B, LEAVES = 12, 256, 16, 8


class Program(NamedTuple):
    name: str
    fn: object
    args: tuple
    axis_env: tuple          # for make_jaxpr on unmapped collectives
    feature_width: int
    bin_width: int


def _small_data(seed: int = 0):
    import numpy as np
    import jax.numpy as jnp
    rng = np.random.RandomState(seed)
    bins = jnp.asarray(rng.randint(0, B, size=(F, N)).astype(np.int8))
    grad = jnp.asarray(rng.randn(N).astype(np.float32))
    hess = jnp.asarray((rng.rand(N) + 0.1).astype(np.float32))
    row_mask = jnp.ones((N,), jnp.bool_)
    fmask = jnp.ones((F,), jnp.bool_)
    nbins = jnp.full((F,), B, jnp.int32)
    return bins, grad, hess, row_mask, fmask, nbins


def _grow_kwargs(compute_dtype="float32"):
    return dict(num_leaves=LEAVES, num_bins_max=B, min_data_in_leaf=4,
                min_sum_hessian_in_leaf=0.1, max_depth=-1,
                compute_dtype=compute_dtype)


def _serial_program(policy: str, compute_dtype: str) -> Program:
    from ..models.grower_unified import grow_tree_unified
    kwargs = _grow_kwargs(compute_dtype)
    if policy == "leafcompact":
        kwargs["use_pallas_partition"] = False
    fn = functools.partial(grow_tree_unified, policy=policy, **kwargs)
    return Program("grow/serial_%s_%s" % (policy, compute_dtype), fn,
                   _small_data(), (), F, B)


def _hist_int8_dp_program() -> Program:
    """The int8 histogram exchange under a data axis: quantize (scale
    pmax) + int-domain accumulator psum — the bit-identity chain J1
    exists to protect."""
    from ..ops.histogram import build_histogram
    from ..parallel.mesh import DATA_AXIS
    bins, grad, hess, row_mask, _fm, _nb = _small_data()
    fn = functools.partial(build_histogram, num_bins_max=B,
                           backend="matmul", chunk=64,
                           compute_dtype="int8", axis_name=DATA_AXIS)
    return Program("hist/int8_dp", fn, (bins, grad, hess, row_mask),
                   ((DATA_AXIS, 2),), F, B)


def _serving_arrays(T: int, seed: int = 3):
    """Shared serving-program inputs: ``T`` chain trees over the small
    schema (node k -> left leaf ~k, right node k+1; last right a leaf)."""
    import numpy as np
    import jax.numpy as jnp
    rng = np.random.RandomState(seed)
    max_nodes, max_leaves, depth = 4, 5, 3
    codes = jnp.asarray(rng.randint(0, B, size=(F, N)).astype(np.int32))
    sf = jnp.asarray(rng.randint(0, F, size=(T, max_nodes)).astype(np.int32))
    tr = jnp.asarray(rng.randint(0, B, size=(T, max_nodes)).astype(np.int32))
    lc = jnp.asarray(np.tile(~np.arange(max_nodes), (T, 1)).astype(np.int32))
    rc_row = np.arange(1, max_nodes + 1)
    rc_row[-1] = ~max_nodes
    rc = jnp.asarray(np.tile(rc_row, (T, 1)).astype(np.int32))
    leaf_value = jnp.asarray(rng.randn(T, max_leaves).astype(np.float32))
    leaf_q = jnp.asarray(rng.randint(-127, 128,
                                     size=(T, max_leaves)).astype(np.int8))
    scale = jnp.asarray((rng.rand(T) + 0.5).astype(np.float32))
    root_state = jnp.zeros((T,), jnp.int32)
    tree_class = jnp.zeros((T,), jnp.int32)
    return (codes, sf, tr, lc, rc, leaf_value, leaf_q, scale, root_state,
            tree_class, depth)


def _serving_programs() -> "List[Program]":
    from ..ops.scoring import bfs_scores_impl, bfs_scores_int8_impl
    (codes, sf, tr, lc, rc, leaf_value, leaf_q, scale, root_state,
     tree_class, depth) = _serving_arrays(3)
    f32 = Program(
        "serve/bfs_f32",
        functools.partial(bfs_scores_impl, max_depth=depth, num_class=1),
        (codes, sf, tr, lc, rc, leaf_value, root_state, tree_class),
        (), F, B)
    int8 = Program(
        "serve/bfs_int8",
        functools.partial(bfs_scores_int8_impl, max_depth=depth,
                          num_class=1),
        (codes, sf, tr, lc, rc, leaf_q, scale, root_state, tree_class),
        (), F, B)
    return [f32, int8]


def sharded_serving_program(quantize: str = "float32",
                            shards: int = 2) -> Program:
    """The tree-sharded serving BFS program (ISSUE 13): the sharded
    score impl shard_mapped over a real ``("tree",)`` mesh, exactly as
    ``ServingEngine._sharded_mapped`` builds it — so graftlint J2's
    collective census covers the tree-axis exchange seams
    (``serve/tree_carry`` ppermute chain + the ``serve/tree_psum``
    masked broadcast) against what XLA will actually execute."""
    import jax
    from jax.sharding import PartitionSpec as P
    from ..ops.scoring import (bfs_scores_sharded_impl,
                               bfs_scores_sharded_int8_impl)
    from ..parallel.learners import shard_map
    from ..parallel.mesh import TREE_AXIS, get_serving_mesh

    if len(jax.devices()) < shards:
        raise RuntimeError(
            "jaxpr layer needs %d devices (set XLA_FLAGS="
            "--xla_force_host_platform_device_count=8 before importing "
            "jax, as scripts/graftlint.py and tests/conftest.py do)"
            % shards)
    (codes, sf, tr, lc, rc, leaf_value, leaf_q, scale, root_state,
     tree_class, depth) = _serving_arrays(4, seed=5)
    mesh = get_serving_mesh(shards)
    t2, t1 = P(TREE_AXIS, None), P(TREE_AXIS)
    if quantize == "int8":
        impl = functools.partial(
            bfs_scores_sharded_int8_impl, max_depth=depth, num_class=1,
            num_trees=4, shards=shards, axis_name=TREE_AXIS)
        in_specs = (P(), t2, t2, t2, t2, t2, t1, t1, t1)
        args = (codes, sf, tr, lc, rc, leaf_q, scale, root_state,
                tree_class)
    else:
        impl = functools.partial(
            bfs_scores_sharded_impl, max_depth=depth, num_class=1,
            num_trees=4, shards=shards, axis_name=TREE_AXIS)
        in_specs = (P(), t2, t2, t2, t2, t2, t1, t1)
        args = (codes, sf, tr, lc, rc, leaf_value, root_state, tree_class)
    mapped = shard_map(impl, mesh=mesh, in_specs=in_specs, out_specs=P())
    return Program("serve/bfs_sharded_%s" % quantize, mapped, args, (),
                   F, B)


def parallel_grow_program(tree_learner: str, hist_dtype: str = "float32",
                          num_machines: int = 4, feature_shards: int = 2,
                          top_k: int = 2) -> Program:
    """The (2,2)-mesh grow program of a parallel learner, built from the
    learner's OWN shard closure (``_grow_fn``) and mesh — what
    ``dryrun_multichip``'s data/hybrid/voting rows execute, minus the
    jit/booster scaffolding the census does not need."""
    import jax
    from types import SimpleNamespace
    from jax.sharding import PartitionSpec as P
    from ..config import OverallConfig
    from ..parallel import create_parallel_learner
    from ..parallel import learners as learners_mod
    from ..parallel.mesh import DATA_AXIS

    if len(jax.devices()) < num_machines:
        raise RuntimeError(
            "jaxpr layer needs %d devices (set XLA_FLAGS="
            "--xla_force_host_platform_device_count=8 before importing "
            "jax, as scripts/graftlint.py and tests/conftest.py do)"
            % num_machines)
    cfg = OverallConfig()
    params = {"objective": "binary", "num_leaves": str(LEAVES),
              "min_data_in_leaf": "4", "min_sum_hessian_in_leaf": "0.1",
              "learning_rate": "0.1", "tree_learner": tree_learner,
              "num_machines": str(num_machines), "hist_dtype": hist_dtype}
    if tree_learner in ("hybrid", "voting"):
        params["feature_shards"] = str(feature_shards)
    if tree_learner == "voting":
        params["top_k"] = str(top_k)
    cfg.set(params, require_data=False)
    learner = create_parallel_learner(cfg)
    mesh = learner._mesh()
    num_shards = int(mesh.shape[DATA_AXIS])
    fake_gbdt = SimpleNamespace(num_bins_max=B, _pack_spec=None)
    kwargs = learner._grow_kwargs(fake_gbdt)
    shard_fn = learner._grow_fn(kwargs, F, num_shards)
    mapped = learners_mod.shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(None, DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS),
                  P(DATA_AXIS), P(), P()),
        out_specs=learners_mod._tree_out_specs(DATA_AXIS))
    name = "grow/%s_leafwise_%s" % (tree_learner, hist_dtype)
    return Program(name, mapped, _small_data(), (), F, B)


def elastic_programs(shards: int = 2) -> "List[Program]":
    """The elastic-training exchange programs (ISSUE 14), built from
    ``lightgbm_tpu.elastic``'s OWN shard_map constructors over a real
    ``(data,)`` mesh — so the census covers the
    ``elastic/times_allgather`` (per-host iteration seconds) and
    ``elastic/survivor_pmin`` (mesh-shrink agreement) seams against what
    the live straggler policy actually executes."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh
    from ..elastic import mapped_times_fn, mapped_vote_fn
    from ..parallel.mesh import DATA_AXIS

    if len(jax.devices()) < shards:
        raise RuntimeError(
            "jaxpr layer needs %d devices (set XLA_FLAGS="
            "--xla_force_host_platform_device_count=8 before importing "
            "jax, as scripts/graftlint.py and tests/conftest.py do)"
            % shards)
    import numpy as np
    mesh = Mesh(np.array(jax.devices()[:shards]), (DATA_AXIS,))
    times = Program("elastic/times_allgather", mapped_times_fn(mesh),
                    (jnp.zeros((shards,), jnp.float32),), (), F, B)
    votes = Program("elastic/survivor_pmin", mapped_vote_fn(mesh),
                    (jnp.ones((shards,), jnp.int32),), (), F, B)
    return [times, votes]


def canonical_programs(parallel: bool = True) -> "List[Program]":
    """The full inventory.  ``parallel=False`` restricts to programs that
    need no multi-device platform (serial + serving + the axis_env hist
    exchange)."""
    programs = [
        _serial_program("leafwise", "float32"),
        _serial_program("leafwise", "int8"),
        _serial_program("depthwise", "float32"),
        _serial_program("leafcompact", "float32"),
        _hist_int8_dp_program(),
    ]
    programs.extend(_serving_programs())
    if parallel:
        programs.extend([
            parallel_grow_program("data"),
            parallel_grow_program("data", hist_dtype="int8"),
            parallel_grow_program("hybrid"),
            parallel_grow_program("voting"),
            # tree-sharded serving (ISSUE 13): the census proves the
            # serve/tree_carry + serve/tree_psum seams cover every
            # collective the sharded walk executes, f32 and int8
            sharded_serving_program("float32"),
            sharded_serving_program("int8"),
        ])
        # elastic-training exchanges (ISSUE 14): times allgather +
        # survivor pmin, censused against the live policy's programs
        programs.extend(elastic_programs())
    return programs


def trace_program(prog: Program):
    """(closed_jaxpr, telemetry seam inventory) for one program — the
    census-armed trace both J-rules consume."""
    import jax
    from .jaxpr_rules import trace_census
    with trace_census() as holder:
        jaxpr = jax.make_jaxpr(prog.fn,
                               axis_env=list(prog.axis_env) or None)(
            *prog.args)
    return jaxpr, holder.sites
