"""Finding records, the rule catalog, and the baseline/allowlist file.

A finding is one violation of one rule at one source location.  Findings
print as ``path:line RULE symbol: message`` (the perf_gate-style one line
per problem), and the driver exits 1 when any finding survives the
baseline — the same contract as ``scripts/perf_gate.py --check``.

Baseline (``GRAFTLINT_BASELINE.json``): pre-existing accepted sites are
suppressed EXPLICITLY, never silently.  Every entry carries a written
``justification`` string (printed by ``scripts/graftlint.py
--explain-allowlist``); entries match on (rule, path suffix, symbol,
site) — never on line numbers, which drift under unrelated edits.  An
entry that matches nothing is itself reported (stale suppressions rot
into silent holes), so the committed baseline can only shrink or be
consciously re-justified.
"""
from __future__ import annotations

import json
from typing import List, NamedTuple, Optional

# Rule catalog: id -> (title, fix hint).  The README "Static analysis"
# section mirrors this table; tests/test_graftlint.py pins every id fires.
RULES = {
    "R1": ("collective-seam-coverage",
           "wrap the seam with telemetry.collective_span(...) or call "
           "telemetry.record_collective(...) in the same function, so the "
           "wire-metrics inventory (ISSUE 5) sees the exchange"),
    "R2": ("cache-key-completeness",
           "add the resolved-config bit to the program cache key tuple "
           "(the _key_extra/_jit_key/_PROGRAMS-key family) so a mid-process "
           "flip retraces instead of reusing stale kernel routing"),
    "R3": ("span-fencing",
           "bind the span (`with telemetry.span(name) as sp:`) and pass the "
           "device result through sp.fence(...) — an unfenced async span "
           "times the dispatch, not the execution (the PR 7 predict bug)"),
    "R4": ("banned-patterns-in-traced-code",
           "traced grower/ops code must stay jnp-only: no np.*, no host "
           "RNG, no time.*, no float64 — host-side helpers belong outside "
           "the traced modules or on the explicit host allowlist"),
    "J1": ("jaxpr-dtype-discipline",
           "keep the int8 accumulator path in the integer domain until the "
           "canonical reassembly point (no float convert before the int "
           "psum), and never narrow ids below the global feature/bin width "
           "(the PR 9 bf16-split-id bug)"),
    "J2": ("jaxpr-collective-census",
           "the collective eqns XLA will execute must match the declared "
           "telemetry seam inventory — wrap the new collective, or remove "
           "the stale record_collective site"),
    "C1": ("thread-lifecycle-registration",
           "give the thread-owning class a close/stop entry point and "
           "register it with lifecycle.track(...) (bare function spawns "
           "track in the same function), so the shared conftest leak "
           "guard can see a leaked instance"),
    "C2": ("future-set-race",
           "wrap the set_result/set_exception in try/except Exception — "
           "a client cancel in the check→set window raises "
           "InvalidStateError in the worker loop and wedges it (the "
           "PR 13 ServingFront bug class)"),
    "C3": ("blocking-under-lock",
           "move the blocking call (join/sleep/IO/device dispatch/"
           "un-timed queue op) outside the `with <lock>:` body — only "
           "waits on the lock object itself release it"),
    "C4": ("env-hatch-discipline",
           "read the LGBM_TPU_* variable through lightgbm_tpu/hatches "
           "(flag/choice/raw/int_value/float_value) and register it in "
           "hatches.HATCHES — raw os.environ reads silently ignore "
           "typo'd values and escape the generated hatch inventory"),
    "D1": ("telemetry-inventory-census",
           "add the emitted counter/span/wire name to the matching "
           "*_FAMILIES tuple in telemetry.py — or delete the stale "
           "inventory line the code no longer emits (the inventory IS "
           "the family documentation)"),
    "D2": ("perf-gate-coverage-census",
           "wire the emitted key into the matching perf_gate key set "
           "(or justify it on drift_rules.D2_INFORMATIONAL); delete or "
           "re-source gate keys nothing emits — a stale gate key "
           "silently gates nothing"),
    "D3": ("config-knob-census",
           "add the knob to cli.KNOB_INVENTORY and give its parse a "
           "reject path (typed loud getter or log.check/log.fatal), or "
           "justify it on drift_rules.D3_FREEFORM/D3_INTERNAL"),
}


class Finding(NamedTuple):
    rule: str                     # rule id from RULES
    path: str                     # repo-relative (or program-qualified)
    line: int                     # 1-based; 0 when not line-anchored (jaxpr)
    symbol: str                   # enclosing function / program name
    site: str                     # what fired (e.g. "lax.psum", a site name)
    message: str

    def format(self) -> str:
        loc = "%s:%d" % (self.path, self.line) if self.line else self.path
        return "%s %s [%s] %s: %s — fix: %s" % (
            loc, self.rule, self.symbol, self.site, self.message,
            RULES[self.rule][1])

    def key(self) -> tuple:
        return (self.rule, self.path, self.symbol, self.site)


class Baseline:
    """Explicit suppression list.  ``match`` consumes entries so stale
    suppressions (matching nothing by the end of a run) are reportable."""

    def __init__(self, entries: Optional[List[dict]] = None):
        self.entries = list(entries or [])
        self._hit = [False] * len(self.entries)

    @classmethod
    def load(cls, path: str) -> "Baseline":
        with open(path) as f:
            data = json.load(f)
        if not isinstance(data, dict) or "suppressions" not in data:
            raise ValueError("baseline %s: expected {\"suppressions\": [...]}"
                             % path)
        for e in data["suppressions"]:
            missing = {"rule", "path", "symbol", "justification"} - set(e)
            if missing:
                raise ValueError("baseline entry %r missing %s"
                                 % (e, sorted(missing)))
        return cls(data["suppressions"])

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump({"version": 1, "suppressions": self.entries}, f,
                      indent=2, sort_keys=True)
            f.write("\n")

    def matches(self, finding: Finding) -> bool:
        for i, e in enumerate(self.entries):
            if (e["rule"] == finding.rule
                    and finding.path.endswith(e["path"])
                    and e["symbol"] == finding.symbol
                    and e.get("site", finding.site) == finding.site):
                self._hit[i] = True
                return True
        return False

    def stale_entries(self) -> List[dict]:
        return [e for e, h in zip(self.entries, self._hit) if not h]

    @staticmethod
    def entry_for(finding: Finding, justification: str) -> dict:
        return {"rule": finding.rule, "path": finding.path,
                "symbol": finding.symbol, "site": finding.site,
                "justification": justification}


def split_baseline(findings: List[Finding], baseline: Optional[Baseline]):
    """(kept, suppressed) under the baseline (None = keep everything)."""
    if baseline is None:
        return list(findings), []
    kept, suppressed = [], []
    for f in findings:
        (suppressed if baseline.matches(f) else kept).append(f)
    return kept, suppressed
