"""Layer 3a: concurrency-lifecycle rules over the threaded subsystems.

PRs 12-14 each burned a review-hardening pass on the same thread/Future
lifecycle defect family — the ServingFront's cancelled-Future race, the
CheckpointWriter join-timeout thread escaping the leak guard, the armed
fault hatch leaking across tests, the io/parser prefetch thread with no
guard registration at all.  These rules turn that family into a gated
check, like graftlint's R-rules did for the seam/cache-key classes:

- **C1 thread-lifecycle-registration** — every ``threading.Thread``
  spawn site must be reachable by the shared live-object inventory
  (``lightgbm_tpu/lifecycle.py``) the conftest leak guard consumes: a
  spawn inside a class requires BOTH a close/stop/shutdown/join entry
  point on the class and a ``lifecycle.track(...)`` call somewhere in
  the class; a bare function spawn requires the ``track`` call in the
  same function.  A thread class that forgets to register is invisible
  to the guard until someone remembers to extend conftest — exactly the
  hole the parser prefetch thread shipped through.
- **C2 future-set-race** — ``Future.set_result``/``set_exception`` in
  worker code must run inside a ``try`` whose handler absorbs the
  cancelled/``InvalidStateError`` race: a client cancelling between a
  ``cancelled()`` check and the set raises in the WORKER thread, killing
  the serve loop and wedging every later request (the exact PR 13 bug,
  generalized).  A bare ``if not fut.cancelled():`` guard is not enough
  — the check→set window is the race.
- **C3 blocking-under-lock** — no blocking operation lexically inside a
  ``with <lock>:`` body (lock-ish context names: ``*lock*``/``*cv*``/
  ``*cond*``/``*mutex*``): thread ``.join``, ``time.sleep``, ``open``,
  un-timed queue ``get``/``put``, un-timed ``Event.wait``, un-timed
  ``Future.result``, and device dispatch/sync (``device_put``/
  ``block_until_ready``).  ``wait``/``notify`` on the lock object
  itself are exempt (a condition wait RELEASES the lock).  A blocking
  call under a held lock stalls every other thread contending it — the
  ServingFront's submit path must stay wait-free while a batch is on
  device.
- **C4 env-hatch-discipline** — every ``os.environ``/``os.getenv`` read
  of an ``LGBM_TPU_*`` name must go through the loud-reject helper
  (``lightgbm_tpu/hatches.py``), and every helper call must name a
  hatch present in the generated ``hatches.HATCHES`` inventory — a
  typo'd hatch value silently doing nothing, and a hatch missing from
  the inventory, are both the drift this rule retires.  Reads through a
  module-level ``NAME = "LGBM_TPU_..."`` constant are resolved, so the
  rule cannot be laundered through an alias.

Pure ``ast`` plus one optional stdlib-only import (the hatches
inventory) — no JAX, so the layer gates environments where the
accelerator stack is absent, like layer 1.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set

from .ast_rules import (_annotate_parents, _attr_chain, _enclosing,
                        _func_qualname, _terminal_name)
from .findings import Finding

CLOSE_METHODS = frozenset({"close", "stop", "shutdown", "join", "disarm"})
LOCKISH_RE = re.compile(r"(lock|cv|cond|mutex)", re.IGNORECASE)
HATCH_PREFIX = "LGBM_TPU_"
HATCH_HELPERS = frozenset({"flag", "choice", "raw", "int_value",
                           "float_value"})
# exception names whose handler absorbs the Future set race (C2)
C2_HANDLERS = frozenset({"Exception", "BaseException", "InvalidStateError",
                         "CancelledError"})


def _default_hatch_inventory() -> Set[str]:
    from .. import hatches
    return set(hatches.HATCHES)


class ConcurrencyConfig:
    """Per-run knobs, overridable by tests (golden fixtures supply their
    own hatch inventory so the rule checks the CLASS, not today's
    inventory)."""

    def __init__(self, hatch_inventory: Optional[Set[str]] = None,
                 hatch_module_suffixes=("lightgbm_tpu/hatches.py",)):
        self.hatch_inventory = (set(hatch_inventory)
                                if hatch_inventory is not None
                                else _default_hatch_inventory())
        self.hatch_module_suffixes = tuple(hatch_module_suffixes)


def _walk_skip_defs(node: ast.AST):
    """``ast.walk`` that prunes nested function/lambda bodies — code
    defined under a ``with`` runs LATER, not under the lock (C3)."""
    stack = [node]
    while stack:
        cur = stack.pop()
        yield cur
        for child in ast.iter_child_nodes(cur):
            if not isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                stack.append(child)


def _is_lifecycle_track(node: ast.AST) -> bool:
    """A registration call: ``lifecycle.track(...)`` (any alias whose
    penultimate chain element names the lifecycle module)."""
    if not isinstance(node, ast.Call):
        return False
    chain = _attr_chain(node.func)
    return (bool(chain) and chain[-1] == "track"
            and (len(chain) == 1 or "lifecycle" in chain[-2]))


class ConcurrencyLint:
    """One parsed module + the C-rule passes."""

    def __init__(self, path: str, source: str, config: ConcurrencyConfig):
        self.path = path
        self.config = config
        self.tree = ast.parse(source, filename=path)
        self.parents = _annotate_parents(self.tree)
        self.findings: List[Finding] = []
        # module-level NAME = "LGBM_TPU_..." constants (C4 alias chase)
        self.env_consts: Dict[str, str] = {}
        for node in self.tree.body:
            if (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, str)
                    and node.value.value.startswith(HATCH_PREFIX)):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        self.env_consts[tgt.id] = node.value.value

    # ------------------------------------------------------------ rule C1

    def _enclosing_class(self, node: ast.AST) -> Optional[ast.ClassDef]:
        for anc in _enclosing(node, self.parents):
            if isinstance(anc, ast.ClassDef):
                return anc
            if isinstance(anc, ast.Module):
                return None
        return None

    def _enclosing_function(self, node: ast.AST):
        for anc in _enclosing(node, self.parents):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None

    def rule_c1(self) -> None:
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = _attr_chain(node.func)
            if not chain or chain[-1] != "Thread":
                continue
            if len(chain) >= 2 and chain[-2] != "threading":
                continue
            qual = _func_qualname(node, self.parents)
            cls = self._enclosing_class(node)
            if cls is not None:
                has_close = any(
                    isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and n.name in CLOSE_METHODS for n in cls.body)
                registers = any(_is_lifecycle_track(n)
                                for n in ast.walk(cls))
                if not has_close:
                    self.findings.append(Finding(
                        "C1", self.path, node.lineno, qual,
                        "threading.Thread",
                        "thread spawned by class %s, which exposes no "
                        "close/stop/shutdown/join entry point — nothing "
                        "can ever reap it" % cls.name))
                elif not registers:
                    self.findings.append(Finding(
                        "C1", self.path, node.lineno, qual,
                        "threading.Thread",
                        "thread-owning class %s never calls "
                        "lifecycle.track(...) — the shared leak-guard "
                        "inventory cannot see a leaked instance"
                        % cls.name))
                continue
            fn = self._enclosing_function(node)
            if fn is None or not any(_is_lifecycle_track(n)
                                     for n in ast.walk(fn)):
                self.findings.append(Finding(
                    "C1", self.path, node.lineno, qual,
                    "threading.Thread",
                    "bare thread spawn without a lifecycle.track(...) "
                    "registration in the same function — invisible to "
                    "the leak guard"))

    # ------------------------------------------------------------ rule C2

    def _in_guarding_try(self, node: ast.AST) -> bool:
        for anc in _enclosing(node, self.parents):
            if not isinstance(anc, ast.Try):
                continue
            # the call must sit in the try BODY (a set inside a
            # handler/finally is not protected by these handlers)
            in_body = any(node is sub for stmt in anc.body
                          for sub in ast.walk(stmt))
            if not in_body:
                continue
            for handler in anc.handlers:
                if handler.type is None:
                    return True
                types = (handler.type.elts
                         if isinstance(handler.type, ast.Tuple)
                         else [handler.type])
                if any(_terminal_name(t) in C2_HANDLERS for t in types):
                    return True
        return False

    def rule_c2(self) -> None:
        for node in ast.walk(self.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("set_result", "set_exception")):
                continue
            if not self._in_guarding_try(node):
                self.findings.append(Finding(
                    "C2", self.path, node.lineno,
                    _func_qualname(node, self.parents),
                    "." + node.func.attr,
                    "Future %s outside a try/except absorbing the "
                    "cancelled/InvalidStateError race — a client cancel "
                    "in the check→set window raises in the worker loop "
                    "and wedges it" % node.func.attr))

    # ------------------------------------------------------------ rule C3

    @staticmethod
    def _lockish(expr: ast.AST) -> Optional[str]:
        chain = _attr_chain(expr)
        if chain and LOCKISH_RE.search(chain[-1]):
            return ".".join(chain)
        return None

    def _blocking_site(self, node: ast.AST, lock_chain: str
                       ) -> Optional[str]:
        if not isinstance(node, ast.Call):
            return None
        chain = _attr_chain(node.func)
        if not chain:
            return None
        name = chain[-1]
        recv = ".".join(chain[:-1])
        has_timeout = (any(kw.arg == "timeout" for kw in node.keywords)
                       or len(node.args) >= (2 if name == "put" else 1))
        if recv == lock_chain:
            return None           # cv.wait()/notify release/own the lock
        if name == "join" and not isinstance(
                getattr(node.func, "value", None), ast.Constant):
            return ".".join(chain)
        if name == "sleep" and chain[0] == "time":
            return ".".join(chain)
        if name == "open" and len(chain) == 1:
            return "open"
        if name in ("block_until_ready", "device_put"):
            return ".".join(chain)
        if name == "get" and not node.args and not node.keywords:
            return ".".join(chain) + "()"
        if name == "put" and node.args and not has_timeout:
            return ".".join(chain)
        if name in ("wait", "result") and not node.args \
                and not node.keywords:
            return ".".join(chain) + "()"
        return None

    def rule_c3(self) -> None:
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.With):
                continue
            for item in node.items:
                lock_chain = self._lockish(item.context_expr)
                if lock_chain is None:
                    continue
                for stmt in node.body:
                    for sub in _walk_skip_defs(stmt):
                        site = self._blocking_site(sub, lock_chain)
                        if site is not None:
                            self.findings.append(Finding(
                                "C3", self.path, sub.lineno,
                                _func_qualname(sub, self.parents), site,
                                "blocking operation lexically inside "
                                "`with %s:` — every thread contending "
                                "the lock stalls behind it"
                                % lock_chain))

    # ------------------------------------------------------------ rule C4

    def _hatch_name(self, arg: ast.AST) -> Optional[str]:
        """The LGBM_TPU_* name an argument resolves to (constant, or a
        module-level constant alias), else None."""
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return arg.value if arg.value.startswith(HATCH_PREFIX) else None
        if isinstance(arg, ast.Name):
            return self.env_consts.get(arg.id)
        chain = _attr_chain(arg)
        if len(chain) == 2 and chain[-1] in self.env_consts:
            # cross-module alias (faults.ENV_VAR): resolvable only when
            # the constant lives in THIS module; foreign aliases are out
            # of lexical reach and stay the owning module's finding
            return self.env_consts[chain[-1]]
        return None

    def rule_c4(self) -> None:
        if any(self.path.endswith(sfx)
               for sfx in self.config.hatch_module_suffixes):
            return                      # the helper itself reads os.environ
        for node in ast.walk(self.tree):
            name = None
            site = None
            if isinstance(node, ast.Call):
                chain = _attr_chain(node.func)
                if (chain[-2:] == ["environ", "get"]
                        or chain == ["os", "getenv"]) and node.args:
                    name = self._hatch_name(node.args[0])
                    site = ".".join(chain)
                elif (chain and chain[-1] in HATCH_HELPERS
                        and len(chain) >= 2 and "hatches" in chain[-2]
                        and node.args):
                    hname = self._hatch_name(node.args[0])
                    if (hname is not None
                            and hname not in self.config.hatch_inventory):
                        self.findings.append(Finding(
                            "C4", self.path, node.lineno,
                            _func_qualname(node, self.parents), hname,
                            "hatch read through the helper but missing "
                            "from the hatches.HATCHES inventory — the "
                            "generated hatch inventory has drifted"))
                    continue
            elif (isinstance(node, ast.Subscript)
                    and _attr_chain(node.value)[-2:] == ["os", "environ"]):
                par = self.parents.get(node)
                if isinstance(par, (ast.Assign, ast.AugAssign)) \
                        and getattr(par, "targets", [None])[0] is node:
                    continue            # writes (harness arming) are fine
                name = self._hatch_name(node.slice)
                site = "os.environ[...]"
            if name is not None:
                self.findings.append(Finding(
                    "C4", self.path, node.lineno,
                    _func_qualname(node, self.parents), name,
                    "raw %s read of %s bypasses the loud-reject hatch "
                    "helper — a typo'd value silently does nothing "
                    "instead of rejecting" % (site, name)))

    def run(self) -> List[Finding]:
        self.rule_c1()
        self.rule_c2()
        self.rule_c3()
        self.rule_c4()
        return self.findings


def run_concurrency_rules(files: Dict[str, str],
                          config: Optional[ConcurrencyConfig] = None
                          ) -> List[Finding]:
    """Run every C-rule over ``{path: source}``; findings sorted by
    (path, line) like the R-rules."""
    config = config or ConcurrencyConfig()
    findings: List[Finding] = []
    for path in sorted(files):
        findings.extend(ConcurrencyLint(path, files[path], config).run())
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
