"""Layer 1: AST invariant rules over the package source (no JAX import).

The rules encode, as machine checks, the defect classes PRs 3-9 kept
re-fixing in "review-hardened" passes:

- **R1 collective-seam-coverage** — every raw ``lax.psum / psum_scatter /
  all_gather / pmax / pmin`` call site must be covered by the wire-metrics
  layer (ISSUE 5): lexically inside a function that is passed through
  ``telemetry.collective_span`` (directly or via a ``functools.partial``
  alias like the learners' ``_c``), OR inside a function that files its
  own ``telemetry.record_collective`` record, OR explicitly allowlisted
  in the baseline with a justification.  This turns the PR 5/9 prose
  claim "zero unwrapped seams" into a proof the driver re-runs forever.
- **R2 cache-key-completeness** — a function that caches a compiled
  program (a ``*_PROGRAMS[key] = ...`` store, or the ``self._jitted`` +
  ``_jit_key`` pattern) and lexically reads a resolved-config bit
  (``partition_overlap_on()`` / ``pallas_partition_ok()`` /
  ``jax.default_backend()`` / ``leafwise_compact_on()`` / a
  ``device_type`` read) must thread that bit into the key expression
  (directly or through a local the key references) — the PR 3/7 stale-
  kernel-routing class.
- **R3 span-fencing** — a ``telemetry.span(name)`` whose name is in the
  device-work set must bind the span and pass its device result through
  ``.fence(...)``; an unfenced async span times the dispatch, not the
  execution (the PR 7 predict-span bug).
- **R4 banned-patterns-in-traced-code** — functions in the traced
  grower/ops modules must not touch ``np.*`` / ``numpy.*``, host RNG
  (``random.*`` / ``np.random``), ``time.*``, or float64 (``jnp.float64``
  literals / ``dtype="float64"``): host-only constructs inside code
  reachable from a jit either fail at trace time on TPU or silently
  constant-fold trace-time values into the compiled program.

Pure ``ast`` — importable (and runnable) without JAX, so the AST layer
can gate environments where the accelerator stack is absent.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .findings import Finding

COLLECTIVE_NAMES = ("psum", "psum_scatter", "all_gather", "pmax", "pmin",
                    "all_to_all", "ppermute")

# Resolved-config calls whose outcome bakes kernel routing into a traced
# program: any cache-keyed program builder that consults one must carry
# it in the key (R2).
RESOLVED_CONFIG_CALLS = ("partition_overlap_on", "pallas_partition_ok",
                         "default_backend", "leafwise_compact_on")
# Resolved-config READS by attribute/constant name (same rule): the
# device-steering knob __graft_entry__ flips between virtual meshes, and
# the booster's resolved mixed-bin layout spec (``_pack_spec``, a plain
# or BLOCK-LOCAL PackSpec since ISSUE 12) — a traced program bakes the
# per-class histogram pass structure in, so a cached program built while
# reading it must thread the spec (or its digest) into the key.
RESOLVED_CONFIG_READS = ("device_type", "_pack_spec")

# Span names that time asynchronous device work and therefore must fence
# their results (R3).  Host-side spans (eval, model_readback — a blocking
# device_get — predict_encode, the ingest spans whose bodies block
# explicitly) are deliberately NOT in the set.
FENCED_SPANS = frozenset({
    "histogram", "split_find", "partition", "grow", "score_update",
    "valid_update", "train_chunk", "predict", "gradient", "goss",
})

# Module path suffixes whose function bodies are traced (reachable from a
# jit) — the R4 scope.  parallel/learners.py stays out: its module-level
# helpers (balanced_ownership) are host-side by design and its traced
# shard closures live textually beside them.
TRACED_MODULE_SUFFIXES = (
    "models/grower_unified.py",
    "ops/histogram.py", "ops/hist_pallas.py", "ops/split.py",
    "ops/compact.py", "ops/scoring.py", "ops/lookup.py", "ops/sampling.py",
)

R4_BANNED_ROOTS = ("np", "numpy", "time", "random")


class LintConfig:
    """Per-run knobs, overridable by tests (golden fixtures mark their
    tmp modules as traced) and by future callers extending the scope."""

    def __init__(self, traced_suffixes=TRACED_MODULE_SUFFIXES,
                 fenced_spans=FENCED_SPANS,
                 host_allow: Optional[Set[str]] = None):
        self.traced_suffixes = tuple(traced_suffixes)
        self.fenced_spans = frozenset(fenced_spans)
        # function names in traced modules that are host-side by design
        self.host_allow = set(host_allow or ())


def _attr_chain(node: ast.AST) -> List[str]:
    """['jax', 'lax', 'psum'] for ``jax.lax.psum``; [] when not a plain
    name/attribute chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        parts.reverse()
        return parts
    return []


def _terminal_name(func: ast.AST) -> str:
    chain = _attr_chain(func)
    return chain[-1] if chain else ""


def _annotate_parents(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def _enclosing(node: ast.AST, parents) -> List[ast.AST]:
    """Ancestor chain innermost-first (the node itself excluded)."""
    out = []
    cur = parents.get(node)
    while cur is not None:
        out.append(cur)
        cur = parents.get(cur)
    return out


def _func_qualname(node: ast.AST, parents) -> str:
    names = []
    for anc in [node] + _enclosing(node, parents):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            names.append(anc.name)
        elif isinstance(anc, ast.ClassDef):
            names.append(anc.name)
    names.reverse()
    return ".".join(names) or "<module>"


class ModuleLint:
    """One parsed module + the shared precomputations the rules need."""

    def __init__(self, path: str, source: str, config: LintConfig):
        self.path = path
        self.config = config
        self.tree = ast.parse(source, filename=path)
        self.parents = _annotate_parents(self.tree)
        self.findings: List[Finding] = []
        self._collect_span_wrappers()

    # -------------------------------------------------- wrapper discovery

    def _collect_span_wrappers(self) -> None:
        """Names that wrap seams: ``collective_span`` itself plus every
        alias assigned from ``functools.partial(telemetry.collective_span,
        ...)`` (the learners' ``_c``), module-wide.  Then the set of
        function names / lambda nodes passed as arguments to any of
        them."""
        wrapper_names = {"collective_span"}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Assign) and isinstance(node.value,
                                                           ast.Call):
                call = node.value
                if (_terminal_name(call.func) == "partial" and call.args
                        and _terminal_name(call.args[0])
                        == "collective_span"):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            wrapper_names.add(tgt.id)
        self.wrapper_names = wrapper_names
        # (scope node, name) pairs: a wrap only covers a function DEFINED
        # in the same enclosing scope as the wrapper call — a bare
        # module-wide name set would let an unwrapped function silently
        # ride a same-named wrapped one elsewhere in the module
        self.wrapped_fn_refs: Set[tuple] = set()
        self.wrapper_calls: List[ast.Call] = []
        for node in ast.walk(self.tree):
            if (isinstance(node, ast.Call)
                    and _terminal_name(node.func) in wrapper_names):
                self.wrapper_calls.append(node)
                scope = self._scope_of(node)
                for arg in node.args:
                    if isinstance(arg, ast.Name):
                        self.wrapped_fn_refs.add((id(scope), arg.id))

    def _scope_of(self, node: ast.AST) -> ast.AST:
        """Innermost FunctionDef (or the Module) STRICTLY containing
        ``node``."""
        for anc in _enclosing(node, self.parents):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Module)):
                return anc
        return self.tree

    def _in_wrapper_call(self, node: ast.AST) -> bool:
        """True when ``node`` (a lambda / nested expr) sits inside the
        argument list of a collective_span(-alias) call."""
        for anc in _enclosing(node, self.parents):
            if isinstance(anc, ast.Call) and anc in self.wrapper_calls:
                return True
        return False

    def _function_records_collective(self, fn: ast.AST) -> bool:
        for node in ast.walk(fn):
            if (isinstance(node, ast.Call)
                    and _terminal_name(node.func) == "record_collective"):
                return True
        return False

    # ------------------------------------------------------------ rule R1

    def rule_r1(self) -> None:
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = _attr_chain(node.func)
            if (len(chain) < 2 or chain[-1] not in COLLECTIVE_NAMES
                    or chain[-2] != "lax"):
                continue
            covered = False
            for anc in _enclosing(node, self.parents):
                if isinstance(anc, ast.Lambda) and self._in_wrapper_call(anc):
                    covered = True
                    break
                if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    wrapped_here = (id(self._scope_of(anc)),
                                    anc.name) in self.wrapped_fn_refs
                    if wrapped_here or self._function_records_collective(anc):
                        covered = True
                        break
            if not covered:
                self.findings.append(Finding(
                    "R1", self.path, node.lineno,
                    _func_qualname(node, self.parents),
                    "lax." + chain[-1],
                    "raw collective outside any telemetry.collective_span/"
                    "record_collective coverage — the wire-metrics "
                    "inventory (and the J2 census) cannot see it"))

    # ------------------------------------------------------------ rule R2

    @staticmethod
    def _is_programs_store(node: ast.Assign):
        """``X[key] = ...`` where X matches ``*_PROGRAMS`` → the key
        expression node (the subscript index)."""
        for tgt in node.targets:
            if isinstance(tgt, ast.Subscript):
                base = _attr_chain(tgt.value)
                if base and base[-1].endswith("_PROGRAMS"):
                    return tgt.slice
        return None

    def rule_r2(self) -> None:
        for fn in ast.walk(self.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            key_exprs: List[ast.AST] = []
            caches = False
            jitted_attr = False
            key_attr = False
            for node in ast.walk(fn):
                # trigger attribution is INNERMOST-only: a caching store
                # inside a nested closure must not also mark every
                # enclosing function as a caching function (duplicate
                # findings, double baseline entries)
                if (not isinstance(node, ast.Assign)
                        or self._innermost_fn(node) is not fn):
                    continue
                key_node = self._is_programs_store(node)
                if key_node is not None:
                    caches = True
                    key_exprs.append(key_node)
                for tgt in node.targets:
                    if (isinstance(tgt, ast.Attribute)
                            and tgt.attr == "_jitted"):
                        jitted_attr = True
                    if (isinstance(tgt, ast.Attribute)
                            and tgt.attr.endswith("_key")):
                        key_attr = True
                        key_exprs.append(node.value)
            if jitted_attr and key_attr:
                caches = True
            if not caches:
                continue
            # dataflow: the key expression, plus ONE level of local-name
            # pull (``use_pp = ... pallas_partition_ok(...)`` feeding the
            # key tuple).  Deliberately NOT transitive: a resolved-config
            # read laundered through a derived value (num_shards <- mesh
            # <- device_type) loses the bit's identity — two configs can
            # derive the same num_shards from different device_types —
            # so a deep chain must not count as key coverage.
            assigns: Dict[str, List[ast.AST]] = {}
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            assigns.setdefault(tgt.id, []).append(node.value)
            # resolve bare-Name seeds first (``self._jit_key = jit_key``
            # names the tuple one hop away; that hop is seeding, not
            # dataflow depth)
            seeds: List[ast.AST] = []
            for expr in key_exprs:
                if isinstance(expr, ast.Name):
                    seeds.extend(assigns.get(expr.id, []) or [expr])
                else:
                    seeds.append(expr)
            # only BARE name references pull their assignment: a key
            # component ``use_pp`` IS the resolved value, but ``mesh.size``
            # is a derived projection of ``mesh`` that may have lost the
            # resolved bit's identity — deriving must not count as
            # coverage
            closure = list(seeds)
            names: Set[str] = set()
            for expr in seeds:
                for sub in ast.walk(expr):
                    if isinstance(sub, ast.Name):
                        par = self.parents.get(sub)
                        derived = (isinstance(par, (ast.Attribute,
                                                    ast.Subscript))
                                   and par.value is sub)
                        if not derived:
                            names.add(sub.id)
            for n in names:
                closure.extend(assigns.get(n, []))

            def in_key(pred) -> bool:
                return any(pred(sub) for expr in closure
                           for sub in ast.walk(expr))

            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    name = _terminal_name(node.func)
                    if name in RESOLVED_CONFIG_CALLS and not in_key(
                            lambda s, _n=name: isinstance(s, ast.Call)
                            and _terminal_name(s.func) == _n):
                        self.findings.append(Finding(
                            "R2", self.path, node.lineno, fn.name,
                            name + "()",
                            "resolved-config call read while building a "
                            "cached program but absent from its cache "
                            "key — a mid-process flip would reuse stale "
                            "routing"))
            for read in RESOLVED_CONFIG_READS:
                reads = [n for n in ast.walk(fn)
                         if (isinstance(n, ast.Attribute) and n.attr == read)
                         or (isinstance(n, ast.Constant) and n.value == read)]
                if reads and not in_key(
                        lambda s, _r=read: (isinstance(s, ast.Attribute)
                                            and s.attr == _r)
                        or (isinstance(s, ast.Constant) and s.value == _r)):
                    self.findings.append(Finding(
                        "R2", self.path, reads[0].lineno, fn.name, read,
                        "resolved-config read while building a cached "
                        "program but absent from its cache key"))

    # ------------------------------------------------------------ rule R3

    def rule_r3(self) -> None:
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.With):
                continue
            for item in node.items:
                ctx = item.context_expr
                if not (isinstance(ctx, ast.Call)
                        and _terminal_name(ctx.func) == "span"
                        and ctx.args
                        and isinstance(ctx.args[0], ast.Constant)):
                    continue
                name = ctx.args[0].value
                if name not in self.config.fenced_spans:
                    continue
                var = item.optional_vars
                fenced = False
                if isinstance(var, ast.Name):
                    for sub in node.body:
                        for call in ast.walk(sub):
                            if (isinstance(call, ast.Call)
                                    and isinstance(call.func, ast.Attribute)
                                    and call.func.attr == "fence"
                                    and isinstance(call.func.value, ast.Name)
                                    and call.func.value.id == var.id):
                                fenced = True
                if not fenced:
                    self.findings.append(Finding(
                        "R3", self.path, ctx.lineno,
                        _func_qualname(node, self.parents),
                        "span(%r)" % name,
                        "device-work span without a .fence(...) on its "
                        "result — it times the async dispatch, not the "
                        "execution"))

    # ------------------------------------------------------------ rule R4

    def _innermost_fn(self, node: ast.AST):
        """Innermost FunctionDef containing ``node`` (None at module
        level) — each violation/trigger is attributed to exactly ONE
        function, never once per enclosing level of a nested closure."""
        for anc in _enclosing(node, self.parents):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None

    def rule_r4(self) -> None:
        if not any(self.path.endswith(sfx)
                   for sfx in self.config.traced_suffixes):
            return
        for node in ast.walk(self.tree):
            fn = self._innermost_fn(node)
            if fn is None or fn.name in self.config.host_allow:
                continue
            qual = _func_qualname(fn, self.parents)
            chain = []
            if isinstance(node, (ast.Attribute, ast.Name)):
                # only report the OUTERMOST attribute of a chain
                parent = self.parents.get(node)
                if isinstance(parent, ast.Attribute):
                    continue
                chain = _attr_chain(node)
            if chain and chain[0] in R4_BANNED_ROOTS:
                self.findings.append(Finding(
                    "R4", self.path, node.lineno, qual,
                    ".".join(chain),
                    "host-side construct inside a traced module — "
                    "np/host-RNG/time values constant-fold at trace "
                    "time (or fail on TPU)"))
            elif chain and chain[-1] == "float64":
                self.findings.append(Finding(
                    "R4", self.path, node.lineno, qual,
                    ".".join(chain),
                    "float64 literal in traced code — the f64 path "
                    "silently downcasts on TPU and breaks the "
                    "bit-identity chain"))
            elif (isinstance(node, ast.keyword)
                  and node.arg == "dtype"
                  and isinstance(node.value, ast.Constant)
                  and node.value.value == "float64"):
                self.findings.append(Finding(
                    "R4", self.path, node.value.lineno, qual,
                    'dtype="float64"',
                    "float64 dtype string in traced code"))

    def run(self) -> List[Finding]:
        self.rule_r1()
        self.rule_r2()
        self.rule_r3()
        self.rule_r4()
        return self.findings


def run_ast_rules(files: Dict[str, str],
                  config: Optional[LintConfig] = None) -> List[Finding]:
    """Run every AST rule over ``{path: source}``; findings sorted by
    (path, line)."""
    config = config or LintConfig()
    findings: List[Finding] = []
    for path in sorted(files):
        findings.extend(ModuleLint(path, files[path], config).run())
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def lint_package(root: str,
                 config: Optional[LintConfig] = None) -> List[Finding]:
    """Walk a package directory and lint every ``.py`` beneath it."""
    import os
    files: Dict[str, str] = {}
    for dirpath, _dirnames, filenames in os.walk(root):
        if "__pycache__" in dirpath:
            continue
        for fname in filenames:
            if fname.endswith(".py"):
                full = os.path.join(dirpath, fname)
                with open(full) as f:
                    files[full] = f.read()
    return run_ast_rules(files, config)
