"""graftlint: AST + jaxpr invariant analysis over the package (ISSUE 10).

PRs 3-9 each needed manual "review-hardened" passes to catch the same
recurring defect classes — unwrapped collective seams, jit cache keys
missing a resolved-config bit, unfenced device-work spans, width-unsafe
dtype narrowing, f32 contamination of the int8 bit-identity chain.  This
package encodes those invariants ONCE as machine-checked rules:

- **Layer 1 (AST, no JAX import)** — ``ast_rules``: R1
  collective-seam-coverage, R2 cache-key-completeness, R3 span-fencing,
  R4 banned-patterns-in-traced-code.
- **Layer 2 (jaxpr)** — ``jaxpr_rules`` over the canonical small-schema
  programs (``programs``): J1 dtype discipline on the int8 accumulator
  path, J2 collective census vs the declared telemetry seam inventory.
- **Layer 3 (ISSUE 15, no JAX import)** — ``concurrency_rules``: C1
  thread-lifecycle-registration, C2 future-set-race, C3
  blocking-under-lock, C4 env-hatch-discipline over the threaded
  subsystems; and ``drift_rules``: D1 telemetry-inventory, D2
  perf-gate-coverage, D3 config-knob-inventory cross-artifact censuses.

Drive it with ``python scripts/graftlint.py --check`` (exit 0 clean / 1
findings / 2 tool error, mirroring perf_gate) or through the tier-1
wrapper in tests/test_graftlint.py.  Accepted sites live in
``GRAFTLINT_BASELINE.json`` with written justifications — suppression is
always explicit, never silent.
"""
from .findings import RULES, Baseline, Finding               # noqa: F401
from .ast_rules import (LintConfig, lint_package,            # noqa: F401
                        run_ast_rules)
from .concurrency_rules import (ConcurrencyConfig,           # noqa: F401
                                run_concurrency_rules)
from .driver import (ALL_LAYERS, GraftlintError,             # noqa: F401
                     default_baseline_path, package_root, run,
                     run_ast_layer, run_concurrency_layer,
                     run_drift_layer, run_jaxpr_layer)
