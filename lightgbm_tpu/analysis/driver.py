"""graftlint driver: run the analysis layers, apply the baseline, shape
the exit.

Four layers (ISSUE 10 + ISSUE 15): "ast" (R-rules), "jaxpr" (J-rules
over the canonical traced programs), "concurrency" (C-rules over the
threaded subsystems) and "drift" (D-rule cross-artifact censuses:
telemetry families, perf_gate key coverage, the CLI knob inventory).

Shared by ``scripts/graftlint.py`` (the pre-merge CLI beside
``perf_gate.py --check``) and the tier-1 pytest wrapper
(tests/test_graftlint.py) so the gate and the test suite can never
disagree about what "clean" means.  Exit-code contract mirrors
perf_gate: 0 clean / 1 findings / 2 tool error.
"""
from __future__ import annotations

import functools
import glob
import importlib.util
import os
from typing import Dict, List, Optional, Tuple

from .ast_rules import LintConfig, lint_package
from .concurrency_rules import ConcurrencyConfig, run_concurrency_rules
from .findings import Baseline, Finding, split_baseline

ALL_LAYERS = ("ast", "jaxpr", "concurrency", "drift")


class GraftlintError(Exception):
    """Tool failure (exit 2) — distinct from findings (exit 1)."""


def package_root() -> str:
    """The lightgbm_tpu package directory (the AST layer's scope)."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def repo_root() -> str:
    return os.path.dirname(package_root())


def default_baseline_path() -> str:
    return os.path.join(repo_root(), "GRAFTLINT_BASELINE.json")


def _package_sources(root: str) -> Dict[str, str]:
    files: Dict[str, str] = {}
    for dirpath, _dirnames, filenames in os.walk(root):
        if "__pycache__" in dirpath:
            continue
        for fname in filenames:
            if fname.endswith(".py"):
                full = os.path.join(dirpath, fname)
                with open(full) as f:
                    files[full] = f.read()
    return files


def run_ast_layer(root: Optional[str] = None,
                  config: Optional[LintConfig] = None,
                  files: Optional[Dict[str, str]] = None) -> List[Finding]:
    try:
        if files is None:
            return lint_package(root or package_root(), config)
        from .ast_rules import run_ast_rules
        return run_ast_rules(files, config)
    except SyntaxError as e:
        raise GraftlintError("AST layer cannot parse %s: %s"
                             % (getattr(e, "filename", "?"), e))


def run_concurrency_layer(root: Optional[str] = None,
                          config: Optional[ConcurrencyConfig] = None,
                          files: Optional[Dict[str, str]] = None
                          ) -> List[Finding]:
    """Layer 3a: C-rules over the package source (no JAX import)."""
    try:
        return run_concurrency_rules(
            files if files is not None
            else _package_sources(root or package_root()), config)
    except SyntaxError as e:
        raise GraftlintError("concurrency layer cannot parse %s: %s"
                             % (getattr(e, "filename", "?"), e))


def _load_perf_gate(repo: str):
    path = os.path.join(repo, "scripts", "perf_gate.py")
    spec = importlib.util.spec_from_file_location("_graftlint_perf_gate",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def run_drift_layer(root: Optional[str] = None,
                    files: Optional[Dict[str, str]] = None
                    ) -> List[Finding]:
    """Layer 3b: cross-artifact censuses (D1 telemetry families, D2
    perf_gate coverage, D3 config knob inventory).  Reads the repo's
    real artifacts; stdlib only (no JAX)."""
    from .drift_rules import (check_knob_inventory,
                              check_perf_gate_coverage,
                              check_telemetry_inventory,
                              recorded_round_keys)
    pkg = root or package_root()
    repo = os.path.dirname(pkg)
    if files is None:
        files = _package_sources(pkg)
    findings: List[Finding] = []
    try:
        tel_path = next(p for p in files if p.endswith("telemetry.py"))
        findings.extend(check_telemetry_inventory(
            files, telemetry_path=tel_path))
        gate_mod = _load_perf_gate(repo)
        with open(os.path.join(repo, "scripts", "perf_gate.py")) as f:
            gate_src = f.read()
        gate_sets = {
            "RATE_KEYS": gate_mod.RATE_KEYS,
            "LATENCY_KEYS": gate_mod.LATENCY_KEYS,
            "ABSOLUTE_ZERO_KEYS": gate_mod.ABSOLUTE_ZERO_KEYS,
            "ABSOLUTE_TRUE_KEYS": gate_mod.ABSOLUTE_TRUE_KEYS,
            "_source": gate_src,
        }
        with open(os.path.join(repo, "bench.py")) as f:
            bench_src = f.read()
        entry_path = os.path.join(repo, "__graft_entry__.py")
        entry_src = ""
        if os.path.exists(entry_path):
            with open(entry_path) as f:
                entry_src = f.read()
        rounds = {}
        for pat in ("BENCH_r*.json", "MULTICHIP_r*.json"):
            for p in glob.glob(os.path.join(repo, pat)):
                with open(p) as f:
                    rounds[p] = f.read()
        findings.extend(check_perf_gate_coverage(
            gate_sets, bench_src, entry_src,
            recorded_keys=recorded_round_keys(rounds),
            gate_path=os.path.join(repo, "scripts", "perf_gate.py"),
            bench_path=os.path.join(repo, "bench.py")))
        cfg_path = next(p for p in files if p.endswith("config.py")
                        and "analysis" not in p)
        cli_path = next(p for p in files if p.endswith("cli.py"))
        findings.extend(check_knob_inventory(
            files[cfg_path], files[cli_path],
            config_path=cfg_path, cli_path=cli_path))
    except GraftlintError:
        raise
    except (OSError, StopIteration, SyntaxError, AttributeError) as e:
        raise GraftlintError("drift layer failed: %s: %s"
                             % (type(e).__name__, e))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


@functools.lru_cache(maxsize=4)
def _jaxpr_layer_cached(parallel: bool) -> Tuple[Finding, ...]:
    """Trace + check the canonical programs ONCE per process: the traces
    dominate the layer's cost, and the tier-1 wrapper and the census
    cross-check tests share one session's worth."""
    from .jaxpr_rules import (check_collective_census,
                              check_dtype_discipline)
    from .programs import canonical_programs, trace_program
    findings: List[Finding] = []
    for prog in canonical_programs(parallel=parallel):
        jaxpr, sites = trace_program(prog)
        findings.extend(check_dtype_discipline(
            jaxpr, program=prog.name, feature_width=prog.feature_width,
            bin_width=prog.bin_width))
        findings.extend(check_collective_census(prog.name, jaxpr, sites))
    return tuple(findings)


def run_jaxpr_layer(parallel: bool = True) -> List[Finding]:
    try:
        return list(_jaxpr_layer_cached(parallel))
    except GraftlintError:
        raise
    except Exception as e:
        raise GraftlintError("jaxpr layer failed: %s: %s"
                             % (type(e).__name__, e))


def run(layers=ALL_LAYERS, baseline: Optional[Baseline] = None,
        root: Optional[str] = None,
        config: Optional[LintConfig] = None) -> dict:
    """Run the requested layers and split by the baseline.  Returns
    ``{"findings", "suppressed", "stale_baseline"}``; raises
    GraftlintError on tool failure."""
    findings: List[Finding] = []
    # one disk pass shared by every source-reading layer: all of them
    # lint the identical snapshot, and a default --check stops slurping
    # the package three times over
    files: Optional[Dict[str, str]] = None
    if {"ast", "concurrency", "drift"} & set(layers):
        files = _package_sources(root or package_root())
    if "ast" in layers:
        findings.extend(run_ast_layer(root, config, files=files))
    if "jaxpr" in layers:
        findings.extend(run_jaxpr_layer())
    if "concurrency" in layers:
        findings.extend(run_concurrency_layer(root, files=files))
    if "drift" in layers:
        findings.extend(run_drift_layer(root, files=files))
    kept, suppressed = split_baseline(findings, baseline)
    return {
        "findings": kept,
        "suppressed": suppressed,
        "stale_baseline": baseline.stale_entries() if baseline else [],
    }
