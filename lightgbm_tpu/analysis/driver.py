"""graftlint driver: run both layers, apply the baseline, shape the exit.

Shared by ``scripts/graftlint.py`` (the pre-merge CLI beside
``perf_gate.py --check``) and the tier-1 pytest wrapper
(tests/test_graftlint.py) so the gate and the test suite can never
disagree about what "clean" means.  Exit-code contract mirrors
perf_gate: 0 clean / 1 findings / 2 tool error.
"""
from __future__ import annotations

import functools
import os
from typing import List, Optional, Tuple

from .ast_rules import LintConfig, lint_package
from .findings import Baseline, Finding, split_baseline


class GraftlintError(Exception):
    """Tool failure (exit 2) — distinct from findings (exit 1)."""


def package_root() -> str:
    """The lightgbm_tpu package directory (the AST layer's scope)."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def repo_root() -> str:
    return os.path.dirname(package_root())


def default_baseline_path() -> str:
    return os.path.join(repo_root(), "GRAFTLINT_BASELINE.json")


def run_ast_layer(root: Optional[str] = None,
                  config: Optional[LintConfig] = None) -> List[Finding]:
    try:
        return lint_package(root or package_root(), config)
    except SyntaxError as e:
        raise GraftlintError("AST layer cannot parse %s: %s"
                             % (getattr(e, "filename", "?"), e))


@functools.lru_cache(maxsize=4)
def _jaxpr_layer_cached(parallel: bool) -> Tuple[Finding, ...]:
    """Trace + check the canonical programs ONCE per process: the traces
    dominate the layer's cost, and the tier-1 wrapper and the census
    cross-check tests share one session's worth."""
    from .jaxpr_rules import (check_collective_census,
                              check_dtype_discipline)
    from .programs import canonical_programs, trace_program
    findings: List[Finding] = []
    for prog in canonical_programs(parallel=parallel):
        jaxpr, sites = trace_program(prog)
        findings.extend(check_dtype_discipline(
            jaxpr, program=prog.name, feature_width=prog.feature_width,
            bin_width=prog.bin_width))
        findings.extend(check_collective_census(prog.name, jaxpr, sites))
    return tuple(findings)


def run_jaxpr_layer(parallel: bool = True) -> List[Finding]:
    try:
        return list(_jaxpr_layer_cached(parallel))
    except GraftlintError:
        raise
    except Exception as e:
        raise GraftlintError("jaxpr layer failed: %s: %s"
                             % (type(e).__name__, e))


def run(layers=("ast", "jaxpr"), baseline: Optional[Baseline] = None,
        root: Optional[str] = None,
        config: Optional[LintConfig] = None) -> dict:
    """Run the requested layers and split by the baseline.  Returns
    ``{"findings", "suppressed", "stale_baseline"}``; raises
    GraftlintError on tool failure."""
    findings: List[Finding] = []
    if "ast" in layers:
        findings.extend(run_ast_layer(root, config))
    if "jaxpr" in layers:
        findings.extend(run_jaxpr_layer())
    kept, suppressed = split_baseline(findings, baseline)
    return {
        "findings": kept,
        "suppressed": suppressed,
        "stale_baseline": baseline.stale_entries() if baseline else [],
    }
