"""Fault-injection hooks for preemption/straggler testing (ISSUE 14).

``LGBM_TPU_FAULT_AT=<iter>[,<kind>]`` arms a one-shot fault that fires at
the first iteration boundary at or past ``<iter>`` in ``run_training``
(between iterations — never mid-dispatch), on the designated process only
(``LGBM_TPU_FAULT_PROC``, default 0).  Kinds:

- ``kill`` (default): ``SIGKILL`` the process — the preemption the
  checkpoint/restore machinery exists for.  No Python cleanup runs, which
  is exactly the point: durability must come from the already-written
  atomic checkpoints, not from exit handlers.
- ``stall``: sleep ``LGBM_TPU_FAULT_STALL_S`` seconds (default 1.0) once
  — a synthetic persistent straggler / hung-host window for the
  watchdog and mesh-shrink paths.
- ``raise``: raise ``RuntimeError("injected fault ...")`` — exercises
  the crash-flush path deterministically.

Unit tests arm programmatically with ``arm()``/``disarm()`` instead of
the env var.  Either way the hatch is process-global state: the conftest
leak guard fails any test that leaves it armed (a later test's training
loop would be killed by a foreign fault).

No test should ever need to race a real preemption: the dryrun harness
rows and tests/test_checkpoint.py / tests/test_elastic.py all drive this
hatch.
"""
from __future__ import annotations

import os
import signal
import time
from typing import Optional, Tuple

from . import hatches, lifecycle
from .utils import log

ENV_VAR = "LGBM_TPU_FAULT_AT"
ENV_PROC = "LGBM_TPU_FAULT_PROC"
ENV_STALL_S = "LGBM_TPU_FAULT_STALL_S"
KINDS = ("kill", "stall", "raise")

# programmatic arming (tests): (iteration, kind, proc) or None
_armed: Optional[Tuple[int, str, int]] = None
_fired = False


def parse_spec(spec: str) -> Tuple[int, str]:
    """``"<iter>[,<kind>]"`` -> (iteration, kind); loud reject on junk."""
    parts = [p.strip() for p in str(spec).split(",") if p.strip()]
    if not parts:
        log.fatal("%s must be '<iter>[,<kind>]', got %r" % (ENV_VAR, spec))
    try:
        iteration = int(parts[0])
    except ValueError:
        log.fatal("%s iteration must be an int, got %r"
                  % (ENV_VAR, parts[0]))
    if iteration < 0:
        log.fatal("%s iteration must be >= 0, got %d" % (ENV_VAR, iteration))
    kind = parts[1] if len(parts) > 1 else "kill"
    if kind not in KINDS:
        log.fatal("%s kind must be one of %s, got %r"
                  % (ENV_VAR, "/".join(KINDS), kind))
    if len(parts) > 2:
        log.fatal("%s takes at most '<iter>,<kind>', got %r"
                  % (ENV_VAR, spec))
    return iteration, kind


def arm(iteration: int, kind: str = "kill", proc: int = 0) -> None:
    """Programmatic arming (unit tests) — beats the env var."""
    global _armed, _fired
    if kind not in KINDS:
        log.fatal("fault kind must be one of %s, got %r"
                  % ("/".join(KINDS), kind))
    _armed = (int(iteration), kind, int(proc))
    _fired = False


def disarm() -> None:
    global _armed, _fired
    _armed = None
    _fired = False


def clear() -> None:
    """Disarm AND drop the env-var arming — the leak-guard closer (a
    foreign fault left armed either way would kill a later test's
    training loop at its configured iteration)."""
    disarm()
    os.environ.pop(ENV_VAR, None)


def armed() -> bool:
    """True when a fault hatch is live — programmatic OR env (the shared
    leak-guard inventory probes this after every test)."""
    return _armed is not None or bool(hatches.raw(ENV_VAR))


# the armed hatch is process-global state like a live thread: register it
# with the shared lifecycle inventory so the conftest leak guard (and
# graftlint C1's census of guard-visible subsystems) reads ONE registry
lifecycle.probe("fault-hatch", armed, clear)


def _spec() -> Optional[Tuple[int, str, int]]:
    if _armed is not None:
        return _armed
    env = hatches.raw(ENV_VAR)
    if not env:
        return None
    iteration, kind = parse_spec(env)
    proc = hatches.int_value(ENV_PROC, 0)
    return iteration, kind, proc


def maybe_fire(iteration: int) -> None:
    """Fire the armed fault once the training loop reaches its iteration
    (called from ``run_training`` at iteration boundaries).  No-op when
    nothing is armed, when this is not the designated process, or when a
    one-shot fault already fired."""
    global _fired
    if _fired:
        return
    spec = _spec()
    if spec is None:
        return
    at, kind, proc = spec
    if iteration < at:
        return
    try:
        import jax
        if jax.process_index() != proc:
            return
    except Exception:
        if proc != 0:
            return
    _fired = True
    if kind == "kill":
        log.warning("fault injection: SIGKILL at iteration %d" % iteration)
        # flush whatever the log layer buffers — SIGKILL runs no handlers
        try:
            import sys
            sys.stdout.flush()
            sys.stderr.flush()
        except Exception:
            pass
        os.kill(os.getpid(), signal.SIGKILL)
    elif kind == "stall":
        stall = hatches.float_value(ENV_STALL_S, 1.0)
        log.warning("fault injection: stalling %.3fs at iteration %d"
                    % (stall, iteration))
        time.sleep(stall)
    else:
        log.warning("fault injection: raising at iteration %d" % iteration)
        # flight-recorder hatch dump (ISSUE 16): flush the last-N-events
        # ring BEFORE the raise — run_training's crash path also dumps,
        # but a raise escaping outside run_training would otherwise
        # leave no timeline at all
        from . import monitor, tracing
        # close the monitor's in-flight window first: its slo_breach /
        # monitor_window events must be IN the ring the dump flushes
        monitor.flush_on_fault("injected_raise")
        tracing.dump_on_fault("injected_raise")
        raise RuntimeError("injected fault at iteration %d" % iteration)
