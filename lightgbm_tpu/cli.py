"""CLI application driver: ``lightgbm-tpu key=value ... [config=train.conf]``.

Re-design of /root/reference/src/application/application.cpp:28-302 and
src/main.cpp.  Same surface: ``task=train|predict``, config files from
examples/ run unchanged (only ``device_type`` is TPU-specific and optional).
Distributed runs replace socket/MPI bootstrap (application.cpp:202-205) with
jax.distributed + a device mesh (lightgbm_tpu/parallel/).

TPU-native training knobs beyond the reference surface (all parsed as
ordinary ``key=value`` options, see config.py for semantics):
``grow_policy``, ``hist_dtype``, ``hist_chunk``, ``dp_schedule``,
``leafwise_compact``, ``leafwise_segments``, ``quant_rounding``,
``mixed_bin`` (per-bin-width-class histogram passes, ISSUE 6) and
``pipeline`` (deferred-readback boosting, ISSUE 6).  ``grow_policy`` and
``hist_dtype`` are documented accuracy/order trades; all the others are
model-invariant — flipping them changes speed, never trees.

Serving knobs (``task=predict``, ISSUE 7 — lightgbm_tpu/serving.py):
``predict_buckets`` (the compiled batch-shape ladder, default
``1,32,1024,65536``; pad-to-bucket keeps steady-state serving at zero
recompiles), ``predict_quantize`` (``float32`` = bit-equal to the
training-side scorer; ``int8`` = quantized leaf values at a quarter of
the table traffic — routing stays exact), ``predict_donate`` (donate the
codes buffer; ``auto`` = accelerators only) and ``predict_algo``
(``bfs`` lockstep breadth-first walk, ``scan`` = legacy per-tree replay
for A/B).  All four are score transforms of the SAME model — only
``predict_quantize=int8`` changes values, by the documented quantization
step.

Distributed elastic serving knobs (ISSUE 13 — same module):
``serve_shards`` shards the flattened ensemble's [T, ...] node tables
contiguously over a 1-D ``("tree",)`` device mesh (0 = single-device;
>1 must not exceed the available devices — loud reject, never a silent
shrink); sharded scores stay BIT-equal to the single-device engine
(f32 and int8) via the canonical-order carry chain + one masked psum
(``serve/tree_psum``).  ``predict_linger_us`` is the cross-request
coalescing front's max linger (a queued request dispatches at latest
this long after its batch's first arrival; 0 = immediately) and
``predict_queue`` bounds in-flight work in top-bucket batches (the
front's queue blocks when full — backpressure, never load shedding —
and ``predict_file`` keeps that many parsed chunks in flight).  All
three are score-invariant: they change latency/placement, never a
result bit (``predict_algo=scan`` composes with none of them beyond
``serve_shards=0`` — the replay is the single-device A/B).

Parallel-training knobs (ISSUE 9 — lightgbm_tpu/parallel/):
``tree_learner`` now spans ``serial|feature|data|hybrid|voting``.
``hybrid`` trains on an explicit 2-D ``(data, feature)`` mesh —
``num_machines = data_shards × feature_shards``, rows sharded on
``data``, feature-block ownership on ``feature``, per-shard histogram
wire bytes cut by ``feature_shards`` — and ``voting`` realizes the
reference's named-but-absent PV-tree mode (top-k per-shard split
voting; full histograms exchanged only for the ≤2·top_k voted
features).  ``feature_shards`` (0 = auto-factor; nonzero must divide
``num_machines``) picks the mesh factoring and ``top_k`` (default 20)
the vote width.  Both learners hold the repo's standing equivalence
bar vs serial (int8 bit-identical; f32 tie-keyed) — voting is exact
whenever 2·top_k covers the owned block, the PV-tree approximation
beyond that.

Streaming ingestion & on-device sampling knobs (ISSUE 8 —
lightgbm_tpu/io/streaming.py + ops/sampling.py): ``streaming``
(``auto`` engages the chunked parse→bin→HBM loader for files ≥256 MB;
``true``/``false`` force — datasets/models are bit-identical either
way), ``ingest_chunk_rows`` (the parse/bin/transfer chunk length, and
the bound on host-resident raw rows; default 200k),
``bagging_device`` (``auto`` draws bagging masks on-device on
accelerator backends — a redraw becomes a threefry key bump instead of
a host full-N draw + upload; the RNG STREAM differs from the host
path, so trees differ by the sampling draw only;
``LGBM_TPU_HOST_BAGGING=1`` is the A/B hatch) and ``goss`` +
``top_rate``/``other_rate`` (gradient-based one-side sampling, run
entirely on device; incompatible with bagging; traced INSIDE the fused
chunk programs since ISSUE 12 — sampled iterations keep the fused-k
dispatch on serial, data/hybrid/voting and feature-parallel learners,
and multi-process GOSS is supported on the chunk path,
grow_policy=depthwise).  ``mixed_bin`` composes with
``tree_learner=hybrid|voting`` via the block-local layout (the class
permutation never crosses an ownership block boundary; degenerates to
uniform, with a warning under ``mixed_bin=true``, when an ownership
block has no narrow feature).
``streaming``/``ingest_chunk_rows``/``bagging_device`` are
model-invariant; ``goss`` changes the trained model by design.

Preemption-safe elastic training knobs (ISSUE 14 —
lightgbm_tpu/checkpoint.py + elastic.py): ``checkpoint_interval``
(iterations between asynchronous atomic checkpoints; 0 = off; > 0
REQUIRES ``checkpoint_dir`` — loud reject otherwise) and
``checkpoint_dir`` (where the ``ckpt-<iter>.json`` files live; a
``task=train`` restart pointing at a dir holding a checkpoint RESUMES
from the latest one: bit-identical continuation — model text, scores,
RNG streams — on the same topology, the documented cross-schedule
budgets on a different ``num_machines``, where ``factor_machines``
re-runs on the surviving count and the binary cache re-shards through
the streaming loader).  ``checkpoint_keep`` (>= 1, loud reject at 0)
bounds retained checkpoint files; the write-temp+rename discipline
guarantees a crash mid-write leaves the previous checkpoint loadable.
``elastic_shrink`` (true/false; requires a parallel ``tree_learner`` —
loud reject under serial) arms the live straggler policy: the
persistent-straggler rule (same implementation as
scripts/timeline_report.py, ``straggler_k`` >= 1 consecutive
strictly-slowest iterations) triggers a drain-at-iteration-boundary
mesh shrink — checkpoint, drop the flagged slot, re-factor, resume.
``checkpoint_*`` knobs are model-invariant (a resumed run reproduces
the uninterrupted one); ``elastic_shrink`` changes topology mid-run and
therefore lands in the same cross-schedule budget class as choosing
that topology at startup.  ``LGBM_TPU_FAULT_AT=<iter>[,<kind>]``
(lightgbm_tpu/faults.py) is the test/harness hatch that kills or stalls
the designated process at an iteration boundary.
"""
from __future__ import annotations

import sys
import time
from typing import List

# --------------------------------------------------------------------------
# THE machine-readable knob inventory (ISSUE 15): one entry per canonical
# ``key=value`` parameter any *Config.set reads (aliases resolve through
# config.ALIAS_TABLE first).  graftlint D3 (analysis/drift_rules.py)
# cross-checks this dict against config.py both ways — a knob parsed but
# undocumented here, or an entry here nothing parses, fails the pre-merge
# gate — so the CLI surface can no longer drift by convention.  Keep the
# values one line: they are the --help-style summary; full semantics live
# on the config.py field comments.

KNOB_INVENTORY = {
    # task / component selection
    "task": "train or predict",
    "boosting_type": "gbdt (gbrt alias)",
    "objective": "objective name (regression/binary/multiclass/lambdarank)",
    "metric": "comma list of eval metric names",
    "device_type": "device selector resolved against jax.devices()",
    "num_threads": "native OpenMP host-path thread count",
    "predict_leaf_index": "predict per-tree leaf indices instead of scores",
    # IO / data
    "data": "training (or predict-input) data file",
    "valid_data": "comma list of validation data files",
    "max_bin": "max bins per feature",
    "data_random_seed": "binning-sample / shard-draw seed",
    "verbose": "log verbosity (-1 fatal .. 2 debug)",
    "has_header": "first data line is a header",
    "label_column": "label column selector",
    "weight_column": "weight column selector",
    "group_column": "query/group column selector",
    "ignore_column": "columns to drop",
    "is_pre_partition": "data files are pre-partitioned per machine",
    "is_enable_sparse": "reference sparse-format toggle (kept for parity)",
    "use_two_round_loading": "reference two-round loader (superseded by "
                             "streaming)",
    "is_save_binary_file": "write a binary dataset cache beside the data",
    "save_binary_format": "native or reference cache layout",
    "streaming": "auto/true/false chunked parse→bin→HBM loader",
    "ingest_chunk_rows": "streaming chunk length (host-resident row bound)",
    "ingest_workers": "byte-range parse worker processes (auto = cpu_count)",
    "output_model": "trained model output path",
    "input_model": "model to continue training from / predict with",
    "input_init_score": "initial-score side file",
    "output_result": "prediction output path",
    "num_model_predict": "how many trees predict uses (-1 = all)",
    "is_sigmoid": "apply sigmoid to binary predict output",
    # observability
    "profile_dir": "jax.profiler trace output directory",
    "metrics_out": "per-iteration JSONL telemetry sink path",
    "metrics_fence": "block_until_ready-fence phase spans",
    "memory_stats": "auto/true/false device-memory gauges",
    "timeline": "auto/true/false per-process JSONL shards",
    "stall_timeout": "hung-collective flight-recorder timeout (seconds)",
    "trace_ring_events": "flight-recorder event-ring slots (drops oldest)",
    "trace_dump_dir": "flight-recorder JSONL dump dir (close + fault)",
    "trace_sketch_growth": "latency-sketch log-bucket growth factor",
    "trace_run_id": "run tag in dump headers (podtrace merge key)",
    "monitor_out": "live-monitor windowed-snapshot JSONL path",
    "monitor_interval_s": "windowed-snapshot interval (seconds, > 0)",
    "slo_p99_us": "serve p99 latency objective (0 = SLO tracking off)",
    "slo_window_s": "SLO error-budget window (seconds, > 0)",
    # serving
    "predict_buckets": "compiled batch-shape ladder (comma ints)",
    "predict_quantize": "float32 or int8 leaf-value serving tables",
    "predict_donate": "auto/true/false codes-buffer donation",
    "predict_algo": "bfs lockstep walk or scan per-tree replay (A/B)",
    "serve_shards": "tree-axis ensemble shards (0 = single device)",
    "predict_linger_us": "coalescing front max linger (microseconds)",
    "predict_queue": "in-flight bound, in top-bucket batches",
    # tree growth
    "min_data_in_leaf": "min rows per leaf",
    "min_sum_hessian_in_leaf": "min hessian mass per leaf",
    "num_leaves": "max leaves per tree",
    "max_depth": "max tree depth (<0 = unlimited)",
    "feature_fraction": "per-tree feature subsample fraction",
    "feature_fraction_seed": "feature-fraction RNG seed",
    "histogram_pool_size": "reference LRU histogram pool (disabled "
                           "distributed)",
    "grow_policy": "leafwise best-first or depthwise level-batched",
    "hist_chunk": "XLA histogram scan row-chunk (0 = per-policy default)",
    "hist_dtype": "float32/bfloat16/int8 histogram operand dtype",
    "dp_schedule": "auto/psum/reduce_scatter DP reduction schedule",
    "leafwise_segments": "split the leafwise grow loop across N dispatches",
    "leafwise_compact": "auto/true/false contiguous-leaf growth",
    "mixed_bin": "auto/true/false per-bin-width-class histogram passes",
    "feature_shards": "2-D mesh feature-axis factor (0 = auto)",
    "top_k": "voting-parallel per-shard vote width",
    "quant_rounding": "nearest or stochastic int8 gradient rounding",
    # boosting loop
    "num_iterations": "boosting iteration budget",
    "learning_rate": "shrinkage rate",
    "bagging_fraction": "row subsample fraction",
    "bagging_freq": "iterations between bagging redraws (0 = off)",
    "bagging_seed": "bagging RNG seed",
    "bagging_device": "auto/true/false on-device bagging draws",
    "goss": "gradient-based one-side sampling",
    "top_rate": "GOSS top-gradient keep fraction",
    "other_rate": "GOSS remainder sample fraction",
    "early_stopping_round": "rounds without improvement before stop",
    "metric_freq": "iterations between metric output lines",
    "is_training_metric": "also evaluate metrics on the training set",
    "num_class": "number of classes (multiclass)",
    "sigmoid": "sigmoid steepness (binary objective/metric)",
    "is_unbalance": "unbalanced-label weighting (binary)",
    "label_gain": "per-label gain table (lambdarank)",
    "max_position": "NDCG truncation position (lambdarank)",
    "ndcg_eval_at": "NDCG eval positions",
    # health monitor
    "health": "auto/true/false training-health monitor",
    "on_anomaly": "warn/halt/record anomaly policy",
    "health_divergence_rounds": "consecutive worsening rounds that flag "
                                "divergence (0 = off)",
    # pipelining / checkpoints / elasticity
    "pipeline": "auto/off/readback deferred-readback boosting",
    "checkpoint_interval": "iterations between async checkpoints (0 = off)",
    "checkpoint_dir": "checkpoint directory (required when interval > 0)",
    "checkpoint_keep": "retained checkpoint files (>= 1)",
    "elastic_shrink": "live straggler mesh-shrink policy",
    "straggler_k": "consecutive strictly-slowest iterations that flag a "
                   "straggler",
    # distributed
    "tree_learner": "serial/feature/data/hybrid/voting",
    "num_machines": "machine (mesh-slot) count",
    "local_listen_port": "reference networking option (parity)",
    "time_out": "reference networking timeout (parity)",
    "machine_list_file": "reference machine list (parity; TPU bootstrap "
                         "uses env hatches)",
}

from . import config as config_mod
from . import telemetry, tracing
from .config import OverallConfig
from .io.dataset import Dataset
from .metrics import create_metric
from .models.gbdt import GBDT
from .models.predictor import Predictor
from .objectives import create_objective
from .utils import log


class Application:
    def __init__(self, argv: List[str]):
        self.config = config_mod.load_config(argv)
        # set number of threads for the native OpenMP host paths
        # (Application::Application, application.cpp:30-34)
        if self.config.num_threads > 0:
            from .native import lib as native_lib
            native_lib.set_num_threads(self.config.num_threads)
        io = self.config.io_config
        # memory gauges resolve "auto" → on whenever a sink is configured
        # (memory_stats=true arms them standalone, snapshot()-only)
        mem_on = io.memory_stats_enabled()
        if io.metrics_out or mem_on:
            telemetry.enable(io.metrics_out or None,
                             fence=io.metrics_fence, memory=mem_on,
                             # timeline="auto" resolves again after
                             # distributed init (init_train); a forced
                             # "true" arms shard mode immediately
                             timeline=(io.timeline == "true"))
            telemetry.reset()
            # flight recorder (ISSUE 16): always-on under the telemetry
            # session — bounded by the preallocated ring, disarmed (and
            # dumped, when trace_dump_dir is set) by telemetry.disable()
            tracing.set_identity(run_id=io.trace_run_id)
            tracing.arm(ring_events=io.trace_ring_events,
                        dump_dir=io.trace_dump_dir or None,
                        sketch_growth=io.trace_sketch_growth)
            log.debug("telemetry armed: metrics_out=%s fence=%s memory=%s "
                      "timeline=%s trace_ring=%d"
                      % (io.metrics_out, io.metrics_fence, mem_on,
                         io.timeline, io.trace_ring_events))
        if io.monitor_out or io.slo_p99_us > 0:
            # live monitor (ISSUE 20): windowed snapshots / SLO burn /
            # score drift, layered on the recorder armed above (an SLO
            # without a sink still tracks — breaches land in the trace
            # ring).  telemetry.disable() flushes and disarms it.
            if not tracing.active():
                tracing.set_identity(run_id=io.trace_run_id)
                tracing.arm(ring_events=io.trace_ring_events,
                            dump_dir=io.trace_dump_dir or None,
                            sketch_growth=io.trace_sketch_growth)
            from . import monitor
            monitor.arm(out_path=io.monitor_out,
                        interval_s=io.monitor_interval_s,
                        slo_p99_us=io.slo_p99_us,
                        slo_window_s=io.slo_window_s)
            log.debug("monitor armed: out=%s interval=%.3fs slo_p99_us=%g"
                      % (io.monitor_out, io.monitor_interval_s,
                         io.slo_p99_us))
        if io.stall_timeout > 0:
            # hung-collective flight recorder (ISSUE 5): gbdt.run_training
            # arms the watchdog thread around the training loop
            telemetry.configure_watchdog(io.stall_timeout)
        self.boosting: GBDT = None
        self.objective = None
        self.train_data = None
        self.valid_datas = []

    def run(self) -> None:
        if self.config.task_type == "train":
            self.init_train()
            self.train()
        else:
            self.init_predict()
            self.predict()

    # -------------------------------------------------------------- training

    def init_train(self) -> None:
        """Application::InitTrain (application.cpp:201-237)."""
        learner = None
        if self.config.is_parallel:
            from .parallel import create_parallel_learner, sync_up_by_min
            from .parallel.mesh import init_distributed
            init_distributed(self.config)
            # distributed determinism: sync seeds/fractions to global min
            # (application.cpp:207-214, 133-135)
            io, tree = self.config.io_config, self.config.boosting_config.tree_config
            io.data_random_seed = sync_up_by_min(io.data_random_seed)
            tree.feature_fraction_seed = sync_up_by_min(tree.feature_fraction_seed)
            tree.feature_fraction = sync_up_by_min(tree.feature_fraction)
            learner = create_parallel_learner(self.config)
            # timeline="auto" resolves HERE, after distributed init, when
            # process_count is final: multi-process runs get per-process
            # shards (the clock handshake ran inside init_distributed)
            if self.config.io_config.timeline_enabled():
                telemetry.set_timeline(True)
            # pod identity is final here too: trace dumps from every
            # process must carry matching (index, count) or podtrace's
            # merge refuses the set
            try:
                import jax as _jax
                tracing.set_identity(process_index=_jax.process_index(),
                                     process_count=_jax.process_count())
            except Exception:
                pass

        self.boosting = GBDT()
        predict_fun = None
        if self.config.io_config.input_model:
            cont_model = GBDT.from_model_file(self.config.io_config.input_model)
            predict_fun = lambda feats: cont_model.predict_raw(feats)
            self.boosting.models = cont_model.models

        self.objective = create_objective(self.config.objective_type,
                                          self.config.objective_config)
        self.load_data(predict_fun)
        self.boosting.init(self.config.boosting_config, self.train_data,
                           self.objective, self.train_metrics, learner=learner)
        for valid_data, metrics, name in self.valid_datas:
            self.boosting.add_valid_dataset(valid_data, metrics, name=name)

        # preemption-safe restart (ISSUE 14): a checkpoint_dir holding a
        # finished checkpoint resumes training from it — bit-identically
        # on the same topology; on a different num_machines the learner's
        # mesh was already re-factored above (factor_machines over the
        # surviving machine count) and the binary cache re-sharded
        # through the streaming loader, so the restore replays onto the
        # new layout (the documented elastic continuation budgets).
        bc = self.config.boosting_config
        if bc.checkpoint_dir:
            from . import checkpoint as ckpt_mod
            latest = ckpt_mod.latest_checkpoint(bc.checkpoint_dir)
            if latest is not None:
                log.info("resuming from checkpoint %s" % latest)
                self.boosting.restore_checkpoint(latest)
        if bc.elastic_shrink and self.config.is_parallel:
            # live straggler mesh-shrink (ISSUE 14): the factory re-runs
            # factor_machines through create_parallel_learner on the
            # surviving machine count; an explicit feature_shards that no
            # longer divides falls back to auto-factoring (with a note)
            # instead of a mid-run fatal
            from .parallel import create_parallel_learner as _factory_cpl
            cfg = self.config

            def _shrunk_learner(num_machines, _cfg=cfg):
                _cfg.network_config.num_machines = int(num_machines)
                fs = _cfg.boosting_config.tree_config.feature_shards
                if fs and int(num_machines) % fs:
                    log.warning(
                        "elastic shrink: feature_shards=%d does not "
                        "divide the surviving %d machines; re-factoring "
                        "automatically" % (fs, num_machines))
                    _cfg.boosting_config.tree_config.feature_shards = 0
                return _factory_cpl(_cfg)

            self.boosting.enable_elastic(_shrunk_learner)

    def load_data(self, predict_fun=None) -> None:
        """Application::LoadData (application.cpp:119-199)."""
        # perf_counter, not time.time(): wall clock is not monotonic (NTP
        # steps would corrupt the duration); message text keeps reference
        # parity
        start = time.perf_counter()
        rank = 0
        shard_count = 1
        bin_finder = None
        if self.config.is_parallel and self.config.is_parallel_find_bin:
            # Row shards are PER PROCESS: one process hosts every row its
            # mesh devices train on (the data-parallel learner shards them
            # on-device), so the reference's per-machine partition
            # (dataset.cpp:172-216) maps to the process grid — a
            # single-process run over N devices loads ALL rows.  Feature
            # parallel loads full rows everywhere, exactly like the
            # reference (is_parallel_find_bin=false for FP,
            # io/config.cpp:164-172).
            import jax as _jax
            from .parallel import get_rank, distributed_bin_finder
            rank = get_rank()
            shard_count = _jax.process_count()
            bin_finder = distributed_bin_finder(self.config)
        # single-process parallel consumers take the streamed bin matrix
        # committed on the LEARNER's device mesh (explicit NamedSharding
        # placement; parallel.mesh.dataset_row_sharding): row-sharded
        # over the (data,) axis for tree_learner=data when the row count
        # divides the mesh, replicated on that mesh otherwise (a
        # multi-device shard_map rejects a one-device commit) — resident
        # loads and serial training are unaffected
        single_proc_parallel = (self.config.is_parallel
                                and shard_count == 1)
        shard_rows = (single_proc_parallel
                      and self.config.boosting_config.tree_learner
                      == "data")
        self.train_data = Dataset.load_train(
            self.config.io_config, rank=rank, num_machines=shard_count,
            predict_fun=predict_fun, bin_finder=bin_finder,
            shard_rows=shard_rows,
            shard_devices=(self.config.network_config.num_machines
                           if single_proc_parallel else None),
            device_type=self.config.device_type)

        self.train_metrics = []
        if self.config.boosting_config.is_provide_training_metric:
            for metric_type in self.config.metric_types:
                metric = create_metric(metric_type, self.config.metric_config)
                if metric is not None:
                    self.train_metrics.append(metric)

        self.valid_datas = []
        for filename in self.config.io_config.valid_data_filenames:
            valid = Dataset.load_valid(self.train_data, filename,
                                       predict_fun=predict_fun,
                                       io_config=self.config.io_config)
            metrics = []
            for metric_type in self.config.metric_types:
                metric = create_metric(metric_type, self.config.metric_config)
                if metric is not None:
                    metrics.append(metric)
            self.valid_datas.append((valid, metrics, filename))
        log.info("Finish loading data, use %f seconds"
                 % (time.perf_counter() - start))

    def train(self) -> None:
        """Application::Train (application.cpp:239-257).

        ``profile_dir=<dir>`` (SURVEY §5.1) wraps the loop in a
        jax.profiler trace — the device-level phase breakdown the
        reference's wall-clock logs cannot give."""
        log.info("Start train ...")
        is_eval = bool(self.train_metrics) or any(
            m for _, m, _ in self.valid_datas)
        start = time.perf_counter()
        # a checkpoint restore already banked boosting.iter iterations;
        # num_iterations is the TOTAL budget of the run, so train only
        # the remainder (a restart after a clean finish trains nothing
        # and just rewrites the final model file)
        remaining = max(
            self.config.boosting_config.num_iterations - self.boosting.iter,
            0)
        if remaining < self.config.boosting_config.num_iterations:
            log.info("checkpoint restore banked %d iteration(s); training "
                     "%d more" % (self.boosting.iter, remaining))

        def _run():
            self.boosting.run_training(
                remaining, is_eval,
                save_fn=lambda: self.boosting.save_model_to_file(
                    False, self.config.io_config.output_model),
                progress_fn=lambda it: log.info(
                    "%f seconds elapsed, finished %d iteration"
                    % (time.perf_counter() - start, it)))

        if self.config.io_config.profile_dir:
            import jax
            with jax.profiler.trace(self.config.io_config.profile_dir):
                _run()
            log.info("Profiler trace written to %s"
                     % self.config.io_config.profile_dir)
        else:
            _run()
        self.boosting.save_model_to_file(
            True, self.config.io_config.output_model)
        log.info("Finished train")

    # ------------------------------------------------------------ prediction

    def init_predict(self) -> None:
        """Application::InitPredict (application.cpp:269-273)."""
        if not self.config.io_config.input_model:
            log.fatal("Please provide a model file for prediction")
        self.boosting = GBDT.from_model_file(self.config.io_config.input_model)

    def predict(self) -> None:
        from .serving import engine_options_from_config
        predictor = Predictor(self.boosting, self.config.io_config.is_sigmoid,
                              self.config.predict_leaf_index,
                              self.config.io_config.num_model_predict,
                              serving_options=engine_options_from_config(
                                  self.config.io_config))
        predictor.predict_file(self.config.io_config.data_filename,
                               self.config.io_config.output_result,
                               self.config.io_config.has_header)
        if telemetry.enabled():
            # the predict task has no training loop to write the final
            # totals record: emit it here so metrics_out= predict runs
            # carry the serve/* family (and the predict-phase roofline)
            # into the sink telemetry_report.py renders
            telemetry.emit_summary()
        log.info("Finished prediction")


def main(argv: List[str] = None) -> int:
    """src/main.cpp equivalent."""
    argv = argv if argv is not None else sys.argv[1:]
    try:
        app = Application(argv)
        app.run()
    except log.LightGBMError:
        return 1
    finally:
        # close the metrics sink armed in Application.__init__ (flushes
        # pending records; harmless no-op when telemetry was never on)
        telemetry.disable()
    return 0


if __name__ == "__main__":
    sys.exit(main())
