"""Compiled serving engine: flattened ensembles, bucketed batch shapes,
int8 scoring.

The production predict front (ROADMAP item 1; reference semantics:
``src/application/predictor.hpp:109-197``).  The training-side scorer
replays trees one at a time (``ops/scoring.ensemble_scores``: a lax.scan
with num_leaves-1 sequential masked steps per tree) and the old
``GBDT._device_predict_encode`` re-flattened the WHOLE ensemble on host on
every call.  This module splits serving into the two halves a steady-state
server actually has:

1. **FlatEnsemble** — built ONCE per trained model: the per-node tensors
   stacked ``[T, max_nodes]`` (split_feature, threshold_rank, left/right
   child), the ``[T, max_leaves]`` leaf-value table, and the host-built
   f64 per-feature threshold rank tables that make integer routing EXACT
   (no f32 threshold-comparison rounding — same encoding contract as
   ``_device_predict_encode``).  ``FlatEnsemble.encode(features)`` is the
   only per-batch host work: one ``np.searchsorted`` per used feature.

2. **ServingEngine** — owns the compiled programs.  Batches are padded to
   a fixed bucket ladder (default 1 / 32 / 1024 / 65536 rows) so
   steady-state serving sees a CLOSED set of program shapes and never
   recompiles; the codes buffer is donated (non-CPU backends) so the pad
   buffer is recycled in place.  Scoring walks all trees breadth-first in
   lockstep (``ops/scoring.bfs_scores_impl``): one gather-based level
   step per depth over the whole [T, N] frontier — O(max_depth) fused
   steps instead of the training scorer's O(T·L) — and accumulates leaf
   values in tree order, so scores are BIT-EQUAL to the training-side
   scorer.  ``quantize="int8"`` swaps the leaf table for int8 + per-tree
   scale (quarter table traffic, single-pass bf16 one-hot read; routing
   stays exact — only leaf VALUES are quantized).

Distributed, elastic serving (ISSUE 13) adds three axes on top:

3. **Tree-axis sharding** (``shards=`` / the ``serve_shards`` knob): the
   [T, ...] node tensors shard CONTIGUOUSLY along a 1-D ``("tree",)``
   mesh (``parallel.mesh.get_serving_mesh``) — each device's HBM holds
   only its tree block, lifting the 10k+-tree / multi-GB-ensemble
   regime a single HBM cannot hold.  The BFS walk is embarrassingly
   parallel in T; the per-shard [C, N] partials are accumulated in
   canonical tree order and carried shard-to-shard (ppermute chain)
   with ONE masked psum at the end (``serve/tree_psum``), so sharded
   scores stay BIT-EQUAL to the single-device engine, f32 and int8
   (ops/scoring.py "tree-axis sharding" block comment has the proof
   sketch).

4. **Cross-request batching** (``ServingFront``): a coalescing queue in
   front of the engine — incoming requests pack onto the SAME bucket
   ladder under a max-linger deadline (``predict_linger_us``), scores
   scatter back per request (rows are independent through the walk, so
   coalescing never changes a result bit).  The queue is BOUNDED
   (``predict_queue`` top-bucket batches); when full, ``submit``
   blocks — backpressure, never load shedding, which is what makes the
   zero-drop contract testable.

5. **Hot swap** (``ServingFront.swap_engine``): double-buffered engine
   replacement — the NEW engine warms its bucket programs while the old
   one serves (``ServingEngine.warmup``), then a swap marker rides the
   request queue and the worker flips atomically when it drains to it.
   Requests enqueued before the swap score on the old engine, after it
   on the new one; none are dropped or torn across engines.

Programs are costmodel-instrumented under phase "predict" (roofline
attribution + compile observability ride along whenever telemetry is
armed), and the engine files ``serve/*`` counters.
"""
from __future__ import annotations

import collections
import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import costmodel, lifecycle, monitor, telemetry, tracing

DEFAULT_BUCKETS: Tuple[int, ...] = (1, 32, 1024, 65536)

# module-level flatten counter: the regression test for the encode-once
# contract (predict_file must flatten the ensemble exactly once across
# its 500k-row chunks) reads the delta — independent of whether
# telemetry is armed
FLATTEN_COUNT = 0


def _tree_max_depth(lc: np.ndarray, rc: np.ndarray, n: int) -> int:
    """Depth (in edges from the root) a BFS walk needs to resolve every
    row of this tree.  Children are always created AFTER their parent
    (node k's children have indices > k, tree.cpp:70-71), so one forward
    pass suffices."""
    if n <= 0:
        return 0
    depth = np.ones(n, np.int32)
    for k in range(n):
        for c in (int(lc[k]), int(rc[k])):
            if c >= 0:
                depth[c] = depth[k] + 1
    return int(depth.max())


class FlatEnsemble:
    """A trained ensemble flattened once into dense per-node tensors plus
    the host-built f64 rank-code tables (see module docstring)."""

    def __init__(self, used, thresholds, sf, tr, lc, rc, lv, nl, root,
                 tree_class, max_nodes: int, max_depth: int,
                 num_class: int):
        self.used = used                 # original column ids, sorted
        self.thresholds = thresholds     # {col: sorted unique f64 thresholds}
        self.split_feature = sf          # [T, max_nodes] int32 (inner ids)
        self.threshold_rank = tr         # [T, max_nodes] int32
        self.left_child = lc             # [T, max_nodes] int32 (~leaf enc)
        self.right_child = rc            # [T, max_nodes] int32
        self.leaf_value = lv             # [T, max_nodes + 1] f32
        self.num_leaves = nl             # [T] int32
        self.root_state = root           # [T] int32: 0, or ~0 for stumps
        self.tree_class = tree_class     # [T] int32
        self.max_nodes = max_nodes
        self.max_depth = max_depth
        self.num_class = num_class
        self.num_trees = sf.shape[0]
        self._int8: Optional[Tuple[np.ndarray, np.ndarray]] = None

    @classmethod
    def from_models(cls, models, num_class: int) -> "FlatEnsemble":
        """Flatten ``models`` (list of models.tree.Tree).  This is the
        once-per-model cost the old per-call ``_device_predict_encode``
        paid on EVERY predict call."""
        global FLATTEN_COUNT
        FLATTEN_COUNT += 1
        telemetry.count("serve/ensemble_flatten")
        T = len(models)
        max_nodes = max(max((t.num_leaves - 1 for t in models), default=1),
                        1)
        used = sorted({int(f) for t in models
                       for f in t.split_feature_real[:t.num_leaves - 1]})
        fmap = {f: i for i, f in enumerate(used)}
        thr = {f: [] for f in used}
        for t in models:
            for f, v in zip(t.split_feature_real, t.threshold):
                thr[int(f)].append(float(v))
        thr = {f: np.unique(np.asarray(v, np.float64))
               for f, v in thr.items()}

        sf = np.zeros((T, max_nodes), np.int32)
        tr = np.zeros((T, max_nodes), np.int32)
        lc = np.zeros((T, max_nodes), np.int32)
        rc = np.zeros((T, max_nodes), np.int32)
        lv = np.zeros((T, max_nodes + 1), np.float32)
        nl = np.zeros((T,), np.int32)
        root = np.zeros((T,), np.int32)
        max_depth = 0
        for k, t in enumerate(models):
            n = t.num_leaves - 1
            nl[k] = t.num_leaves
            lv[k, :t.num_leaves] = t.leaf_value
            if n <= 0:
                root[k] = -1      # ~0: the stump's single leaf
                continue
            sf[k, :n] = [fmap[int(f)] for f in t.split_feature_real[:n]]
            tr[k, :n] = [int(np.searchsorted(thr[int(f)], float(v), "left"))
                         for f, v in zip(t.split_feature_real[:n],
                                         t.threshold[:n])]
            lc[k, :n] = t.left_child[:n]
            rc[k, :n] = t.right_child[:n]
            max_depth = max(max_depth,
                            _tree_max_depth(lc[k], rc[k], n))
        tc = (np.arange(T) % max(num_class, 1)).astype(np.int32)
        return cls(used, thr, sf, tr, lc, rc, lv, nl, root, tc,
                   max_nodes, max_depth, max(num_class, 1))

    def encode(self, features: np.ndarray) -> np.ndarray:
        """Rank-encode raw feature values against the ensemble's own
        threshold tables, in float64 on host — the integer walk on device
        then routes rows EXACTLY like the reference's double comparisons
        (tree.h:163-175).  [F_used, N] int32; the only per-batch host
        work."""
        N = features.shape[0]
        codes = np.zeros((max(len(self.used), 1), N), np.int32)
        for i, f in enumerate(self.used):
            # code = #{thresholds < x}; x > t_j  <=>  code > j, and an
            # exact tie x == t_j gives code == j -> left (`value > t`)
            vals = features[:, f]
            c = np.searchsorted(self.thresholds[f], vals, side="left")
            # NaN sorts past every threshold; the host walk's `value > t`
            # is False for NaN -> always left.  Match it.
            c[np.isnan(vals)] = 0
            codes[i] = c
        return codes

    def int8_tables(self) -> Tuple[np.ndarray, np.ndarray]:
        """(leaf_q [T, max_leaves] int8, scale [T] f32), built lazily and
        cached.  Symmetric per-tree quantization: scale = max|leaf|/127,
        q = round(leaf/scale) — a leaf reads back as ``q * scale``."""
        if self._int8 is None:
            amax = np.abs(self.leaf_value).max(axis=1)
            scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
            q = np.clip(np.round(self.leaf_value / scale[:, None]),
                        -127, 127).astype(np.int8)
            self._int8 = (q, scale)
        return self._int8

    def dequantized_leaf_value(self) -> np.ndarray:
        """[T, max_leaves] f32 leaf table of the int8 ensemble — the host
        reference the int8 engine must score bit-equal against."""
        q, scale = self.int8_tables()
        return q.astype(np.float32) * scale[:, None]


class ServingEngine:
    """Compiled, batched prediction over one FlatEnsemble (see module
    docstring).  Thread-compat with the repo's other device paths: one
    engine per model, calls are serialized by the caller."""

    def __init__(self, flat: FlatEnsemble,
                 buckets: Sequence[int] = DEFAULT_BUCKETS,
                 quantize: str = "float32", donate: str = "auto",
                 algo: str = "bfs", shards: int = 0, linger_us: int = 200,
                 queue: int = 4, device_type: str = ""):
        if quantize not in ("float32", "int8"):
            raise ValueError("quantize must be float32 or int8")
        if algo not in ("bfs", "scan"):
            raise ValueError("algo must be bfs or scan")
        buckets = tuple(sorted({int(b) for b in buckets}))
        if not buckets or buckets[0] < 1:
            raise ValueError("buckets must be positive ints")
        shards = int(shards)
        if shards < 0:
            raise ValueError("shards must be >= 0 (0 = single-device)")
        if int(linger_us) < 0:
            raise ValueError("linger_us must be >= 0")
        if int(queue) < 1:
            raise ValueError("queue must be >= 1 (in-flight batches)")
        self.flat = flat
        self.buckets = buckets
        self.quantize = quantize
        self.algo = algo
        self.donate = self._resolve_donate(donate)
        # tree-axis sharding (ISSUE 13): 0/1 = the single-device engine,
        # >1 = contiguous tree blocks over a ("tree",) mesh.  The mesh is
        # built EAGERLY so an over-subscribed shard count fails at engine
        # construction, not at the first request.
        self.shards = shards if shards > 1 else 1
        self.device_type = device_type
        self._mesh = None
        if self.shards > 1:
            if algo == "scan":
                raise ValueError(
                    "predict_algo=scan cannot tree-shard (the per-tree "
                    "replay is a single-device A/B path); use bfs")
            from .parallel.mesh import get_serving_mesh
            self._mesh = get_serving_mesh(self.shards, device_type)
        # ServingFront defaults (axis b): carried on the engine so
        # engine_options_from_config stays the single IOConfig mapping
        self.linger_us = int(linger_us)
        self.queue = int(queue)
        self._tables = None            # device-resident node tensors
        self._programs: Dict[tuple, object] = {}

    @staticmethod
    def _resolve_donate(donate: str) -> bool:
        if donate not in ("auto", "true", "false"):
            raise ValueError("donate must be auto, true or false")
        if donate == "auto":
            # CPU ignores donation with a warning per call site — auto
            # keeps serving logs clean there; accelerators donate
            try:
                import jax
                return jax.default_backend() != "cpu"
            except Exception:
                return False
        return donate == "true"

    # ------------------------------------------------------------ programs

    def _device_tables(self):
        """Push the flattened tensors to device ONCE (cached jnp arrays;
        re-used by every bucketed call — steady-state serving transfers
        only the codes buffer).  Under ``shards > 1`` the [T, ...]
        tables are padded to a shard multiple with inert stump trees
        (root ~0, zero leaves — additionally MASKED out of the
        accumulate by the static true tree count) and committed with a
        tree-axis NamedSharding, so each mesh device holds ONLY its
        contiguous tree block."""
        if self._tables is None:
            import jax.numpy as jnp
            f = self.flat
            if self.shards > 1:
                import jax
                from jax.sharding import NamedSharding, PartitionSpec
                from .parallel.mesh import TREE_AXIS
                pad = (-f.num_trees) % self.shards

                def put(arr, fill=0):
                    arr = np.asarray(arr)
                    if pad:
                        widths = [(0, pad)] + [(0, 0)] * (arr.ndim - 1)
                        arr = np.pad(arr, widths, constant_values=fill)
                    spec = PartitionSpec(TREE_AXIS,
                                         *([None] * (arr.ndim - 1)))
                    return jax.device_put(
                        arr, NamedSharding(self._mesh, spec))

                t = {
                    "sf": put(f.split_feature),
                    "tr": put(f.threshold_rank),
                    "lc": put(f.left_child),
                    "rc": put(f.right_child),
                    "root": put(f.root_state, fill=-1),
                    "tc": put(f.tree_class),
                    "nl": put(f.num_leaves, fill=1),
                }
                if self.quantize == "int8":
                    q, scale = f.int8_tables()
                    t["lv_q"] = put(q)
                    t["lv_scale"] = put(scale, fill=1)
                else:
                    t["lv"] = put(f.leaf_value)
                self._tables = t
                return self._tables
            t = {
                "sf": jnp.asarray(f.split_feature),
                "tr": jnp.asarray(f.threshold_rank),
                "lc": jnp.asarray(f.left_child),
                "rc": jnp.asarray(f.right_child),
                "root": jnp.asarray(f.root_state),
                "tc": jnp.asarray(f.tree_class),
                "nl": jnp.asarray(f.num_leaves),
            }
            if self.quantize == "int8":
                q, scale = f.int8_tables()
                t["lv_q"] = jnp.asarray(q)
                t["lv_scale"] = jnp.asarray(scale)
                # the scan A/B path reads a plain f32 table: give it the
                # DEQUANTIZED one so algo=scan scores the same quantized
                # model bit-for-bit (never silently full precision)
                t["lv"] = jnp.asarray(f.dequantized_leaf_value())
            else:
                t["lv"] = jnp.asarray(f.leaf_value)
            self._tables = t
        return self._tables

    def _program(self, kind: str):
        """One costmodel-instrumented jit per kind ("scores"/"leaves");
        bucket shapes are signatures of the SAME program object, so the
        compiled-program inventory stays a closed set (the no-recompile
        assertion tests/test_serving.py pins via the compile counters).

        The cache key carries the resolved backend + device_type + shard
        count beside the kind (the graftlint R2 rule class): a
        mid-process backend flip — or two engines at different shard
        counts sharing a future program registry — must never reuse a
        program compiled for the other routing."""
        import jax
        key = (kind, jax.default_backend(), self.device_type, self.shards)
        prog = self._programs.get(key)
        if prog is None:
            from .ops import scoring
            tag = "_int8" if (self.quantize == "int8"
                              and kind == "scores") else ""
            if self.shards > 1:
                fn = self._sharded_mapped(kind, scoring)
                prog = costmodel.instrument(
                    f"serve/bfs_{kind}{tag}_sharded", fn, phase="predict")
                self._programs[key] = prog
                return prog
            donate = (0,) if self.donate else ()
            if kind == "scores":
                impl = (scoring.bfs_scores_int8_impl
                        if self.quantize == "int8"
                        else scoring.bfs_scores_impl)
                fn = jax.jit(impl,
                             static_argnames=("max_depth", "num_class"),
                             donate_argnums=donate)
            else:
                fn = jax.jit(scoring.bfs_leaf_indices_impl,
                             static_argnames=("max_depth",),
                             donate_argnums=donate)
            prog = costmodel.instrument(f"serve/bfs_{kind}{tag}", fn,
                                        phase="predict")
            self._programs[key] = prog
        return prog

    def _sharded_mapped(self, kind: str, scoring):
        """The tree-sharded program body: the sharded impl with its
        statics bound, shard_mapped over the ("tree",) mesh — codes
        replicated, node tables tree-sharded, scores replicated out
        (the in-program carry chain + masked psum already leave every
        shard holding the full [C, N] result).  Donation is skipped:
        the codes buffer is replicated over the mesh, so there is no
        per-device buffer to recycle in place."""
        import functools

        import jax
        from jax.sharding import PartitionSpec as P

        from .parallel.learners import shard_map
        from .parallel.mesh import TREE_AXIS
        f = self.flat
        t2 = P(TREE_AXIS, None)
        t1 = P(TREE_AXIS)
        if kind == "scores":
            if self.quantize == "int8":
                impl = functools.partial(
                    scoring.bfs_scores_sharded_int8_impl,
                    max_depth=f.max_depth, num_class=f.num_class,
                    num_trees=f.num_trees, shards=self.shards,
                    axis_name=TREE_AXIS)
                in_specs = (P(), t2, t2, t2, t2, t2, t1, t1, t1)
            else:
                impl = functools.partial(
                    scoring.bfs_scores_sharded_impl,
                    max_depth=f.max_depth, num_class=f.num_class,
                    num_trees=f.num_trees, shards=self.shards,
                    axis_name=TREE_AXIS)
                in_specs = (P(), t2, t2, t2, t2, t2, t1, t1)
            out_specs = P()
        else:
            impl = functools.partial(scoring.bfs_leaf_indices_impl,
                                     max_depth=f.max_depth)
            in_specs = (P(), t2, t2, t2, t2, t1)
            # leaf ids need no exchange at all: the per-shard [Tb, N]
            # blocks reassemble along the tree axis in the output spec
            out_specs = t2
        return jax.jit(shard_map(impl, mesh=self._mesh,
                                 in_specs=in_specs, out_specs=out_specs))

    def _run_scores(self, codes_chunk):
        import jax.numpy as jnp
        t = self._device_tables()
        f = self.flat
        if self.algo == "scan":
            # legacy per-tree replay (the training-side scorer) at the
            # engine's bucket shapes — the A/B reference bench_predict
            # prices the breadth-first walk against.  t["lv"] is the
            # device-cached f32 table (dequantized under quantize=int8),
            # so the A/B pays no per-call upload and never silently
            # serves full precision for an int8 engine.
            from .ops.scoring import ensemble_scores
            return ensemble_scores(
                jnp.asarray(codes_chunk), t["sf"], t["tr"], t["lc"],
                t["rc"], t["lv"], t["nl"], t["tc"],
                max_nodes=f.max_nodes, num_class=f.num_class)
        prog = self._program("scores")
        if self.shards > 1:
            # statics are partial-bound inside the shard_mapped program
            if self.quantize == "int8":
                return prog(jnp.asarray(codes_chunk), t["sf"], t["tr"],
                            t["lc"], t["rc"], t["lv_q"], t["lv_scale"],
                            t["root"], t["tc"])
            return prog(jnp.asarray(codes_chunk), t["sf"], t["tr"],
                        t["lc"], t["rc"], t["lv"], t["root"], t["tc"])
        if self.quantize == "int8":
            return prog(jnp.asarray(codes_chunk), t["sf"], t["tr"],
                        t["lc"], t["rc"], t["lv_q"], t["lv_scale"],
                        t["root"], t["tc"], max_depth=f.max_depth,
                        num_class=f.num_class)
        return prog(jnp.asarray(codes_chunk), t["sf"], t["tr"], t["lc"],
                    t["rc"], t["lv"], t["root"], t["tc"],
                    max_depth=f.max_depth, num_class=f.num_class)

    def _run_leaves(self, codes_chunk):
        import jax.numpy as jnp
        t = self._device_tables()
        f = self.flat
        if self.algo == "scan":
            from .ops.scoring import ensemble_leaf_indices
            return ensemble_leaf_indices(
                jnp.asarray(codes_chunk), t["sf"], t["tr"], t["lc"],
                t["rc"], t["nl"], max_nodes=f.max_nodes)
        if self.shards > 1:
            return self._program("leaves")(
                jnp.asarray(codes_chunk), t["sf"], t["tr"], t["lc"],
                t["rc"], t["root"])
        return self._program("leaves")(
            jnp.asarray(codes_chunk), t["sf"], t["tr"], t["lc"], t["rc"],
            t["root"], max_depth=f.max_depth)

    # ------------------------------------------------------------- serving

    def bucket_for(self, n: int) -> int:
        """Smallest bucket that holds ``n`` rows (callers chunk at the
        largest bucket first, so n <= buckets[-1] here)."""
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    def _bucketed(self, features: np.ndarray, run, assemble):
        """encode → chunk at the largest bucket → pad-to-bucket → run →
        strip padding.  ``run`` maps a padded [F, B] codes chunk to a
        device result; ``assemble`` concatenates the per-chunk np arrays
        along the row axis."""
        with telemetry.span("predict_encode"):
            codes = self.flat.encode(features)
        N = codes.shape[1]
        maxb = self.buckets[-1]
        outs = []
        telemetry.count("serve/predict_calls")
        telemetry.count("serve/rows", N)
        # per-request attribution (ISSUE 16): when a ServingFront batch
        # is being scored on this thread, fill in its dispatch/walk
        # boundary marks + pad/bucket detail; direct engine calls see
        # None and pay nothing
        bt = tracing.current_batch()
        with telemetry.span("predict") as sp:
            for s in range(0, max(N, 1), maxb):
                chunk = codes[:, s:s + maxb]
                n = chunk.shape[1]
                b = self.bucket_for(n)
                if b > n:
                    telemetry.count("serve/pad_rows", b - n)
                    if bt is not None:
                        bt.add_pad(b - n)
                    chunk = np.concatenate(
                        [chunk, np.zeros((chunk.shape[0], b - n),
                                         chunk.dtype)], axis=1)
                telemetry.count(f"serve/bucket_{b}")
                if bt is not None:
                    bt.set_bucket(b)
                    bt.mark_run_begin()
                out = run(chunk)
                if bt is not None:
                    bt.mark_dispatched()
                # fence like every device-work span (PR 4): unfenced
                # async spans time the dispatch, not the walk, and the
                # predict-phase roofline would be meaningless
                outs.append((sp.fence(out), n))
                if bt is not None:
                    bt.mark_run_end()
        return assemble(outs)

    def scores(self, features: np.ndarray) -> np.ndarray:
        """[num_class, N] raw ensemble score sums (float64 on host, f32
        accumulation on device — identical to the training-side scorer's
        accumulation order)."""
        if self.flat.num_trees == 0:
            return np.zeros((self.flat.num_class, features.shape[0]))
        return self._bucketed(
            features, self._run_scores,
            lambda outs: np.concatenate(
                [np.asarray(o, np.float64)[:, :n] for o, n in outs],
                axis=1))

    def leaf_indices(self, features: np.ndarray) -> np.ndarray:
        """[N, T] leaf index per tree (PredictLeafIndex layout).  The
        row slice strips the inert pad trees a sharded engine appends to
        reach a shard multiple (a no-op single-device, where the device
        result has exactly num_trees rows)."""
        if self.flat.num_trees == 0:
            return np.zeros((features.shape[0], 0), np.int32)
        T = self.flat.num_trees
        return self._bucketed(
            features, self._run_leaves,
            lambda outs: np.concatenate(
                [np.asarray(o, np.int32)[:T, :n].T for o, n in outs],
                axis=0))

    def warmup(self, buckets: Optional[Sequence[int]] = None):
        """Compile the scores program at every bucket shape ahead of
        serving — the hot-swap double-buffer step: the NEW engine warms
        while the OLD one keeps serving, so the drain-and-flip never
        pays a compile inside the request path (and ``bench_serve``'s
        ``serve_recompiles=0`` stays true across a swap).  Returns self
        so ``ServingFront.swap_engine(engine.warmup())`` chains."""
        if self.flat.num_trees == 0:
            return self
        F = max(len(self.flat.used), 1)
        with telemetry.span("predict_warmup"):
            for b in (buckets if buckets is not None else self.buckets):
                codes = np.zeros((F, int(b)), np.int32)
                np.asarray(self._run_scores(codes))
        telemetry.count("serve/warmups")
        return self


class _FrontRequest:
    __slots__ = ("features", "future", "rows", "t_submit", "trace_id",
                 "t_enq_ns", "block_ns")

    def __init__(self, features, future, rows, t_submit, trace_id=0,
                 t_enq_ns=0, block_ns=0):
        self.features = features
        self.future = future
        self.rows = rows
        self.t_submit = t_submit
        # flight-recorder identity + integer enqueue stamp (ISSUE 16):
        # the attribution identity needs perf_counter_ns boundaries —
        # float-second chains do not telescope exactly
        self.trace_id = trace_id
        self.t_enq_ns = t_enq_ns
        self.block_ns = block_ns


class _SwapMarker:
    __slots__ = ("engine", "event", "t0")

    def __init__(self, engine, event, t0):
        self.engine = engine
        self.event = event
        self.t0 = t0


def _drift_identity(engine) -> tuple:
    """(drift key, training-time reference histogram) for one installed
    engine — the reference is the parsed ``score_reference=`` metadata
    block, carried on the engine or its FlatEnsemble (None when the
    model predates capture; the A/A lane still runs without it)."""
    ref = getattr(engine, "score_reference", None)
    if ref is None:
        ref = getattr(getattr(engine, "flat", None),
                      "score_reference", None)
    return monitor.engine_key(), ref


class ServingFront:
    """Cross-request coalescing front over a ServingEngine (ISSUE 13
    axes b + c — see the module docstring).

    One worker thread drains a bounded request queue: it waits up to
    ``linger_us`` past the FIRST queued request's arrival (or until a
    top-bucket batch is available), concatenates whole requests onto one
    batch, runs ``engine.scores`` once, and scatters the score columns
    back to each request's Future.  Rows are independent through the
    BFS walk and the per-class accumulation, so a coalesced request's
    scores are bit-identical to scoring it alone.

    The queue is bounded at ``queue`` top-bucket batches of rows:
    ``submit`` BLOCKS when full (backpressure) — the front never sheds
    load, which is what makes the zero-drop hot-swap contract testable.

    ``swap_engine(new_engine)`` is the drain-and-flip atomic hot swap:
    the marker rides the queue, requests ahead of it score on the old
    engine, requests behind it (and everything submitted after the call
    returns) on the new one — no request is dropped or torn across
    engines.  Pass an already-``warmup()``-ed engine (the default warms
    it for you) so the flip never pays a compile in the request path.

    Telemetry (``serve/front_*`` / ``serve/coalesced_*`` /
    ``serve/linger_wait_us`` / ``serve/queue_depth_*`` /
    ``serve/swaps`` / ``serve/swap_drain_us``) files alongside the
    engine's own counters; ``stats`` carries the host-side mirror."""

    def __init__(self, engine: ServingEngine,
                 linger_us: Optional[int] = None,
                 queue: Optional[int] = None):
        self._engine = engine
        self.linger_s = (engine.linger_us if linger_us is None
                         else int(linger_us)) / 1e6
        batches = engine.queue if queue is None else int(queue)
        if batches < 1:
            raise ValueError("queue must be >= 1 (in-flight batches)")
        self.queue_rows = batches * engine.buckets[-1]
        self._cond = threading.Condition()
        self._queue: "collections.deque" = collections.deque()
        self._queued_rows = 0
        self._closed = False
        self.stats = {"requests": 0, "rows": 0, "batches": 0,
                      "coalesced_rows": 0, "queue_peak_rows": 0,
                      "linger_wait_s": 0.0, "swaps": 0,
                      "last_swap_drain_s": None}
        # score-drift feed (ISSUE 20): each installed engine gets a
        # fresh drift key so a swapped-in candidate starts a clean live
        # histogram; the training-time reference (model-file
        # ``score_reference=`` metadata, carried on the FlatEnsemble)
        # rides along to monitor.record_scores
        self._monitor_key, self._monitor_ref = _drift_identity(engine)
        self._thread = threading.Thread(target=self._serve_loop,
                                        name="lgbm-serving-front",
                                        daemon=True)
        # shared live-object inventory (ISSUE 15): a test that leaks the
        # front's worker thread used to be invisible to the conftest
        # guard — the registry makes the guard and graftlint C1 read one
        # list
        lifecycle.track("serving-front", self, self.close)
        self._thread.start()

    @property
    def engine(self) -> ServingEngine:
        return self._engine

    # ------------------------------------------------------------ requests

    def submit(self, features: np.ndarray) -> Future:
        """Enqueue one request ([n, F] raw features); returns a Future
        resolving to the engine's [num_class, n] raw score sums.  Blocks
        while the bounded queue is full (backpressure, never drops)."""
        features = np.asarray(features)
        if features.ndim != 2:
            raise ValueError("submit expects a [rows, features] matrix")
        n = features.shape[0]
        fut: Future = Future()
        t_arrive_ns = time.perf_counter_ns()
        with self._cond:
            if self._closed:
                raise RuntimeError("ServingFront is closed")
            blocked = False
            while self._queued_rows > 0 \
                    and self._queued_rows + n > self.queue_rows:
                blocked = True
                self._cond.wait(0.05)
                if self._closed:
                    raise RuntimeError("ServingFront is closed")
            # enqueue stamp AFTER any backpressure block: the traced
            # wall time is enqueue → complete; the block rides the
            # timeline as its own event, not inside the identity
            t_enq_ns = time.perf_counter_ns()
            req = _FrontRequest(features, fut, n, time.perf_counter(),
                                trace_id=tracing.next_trace_id(),
                                t_enq_ns=t_enq_ns,
                                block_ns=(t_enq_ns - t_arrive_ns
                                          if blocked else 0))
            self._queue.append(req)
            self._queued_rows += n
            self.stats["requests"] += 1
            self.stats["rows"] += n
            if self._queued_rows > self.stats["queue_peak_rows"]:
                self.stats["queue_peak_rows"] = self._queued_rows
            # the enqueue event files BEFORE the front lock releases:
            # the worker cannot dequeue (it needs this lock) until the
            # event is in the ring, so ring order always shows a
            # request's enqueue before its completion — the ordering
            # contract trace_report --check validates.  tracing._lock is
            # a leaf lock; tracing never calls back into the front.
            if tracing.active():
                # depth_rows = rows already queued AHEAD of this request
                # at its enqueue instant (own rows excluded) — the
                # SLO-prep signal the adaptive-linger design needs
                tracing.event("serve_enqueue", trace=req.trace_id, rows=n,
                              t_ns=t_enq_ns,
                              depth_rows=self._queued_rows - n)
                if blocked:
                    tracing.event("serve_backpressure", trace=req.trace_id,
                                  block_ns=req.block_ns)
            self._cond.notify_all()
        telemetry.count("serve/front_requests")
        telemetry.count("serve/front_rows", n)
        return fut

    def predict(self, features: np.ndarray,
                timeout: Optional[float] = None) -> np.ndarray:
        """Synchronous convenience: ``submit(...).result(timeout)``."""
        return self.submit(features).result(timeout)

    # ------------------------------------------------------------ hot swap

    def swap_engine(self, new_engine: ServingEngine, warmup: bool = True,
                    timeout: Optional[float] = None) -> float:
        """Drain-and-flip atomic hot swap (axis c).  Warms the new
        engine's bucket programs FIRST (double buffering: the old engine
        keeps serving during the compile), then appends a swap marker to
        the request queue and blocks until the worker drains to it and
        flips.  Returns the drain time in seconds (marker enqueue →
        flip), recorded as ``serve/swap_drain_us``."""
        if warmup:
            new_engine.warmup()
        marker = _SwapMarker(new_engine, threading.Event(),
                             time.perf_counter())
        with self._cond:
            if self._closed:
                raise RuntimeError("ServingFront is closed")
            self._queue.append(marker)
            self._cond.notify_all()
        tracing.event("serve_swap_enqueue")
        if not marker.event.wait(timeout):
            # a timed-out swap must not flip LATER behind the caller's
            # back: withdraw the marker if the worker has not reached it
            # yet; if it is already gone the flip is committed (the
            # worker sets the event right after popping) — wait it out
            # and report the swap normally
            with self._cond:
                try:
                    self._queue.remove(marker)
                    withdrawn = True
                except ValueError:
                    withdrawn = False
            if withdrawn:
                raise TimeoutError("hot-swap drain timed out (swap "
                                   "withdrawn; the old engine still "
                                   "serves)")
            marker.event.wait(60.0)
        drain = time.perf_counter() - marker.t0
        self.stats["swaps"] += 1
        self.stats["last_swap_drain_s"] = drain
        telemetry.count("serve/swaps")
        telemetry.count("serve/swap_drain_us", int(drain * 1e6))
        return drain

    # ------------------------------------------------------------ lifecycle

    def close(self, timeout: float = 60.0) -> None:
        """Stop accepting, drain EVERY queued request (zero-drop also at
        shutdown), join the worker, and file the queue-peak gauge."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout)
        # a worker wedged on a hung device dispatch stays REGISTERED — the
        # leak guard exists to surface exactly that (same contract as
        # CheckpointWriter.close)
        if not self._thread.is_alive():
            lifecycle.untrack(self)
        telemetry.count("serve/queue_peak_rows",
                        self.stats["queue_peak_rows"])

    def __enter__(self) -> "ServingFront":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    # ------------------------------------------------------------- worker

    def _rows_before_marker(self, cap: int) -> int:
        """Rows queued ahead of the first swap marker, scanning at most
        until ``cap`` is reached — the caller only compares against the
        top bucket, and a full bounded queue can hold ~queue_rows
        1-row requests (an uncapped scan under the lock would stall
        every submit on each linger poll)."""
        rows = 0
        for item in self._queue:
            if isinstance(item, _SwapMarker) or rows >= cap:
                break
            rows += item.rows
        return rows

    def _serve_loop(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._closed:
                    self._cond.wait(0.1)
                if not self._queue:
                    break                      # closed and drained
                head = self._queue[0]
                if isinstance(head, _SwapMarker):
                    # the flip: everything ahead has been scored on the
                    # old engine; everything behind scores on the new one
                    self._queue.popleft()
                    self._engine = head.engine
                    self._monitor_key, self._monitor_ref = \
                        _drift_identity(head.engine)
                    head.event.set()
                    tracing.event("serve_swap_flip",
                                  drain_us=int((time.perf_counter()
                                                - head.t0) * 1e6))
                    continue
                # first batch boundary (ISSUE 16): the worker has seen
                # the head — queue-wait ends here, linger-wait begins
                t_linger_ns = time.perf_counter_ns()
                maxb = self._engine.buckets[-1]
                deadline = head.t_submit + self.linger_s
                while not self._closed:
                    if self._rows_before_marker(maxb) >= maxb:
                        break
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        break
                    self._cond.wait(min(remaining, 0.05))
                batch: "List[_FrontRequest]" = []
                total = 0
                while self._queue and not isinstance(self._queue[0],
                                                     _SwapMarker):
                    r = self._queue[0]
                    if batch and total + r.rows > maxb:
                        break                  # next batch picks it up
                    self._queue.popleft()
                    batch.append(r)
                    total += r.rows
                t_form_ns = time.perf_counter_ns()
                self._queued_rows -= total
                depth_after = self._queued_rows
                engine = self._engine
                mon_key, mon_ref = self._monitor_key, self._monitor_ref
                self._cond.notify_all()        # wake blocked submitters
            # device work runs OUTSIDE the lock: submit stays wait-free
            # while a batch is on device
            wait_s = time.perf_counter() - batch[0].t_submit
            self.stats["batches"] += 1
            self.stats["coalesced_rows"] += total
            self.stats["linger_wait_s"] += wait_s
            telemetry.count("serve/coalesced_batches")
            telemetry.count("serve/coalesced_rows", total)
            telemetry.count("serve/coalesced_requests", len(batch))
            telemetry.count("serve/linger_wait_us", int(wait_s * 1e6))
            telemetry.count("serve/queue_depth_rows", total + depth_after)
            telemetry.count("serve/queue_depth_samples")
            feats = (batch[0].features if len(batch) == 1 else
                     np.concatenate([r.features for r in batch], axis=0))
            # batch trace (ISSUE 16): installed thread-locally so
            # engine._bucketed fills in the dispatch/walk marks +
            # pad/bucket detail while scoring on THIS thread
            bt = tracing.begin_batch() if tracing.active() else None
            try:
                scores = engine.scores(feats)
            except BaseException as e:  # surfaced per request, never lost
                tracing.end_batch()
                if bt is not None:
                    tracing.event("serve_error", batch=bt.batch_id,
                                  rows=total, error=type(e).__name__)
                for r in batch:
                    # same check→set race as delivery below: a client
                    # cancelling between the check and the set raises
                    # InvalidStateError, which would kill THIS worker
                    # loop and wedge every later request (the PR 13 bug
                    # class, graftlint C2)
                    try:
                        if not (r.future.cancelled() or r.future.done()):
                            r.future.set_exception(e)
                    except Exception:
                        pass
                continue
            tracing.end_batch()
            if monitor.active():
                # live drift feed: every delivered score lands in this
                # engine's signed log-bucket histogram (A/A halves split
                # inside) — outside the front lock, after device work
                monitor.record_scores(mon_key, scores, reference=mon_ref)
            t_scores_ns = time.perf_counter_ns()
            if bt is not None:
                tracing.event("serve_batch", batch=bt.batch_id,
                              requests=len(batch), rows=total,
                              bucket=bt.bucket, pad_rows=bt.pad_rows,
                              wait_us=int(wait_s * 1e6))
                # per-bucket dispatch tallies ride the dump header (the
                # ladder occupancy the express-lane design needs)
                tracing.bump("serve/dispatch_bucket_%d" % bt.bucket)
                tracing.bump("serve/dispatch_rows_bucket_%d" % bt.bucket,
                             total)
                bounds = (t_linger_ns, t_form_ns, bt.run_begin_ns,
                          bt.dispatched_ns, t_scores_ns)
            ofs = 0
            for r in batch:
                # per-request delivery: one client cancelling its Future
                # in the check→set window must not poison the OTHER
                # requests of the same coalesced batch
                try:
                    if not r.future.cancelled():
                        r.future.set_result(scores[:, ofs:ofs + r.rows])
                except Exception:
                    pass
                ofs += r.rows
                if bt is not None:
                    # complete stamp per request, AFTER its delivery —
                    # the six components telescope exactly to
                    # t_done - t_enq (the test-pinned identity)
                    tracing.record_serve_request(
                        r.trace_id, bt, r.t_enq_ns,
                        time.perf_counter_ns(), bounds, r.rows,
                        block_ns=r.block_ns)


def engine_options_from_config(io_config) -> dict:
    """The IOConfig → ServingEngine option mapping, single-homed (cli.py
    and Predictor both consult it).  ``serve_shards`` /
    ``predict_linger_us`` / ``predict_queue`` (ISSUE 13) ride beside the
    PR 7 knobs — the engine validates them loudly at construction."""
    return {
        "buckets": io_config.predict_bucket_list(),
        "quantize": io_config.predict_quantize,
        "donate": io_config.predict_donate,
        "algo": io_config.predict_algo,
        "shards": io_config.serve_shards,
        "linger_us": io_config.predict_linger_us,
        "queue": io_config.predict_queue,
    }
