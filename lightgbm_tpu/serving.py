"""Compiled serving engine: flattened ensembles, bucketed batch shapes,
int8 scoring.

The production predict front (ROADMAP item 1; reference semantics:
``src/application/predictor.hpp:109-197``).  The training-side scorer
replays trees one at a time (``ops/scoring.ensemble_scores``: a lax.scan
with num_leaves-1 sequential masked steps per tree) and the old
``GBDT._device_predict_encode`` re-flattened the WHOLE ensemble on host on
every call.  This module splits serving into the two halves a steady-state
server actually has:

1. **FlatEnsemble** — built ONCE per trained model: the per-node tensors
   stacked ``[T, max_nodes]`` (split_feature, threshold_rank, left/right
   child), the ``[T, max_leaves]`` leaf-value table, and the host-built
   f64 per-feature threshold rank tables that make integer routing EXACT
   (no f32 threshold-comparison rounding — same encoding contract as
   ``_device_predict_encode``).  ``FlatEnsemble.encode(features)`` is the
   only per-batch host work: one ``np.searchsorted`` per used feature.

2. **ServingEngine** — owns the compiled programs.  Batches are padded to
   a fixed bucket ladder (default 1 / 32 / 1024 / 65536 rows) so
   steady-state serving sees a CLOSED set of program shapes and never
   recompiles; the codes buffer is donated (non-CPU backends) so the pad
   buffer is recycled in place.  Scoring walks all trees breadth-first in
   lockstep (``ops/scoring.bfs_scores_impl``): one gather-based level
   step per depth over the whole [T, N] frontier — O(max_depth) fused
   steps instead of the training scorer's O(T·L) — and accumulates leaf
   values in tree order, so scores are BIT-EQUAL to the training-side
   scorer.  ``quantize="int8"`` swaps the leaf table for int8 + per-tree
   scale (quarter table traffic, single-pass bf16 one-hot read; routing
   stays exact — only leaf VALUES are quantized).

Programs are costmodel-instrumented under phase "predict" (roofline
attribution + compile observability ride along whenever telemetry is
armed), and the engine files ``serve/*`` counters.
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from . import costmodel, telemetry

DEFAULT_BUCKETS: Tuple[int, ...] = (1, 32, 1024, 65536)

# module-level flatten counter: the regression test for the encode-once
# contract (predict_file must flatten the ensemble exactly once across
# its 500k-row chunks) reads the delta — independent of whether
# telemetry is armed
FLATTEN_COUNT = 0


def _tree_max_depth(lc: np.ndarray, rc: np.ndarray, n: int) -> int:
    """Depth (in edges from the root) a BFS walk needs to resolve every
    row of this tree.  Children are always created AFTER their parent
    (node k's children have indices > k, tree.cpp:70-71), so one forward
    pass suffices."""
    if n <= 0:
        return 0
    depth = np.ones(n, np.int32)
    for k in range(n):
        for c in (int(lc[k]), int(rc[k])):
            if c >= 0:
                depth[c] = depth[k] + 1
    return int(depth.max())


class FlatEnsemble:
    """A trained ensemble flattened once into dense per-node tensors plus
    the host-built f64 rank-code tables (see module docstring)."""

    def __init__(self, used, thresholds, sf, tr, lc, rc, lv, nl, root,
                 tree_class, max_nodes: int, max_depth: int,
                 num_class: int):
        self.used = used                 # original column ids, sorted
        self.thresholds = thresholds     # {col: sorted unique f64 thresholds}
        self.split_feature = sf          # [T, max_nodes] int32 (inner ids)
        self.threshold_rank = tr         # [T, max_nodes] int32
        self.left_child = lc             # [T, max_nodes] int32 (~leaf enc)
        self.right_child = rc            # [T, max_nodes] int32
        self.leaf_value = lv             # [T, max_nodes + 1] f32
        self.num_leaves = nl             # [T] int32
        self.root_state = root           # [T] int32: 0, or ~0 for stumps
        self.tree_class = tree_class     # [T] int32
        self.max_nodes = max_nodes
        self.max_depth = max_depth
        self.num_class = num_class
        self.num_trees = sf.shape[0]
        self._int8: Optional[Tuple[np.ndarray, np.ndarray]] = None

    @classmethod
    def from_models(cls, models, num_class: int) -> "FlatEnsemble":
        """Flatten ``models`` (list of models.tree.Tree).  This is the
        once-per-model cost the old per-call ``_device_predict_encode``
        paid on EVERY predict call."""
        global FLATTEN_COUNT
        FLATTEN_COUNT += 1
        telemetry.count("serve/ensemble_flatten")
        T = len(models)
        max_nodes = max(max((t.num_leaves - 1 for t in models), default=1),
                        1)
        used = sorted({int(f) for t in models
                       for f in t.split_feature_real[:t.num_leaves - 1]})
        fmap = {f: i for i, f in enumerate(used)}
        thr = {f: [] for f in used}
        for t in models:
            for f, v in zip(t.split_feature_real, t.threshold):
                thr[int(f)].append(float(v))
        thr = {f: np.unique(np.asarray(v, np.float64))
               for f, v in thr.items()}

        sf = np.zeros((T, max_nodes), np.int32)
        tr = np.zeros((T, max_nodes), np.int32)
        lc = np.zeros((T, max_nodes), np.int32)
        rc = np.zeros((T, max_nodes), np.int32)
        lv = np.zeros((T, max_nodes + 1), np.float32)
        nl = np.zeros((T,), np.int32)
        root = np.zeros((T,), np.int32)
        max_depth = 0
        for k, t in enumerate(models):
            n = t.num_leaves - 1
            nl[k] = t.num_leaves
            lv[k, :t.num_leaves] = t.leaf_value
            if n <= 0:
                root[k] = -1      # ~0: the stump's single leaf
                continue
            sf[k, :n] = [fmap[int(f)] for f in t.split_feature_real[:n]]
            tr[k, :n] = [int(np.searchsorted(thr[int(f)], float(v), "left"))
                         for f, v in zip(t.split_feature_real[:n],
                                         t.threshold[:n])]
            lc[k, :n] = t.left_child[:n]
            rc[k, :n] = t.right_child[:n]
            max_depth = max(max_depth,
                            _tree_max_depth(lc[k], rc[k], n))
        tc = (np.arange(T) % max(num_class, 1)).astype(np.int32)
        return cls(used, thr, sf, tr, lc, rc, lv, nl, root, tc,
                   max_nodes, max_depth, max(num_class, 1))

    def encode(self, features: np.ndarray) -> np.ndarray:
        """Rank-encode raw feature values against the ensemble's own
        threshold tables, in float64 on host — the integer walk on device
        then routes rows EXACTLY like the reference's double comparisons
        (tree.h:163-175).  [F_used, N] int32; the only per-batch host
        work."""
        N = features.shape[0]
        codes = np.zeros((max(len(self.used), 1), N), np.int32)
        for i, f in enumerate(self.used):
            # code = #{thresholds < x}; x > t_j  <=>  code > j, and an
            # exact tie x == t_j gives code == j -> left (`value > t`)
            vals = features[:, f]
            c = np.searchsorted(self.thresholds[f], vals, side="left")
            # NaN sorts past every threshold; the host walk's `value > t`
            # is False for NaN -> always left.  Match it.
            c[np.isnan(vals)] = 0
            codes[i] = c
        return codes

    def int8_tables(self) -> Tuple[np.ndarray, np.ndarray]:
        """(leaf_q [T, max_leaves] int8, scale [T] f32), built lazily and
        cached.  Symmetric per-tree quantization: scale = max|leaf|/127,
        q = round(leaf/scale) — a leaf reads back as ``q * scale``."""
        if self._int8 is None:
            amax = np.abs(self.leaf_value).max(axis=1)
            scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
            q = np.clip(np.round(self.leaf_value / scale[:, None]),
                        -127, 127).astype(np.int8)
            self._int8 = (q, scale)
        return self._int8

    def dequantized_leaf_value(self) -> np.ndarray:
        """[T, max_leaves] f32 leaf table of the int8 ensemble — the host
        reference the int8 engine must score bit-equal against."""
        q, scale = self.int8_tables()
        return q.astype(np.float32) * scale[:, None]


class ServingEngine:
    """Compiled, batched prediction over one FlatEnsemble (see module
    docstring).  Thread-compat with the repo's other device paths: one
    engine per model, calls are serialized by the caller."""

    def __init__(self, flat: FlatEnsemble,
                 buckets: Sequence[int] = DEFAULT_BUCKETS,
                 quantize: str = "float32", donate: str = "auto",
                 algo: str = "bfs"):
        if quantize not in ("float32", "int8"):
            raise ValueError("quantize must be float32 or int8")
        if algo not in ("bfs", "scan"):
            raise ValueError("algo must be bfs or scan")
        buckets = tuple(sorted({int(b) for b in buckets}))
        if not buckets or buckets[0] < 1:
            raise ValueError("buckets must be positive ints")
        self.flat = flat
        self.buckets = buckets
        self.quantize = quantize
        self.algo = algo
        self.donate = self._resolve_donate(donate)
        self._tables = None            # device-resident node tensors
        self._programs: Dict[str, object] = {}

    @staticmethod
    def _resolve_donate(donate: str) -> bool:
        if donate not in ("auto", "true", "false"):
            raise ValueError("donate must be auto, true or false")
        if donate == "auto":
            # CPU ignores donation with a warning per call site — auto
            # keeps serving logs clean there; accelerators donate
            try:
                import jax
                return jax.default_backend() != "cpu"
            except Exception:
                return False
        return donate == "true"

    # ------------------------------------------------------------ programs

    def _device_tables(self):
        """Push the flattened tensors to device ONCE (cached jnp arrays;
        re-used by every bucketed call — steady-state serving transfers
        only the codes buffer)."""
        if self._tables is None:
            import jax.numpy as jnp
            f = self.flat
            t = {
                "sf": jnp.asarray(f.split_feature),
                "tr": jnp.asarray(f.threshold_rank),
                "lc": jnp.asarray(f.left_child),
                "rc": jnp.asarray(f.right_child),
                "root": jnp.asarray(f.root_state),
                "tc": jnp.asarray(f.tree_class),
                "nl": jnp.asarray(f.num_leaves),
            }
            if self.quantize == "int8":
                q, scale = f.int8_tables()
                t["lv_q"] = jnp.asarray(q)
                t["lv_scale"] = jnp.asarray(scale)
                # the scan A/B path reads a plain f32 table: give it the
                # DEQUANTIZED one so algo=scan scores the same quantized
                # model bit-for-bit (never silently full precision)
                t["lv"] = jnp.asarray(f.dequantized_leaf_value())
            else:
                t["lv"] = jnp.asarray(f.leaf_value)
            self._tables = t
        return self._tables

    def _program(self, kind: str):
        """One costmodel-instrumented jit per kind ("scores"/"leaves");
        bucket shapes are signatures of the SAME program object, so the
        compiled-program inventory stays a closed set (the no-recompile
        assertion tests/test_serving.py pins via the compile counters)."""
        prog = self._programs.get(kind)
        if prog is None:
            import jax

            from .ops import scoring
            donate = (0,) if self.donate else ()
            if kind == "scores":
                impl = (scoring.bfs_scores_int8_impl
                        if self.quantize == "int8"
                        else scoring.bfs_scores_impl)
                fn = jax.jit(impl,
                             static_argnames=("max_depth", "num_class"),
                             donate_argnums=donate)
            else:
                fn = jax.jit(scoring.bfs_leaf_indices_impl,
                             static_argnames=("max_depth",),
                             donate_argnums=donate)
            tag = "_int8" if (self.quantize == "int8"
                              and kind == "scores") else ""
            prog = costmodel.instrument(f"serve/bfs_{kind}{tag}", fn,
                                        phase="predict")
            self._programs[kind] = prog
        return prog

    def _run_scores(self, codes_chunk):
        import jax.numpy as jnp
        t = self._device_tables()
        f = self.flat
        if self.algo == "scan":
            # legacy per-tree replay (the training-side scorer) at the
            # engine's bucket shapes — the A/B reference bench_predict
            # prices the breadth-first walk against.  t["lv"] is the
            # device-cached f32 table (dequantized under quantize=int8),
            # so the A/B pays no per-call upload and never silently
            # serves full precision for an int8 engine.
            from .ops.scoring import ensemble_scores
            return ensemble_scores(
                jnp.asarray(codes_chunk), t["sf"], t["tr"], t["lc"],
                t["rc"], t["lv"], t["nl"], t["tc"],
                max_nodes=f.max_nodes, num_class=f.num_class)
        prog = self._program("scores")
        if self.quantize == "int8":
            return prog(jnp.asarray(codes_chunk), t["sf"], t["tr"],
                        t["lc"], t["rc"], t["lv_q"], t["lv_scale"],
                        t["root"], t["tc"], max_depth=f.max_depth,
                        num_class=f.num_class)
        return prog(jnp.asarray(codes_chunk), t["sf"], t["tr"], t["lc"],
                    t["rc"], t["lv"], t["root"], t["tc"],
                    max_depth=f.max_depth, num_class=f.num_class)

    def _run_leaves(self, codes_chunk):
        import jax.numpy as jnp
        t = self._device_tables()
        f = self.flat
        if self.algo == "scan":
            from .ops.scoring import ensemble_leaf_indices
            return ensemble_leaf_indices(
                jnp.asarray(codes_chunk), t["sf"], t["tr"], t["lc"],
                t["rc"], t["nl"], max_nodes=f.max_nodes)
        return self._program("leaves")(
            jnp.asarray(codes_chunk), t["sf"], t["tr"], t["lc"], t["rc"],
            t["root"], max_depth=f.max_depth)

    # ------------------------------------------------------------- serving

    def bucket_for(self, n: int) -> int:
        """Smallest bucket that holds ``n`` rows (callers chunk at the
        largest bucket first, so n <= buckets[-1] here)."""
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    def _bucketed(self, features: np.ndarray, run, assemble):
        """encode → chunk at the largest bucket → pad-to-bucket → run →
        strip padding.  ``run`` maps a padded [F, B] codes chunk to a
        device result; ``assemble`` concatenates the per-chunk np arrays
        along the row axis."""
        with telemetry.span("predict_encode"):
            codes = self.flat.encode(features)
        N = codes.shape[1]
        maxb = self.buckets[-1]
        outs = []
        telemetry.count("serve/predict_calls")
        telemetry.count("serve/rows", N)
        with telemetry.span("predict") as sp:
            for s in range(0, max(N, 1), maxb):
                chunk = codes[:, s:s + maxb]
                n = chunk.shape[1]
                b = self.bucket_for(n)
                if b > n:
                    telemetry.count("serve/pad_rows", b - n)
                    chunk = np.concatenate(
                        [chunk, np.zeros((chunk.shape[0], b - n),
                                         chunk.dtype)], axis=1)
                telemetry.count(f"serve/bucket_{b}")
                # fence like every device-work span (PR 4): unfenced
                # async spans time the dispatch, not the walk, and the
                # predict-phase roofline would be meaningless
                outs.append((sp.fence(run(chunk)), n))
        return assemble(outs)

    def scores(self, features: np.ndarray) -> np.ndarray:
        """[num_class, N] raw ensemble score sums (float64 on host, f32
        accumulation on device — identical to the training-side scorer's
        accumulation order)."""
        if self.flat.num_trees == 0:
            return np.zeros((self.flat.num_class, features.shape[0]))
        return self._bucketed(
            features, self._run_scores,
            lambda outs: np.concatenate(
                [np.asarray(o, np.float64)[:, :n] for o, n in outs],
                axis=1))

    def leaf_indices(self, features: np.ndarray) -> np.ndarray:
        """[N, T] leaf index per tree (PredictLeafIndex layout)."""
        if self.flat.num_trees == 0:
            return np.zeros((features.shape[0], 0), np.int32)
        return self._bucketed(
            features, self._run_leaves,
            lambda outs: np.concatenate(
                [np.asarray(o, np.int32)[:, :n].T for o, n in outs],
                axis=0))


def engine_options_from_config(io_config) -> dict:
    """The IOConfig → ServingEngine option mapping, single-homed (cli.py
    and Predictor both consult it)."""
    return {
        "buckets": io_config.predict_bucket_list(),
        "quantize": io_config.predict_quantize,
        "donate": io_config.predict_donate,
        "algo": io_config.predict_algo,
    }
