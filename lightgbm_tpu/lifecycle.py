"""Shared live-object inventory for thread-owning subsystems (ISSUE 15).

PRs 12-14 each grew a concurrent subsystem with its own private liveness
bookkeeping — ``checkpoint._LIVE_WRITERS``, ``faults.armed()``, a
watchdog flag in telemetry — and the test-suite leak guard
(tests/conftest.py) had to hand-enumerate every one.  A new thread class
was therefore INVISIBLE to the guard until someone remembered to extend
conftest (the ``io/parser.py`` prefetch thread and the ServingFront
worker both shipped without any registration path at all).  This module
is the single registry both consumers read:

- the conftest leak guard iterates :func:`leaks` after every test and
  fails the offender, naming the leaked kind, then calls each entry's
  ``closer`` so the rest of the suite runs unpoisoned;
- graftlint C1 (analysis/concurrency_rules.py) requires every
  ``threading.Thread`` spawn site to sit beside a :func:`track` call, so
  a thread class that forgets to register fails the pre-merge gate
  instead of silently escaping the guard.

Two registration shapes:

- :func:`track`/:func:`untrack` — a live OBJECT owning a thread (a
  CheckpointWriter, a ServingFront, a prefetch handle).  ``closer`` must
  be idempotent: the guard calls it on a leaked entry, and well-behaved
  owners also call their own close twice (context manager + explicit).
- :func:`probe` — process-global hatch STATE that is not an object (the
  faults module's armed one-shot): ``check()`` returning truthy at guard
  time is a leak; ``closer()`` clears it.

Pure stdlib, threadsafe (track/untrack run on worker threads), no JAX —
importable by the analysis layer and by every threaded subsystem without
ordering hazards.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple

_lock = threading.Lock()
# id(handle) -> (kind, name, closer, handle).  The handle reference is
# deliberately strong: an owner that drops its last reference without
# closing is exactly the leak the registry exists to surface.
_LIVE: Dict[int, Tuple[str, str, Callable[[], None], object]] = {}
_PROBES: List[Tuple[str, Callable[[], bool], Callable[[], None]]] = []


def track(kind: str, handle: object, closer: Callable[[], None],
          name: Optional[str] = None) -> object:
    """Register a live thread-owning object.  Returns ``handle`` so the
    call can wrap a constructor expression.  Re-tracking the same handle
    replaces its entry (idempotent)."""
    with _lock:
        _LIVE[id(handle)] = (str(kind), name or type(handle).__name__,
                             closer, handle)
    return handle


def untrack(handle: object) -> None:
    """Deregister (idempotent — closing twice must not raise)."""
    with _lock:
        _LIVE.pop(id(handle), None)


def tracked(handle: object) -> bool:
    with _lock:
        return id(handle) in _LIVE


def probe(kind: str, check: Callable[[], bool],
          closer: Callable[[], None]) -> None:
    """Register a process-global leak probe (module import time; never
    deregistered — the probe's ``check`` decides liveness per call)."""
    with _lock:
        for i, (k, _c, _cl) in enumerate(_PROBES):
            if k == kind:                 # module reload: replace, not stack
                _PROBES[i] = (kind, check, closer)
                return
        _PROBES.append((str(kind), check, closer))


def live(kind: Optional[str] = None) -> List[Tuple[str, str]]:
    """Live tracked entries as (kind, name) pairs, optionally filtered."""
    with _lock:
        return [(k, n) for (k, n, _c, _h) in _LIVE.values()
                if kind is None or k == kind]


def live_count(kind: Optional[str] = None) -> int:
    return len(live(kind))


def leaks() -> List[Tuple[str, str, Callable[[], None]]]:
    """Everything currently leaked: live tracked objects plus tripped
    probes, as (kind, name, closer) — the conftest guard's one read."""
    with _lock:
        out = [(k, n, c) for (k, n, c, _h) in _LIVE.values()]
        probes = list(_PROBES)
    for kind, check, closer in probes:
        try:
            if check():
                out.append((kind, kind, closer))
        except Exception:  # a broken probe is itself a leak to surface
            out.append((kind, kind + " (probe raised)", closer))
    return out
