"""Objective functions: gradients/hessians on device.

Re-design of /root/reference/src/objective/ as pure jnp element-wise (or
per-query, for lambdarank) transforms.  Factory mirrors
objective_function.cpp:9-20.  Gradients/hessians are float32 (score_t,
meta.h:15).
"""
from __future__ import annotations

from ..utils import log
from .regression import RegressionL2Loss
from .binary import BinaryLogloss
from .multiclass import MulticlassLogloss
from .rank import LambdarankNDCG


def create_objective(objective_type: str, config):
    """CreateObjectiveFunction (objective_function.cpp:9-20)."""
    if objective_type == "regression":
        return RegressionL2Loss(config)
    if objective_type == "binary":
        return BinaryLogloss(config)
    if objective_type == "lambdarank":
        return LambdarankNDCG(config)
    if objective_type == "multiclass":
        return MulticlassLogloss(config)
    log.fatal("Unknown objective type name: %s" % objective_type)
