"""Lambdarank (NDCG) objective.

Re-design of /root/reference/src/objective/rank_objective.hpp:19-230.  The
reference loops queries with OpenMP and docs in O(q²) nested loops; the TPU
formulation pads every query to the max query length and computes the whole
pairwise lambda matrix per query with vmapped dense [Q, Q] ops, processed in
query blocks (lax.map) to bound memory.  The 1M-entry sigmoid lookup table
(rank_objective.hpp:179-192) is replaced by computing the sigmoid exactly —
a table is a CPU trick, the VPU computes exp faster than it gathers.

Math parity (rank_objective.hpp:76-164):
- pairs (high, low) sorted by score desc; only label(high) > label(low);
- ΔNDCG = (gain_hi − gain_lo)·|disc_hi − disc_lo|·inv_max_dcg, regularized by
  /(0.01+|Δscore|) when best ≠ worst score;
- λ = −σ(Δs)·ΔNDCG accumulated ± on (high, low); hessian 2·ΔNDCG·σ(2−σ).
"""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp

from ..utils import log
from ..metrics.dcg import DCGCalculator

K_MIN_SCORE = -np.inf


class LambdarankNDCG:
    # per-query tables index the GLOBAL score vector, so the params are
    # not row-shardable — instead the data-parallel learner gathers the
    # score shards and computes the pairwise lambdas replicated, then
    # slices each shard's rows back out (needs_global_score protocol).
    # The reference distributes this per machine over its own queries
    # (rank_objective.hpp:68-192 under dataset.cpp:189-206 query-atomic
    # sharding); the replicated formulation trades S-fold redundant
    # O(sum q^2) VPU work — a small term next to histogram building — for
    # zero extra collectives beyond the all_gather the in-program metrics
    # already pay, and stays correct even when device-level row blocks cut
    # queries mid-way (only PROCESS shards are query-atomic).
    rows_aligned_params = False
    needs_global_score = True

    def __init__(self, config):
        self._sigmoid = float(config.sigmoid)
        if self._sigmoid <= 0.0:
            log.fatal("sigmoid param %f should greater than zero" % self._sigmoid)
        self.label_gain = np.asarray(config.label_gain, dtype=np.float32)
        self.optimize_pos_at = int(config.max_position)
        self.weights = None

    def init(self, metadata, num_data: int) -> None:
        if metadata.query_boundaries is None:
            log.fatal("For lambdarank tasks, should have query information")
        label = np.asarray(metadata.label)
        boundaries = np.asarray(metadata.query_boundaries)
        nq = boundaries.size - 1
        sizes = np.diff(boundaries)
        qmax = int(sizes.max())
        self.num_data = num_data
        dcg = DCGCalculator(self.label_gain)

        # cached inverse max DCG per query (rank_objective.hpp:53-63)
        inv_max_dcg = np.zeros(nq, dtype=np.float32)
        for q in range(nq):
            lo, hi = boundaries[q], boundaries[q + 1]
            max_dcg = dcg.cal_max_dcg_at_k(self.optimize_pos_at, label[lo:hi])
            inv_max_dcg[q] = 1.0 / max_dcg if max_dcg > 0 else max_dcg

        # padded [nq, qmax] doc-index layout
        doc_index = np.full((nq, qmax), -1, dtype=np.int32)
        for q in range(nq):
            lo, hi = boundaries[q], boundaries[q + 1]
            doc_index[q, :hi - lo] = np.arange(lo, hi)
        valid = doc_index >= 0

        self.doc_index = jnp.asarray(np.where(valid, doc_index, 0))
        self.valid = jnp.asarray(valid)
        self.counts = jnp.asarray(sizes.astype(np.int32))
        self.inv_max_dcg = jnp.asarray(inv_max_dcg)
        self.labels_padded = jnp.asarray(
            np.where(valid, label[np.where(valid, doc_index, 0)], 0.0)
            .astype(np.float32))
        self.discount = jnp.asarray(
            dcg.discount[:qmax].astype(np.float32))
        self.gains = jnp.asarray(self.label_gain)
        self.qmax = qmax
        self.nq = nq
        if metadata.weights is not None:
            self.weights = jnp.asarray(metadata.weights, jnp.float32)
        # query block size bounds the [block, Q, Q] working set to ~64 MB
        self.block = max(1, min(nq, (1 << 24) // max(qmax * qmax, 1)))

    def get_gradients(self, score: jax.Array):
        _, params, fn = self.chunk_spec()
        return fn(params, score)

    def globalize_layout(self, global_md, shard_layout,
                         num_padded: int) -> None:
        """Multi-process data parallel: rebuild the per-query tables over
        the GLOBAL rows in the padded-global coordinate system.

        ``global_md`` is the all-process metadata (Metadata.global_view:
        labels/query layout concatenated in process order — valid because
        row sharding is query-atomic, dataset.cpp:189-206);
        ``shard_layout`` maps compacted global row c of process p to padded
        position start_p + (c - c_p).  The rebuilt doc_index then indexes
        the padded global score directly, and weights scatter into a
        padded vector so the lambda products line up."""
        self.init(global_md, int(np.sum([ln for _, ln in shard_layout])))
        pad_pos = np.concatenate(
            [start + np.arange(ln) for start, ln in shard_layout]
        ).astype(np.int32)
        doc_index = np.asarray(self.doc_index)
        valid = np.asarray(self.valid)
        self.doc_index = jnp.asarray(
            np.where(valid, pad_pos[doc_index], 0).astype(np.int32))
        if self.weights is not None:
            w = np.zeros(num_padded, np.float32)
            w[pad_pos] = np.asarray(self.weights)
            self.weights = jnp.asarray(w)
        self.num_data = num_padded

    def chunk_spec(self):
        # block is static (it shapes the padded query-block map); the
        # scatter length follows the score length at trace time, so one
        # callable serves both the true-row and shard-padded layouts
        fn = functools.partial(_rank_gradients, block=self.block)
        key = ("lambdarank", self.num_data, self.block, self.qmax, self.nq,
               self.weights is not None)
        return key, self.chunk_params(), _RANK_FNS.setdefault(key, fn)

    def chunk_params(self):
        return {"doc_index": self.doc_index, "valid": self.valid,
                "labels": self.labels_padded, "inv_max_dcg": self.inv_max_dcg,
                "discount": self.discount, "gains": self.gains,
                "sigmoid": jnp.float32(self._sigmoid),
                "weights": self.weights}

    @property
    def sigmoid(self) -> float:
        # ranking scores are used raw at predict time (rank_objective.hpp:194-199)
        return -1.0

    @property
    def num_class(self) -> int:
        return 1


# one callable per static key so the chunk trainer's program cache can use
# function identity (a fresh functools.partial per call would defeat it)
_RANK_FNS: dict = {}


def _rank_gradients(params, score, *, block: int):
    # named_scope: profile_dir= traces label the lambda ops with the
    # objective (matches the telemetry "gradient" phase; ISSUE 2)
    with jax.named_scope("gradient_lambdarank"):
        lambdas, hessians = _lambdarank_grads(
            score.astype(jnp.float32), params["doc_index"], params["valid"],
            params["labels"], params["inv_max_dcg"], params["discount"],
            params["gains"], params["sigmoid"], block)
        if params["weights"] is not None:
            w = params["weights"]
            if w.shape[0] < lambdas.shape[0]:
                # single-process DP pads rows at the tail; padded rows
                # carry zero lambdas, so zero-padding the weights is exact
                w = jnp.pad(w, (0, lambdas.shape[0] - w.shape[0]))
            lambdas = lambdas * w
            hessians = hessians * w
        return lambdas, hessians


@functools.partial(jax.jit, static_argnames=("block",))
def _lambdarank_grads(score, doc_index, valid, labels, inv_max_dcg, discount,
                      gains, sigmoid, block: int):
    # scatter length follows the (possibly shard-padded) score; doc_index
    # never points at padding, so padded rows get exactly zero
    num_data = score.shape[0]
    nq, qmax = doc_index.shape
    scores_padded = jnp.where(valid, score[doc_index], K_MIN_SCORE)

    pad_q = (-nq) % block
    def pad0(x):
        return jnp.pad(x, [(0, pad_q)] + [(0, 0)] * (x.ndim - 1))
    blocks = (nq + pad_q) // block

    def reshape(x):
        return pad0(x).reshape((blocks, block) + x.shape[1:])

    def one_query(s, l, imd):
        """Pairwise lambdas for one padded query (rank_objective.hpp:76-156)."""
        order = jnp.argsort(-s)          # score desc; padded (-inf) sink last
        ss = s[order]
        ll = l[order].astype(jnp.int32)
        cnt = jnp.sum(ss != K_MIN_SCORE).astype(jnp.int32)
        best = ss[0]
        worst_idx = jnp.maximum(cnt - 1, 0)
        worst_idx = jnp.where(
            (worst_idx > 0) & (ss[worst_idx] == K_MIN_SCORE),
            worst_idx - 1, worst_idx)
        worst = ss[worst_idx]

        hi_s, lo_s = ss[:, None], ss[None, :]
        hi_l, lo_l = ll[:, None], ll[None, :]
        pair = (hi_l > lo_l) & (hi_s != K_MIN_SCORE) & (lo_s != K_MIN_SCORE)
        delta = hi_s - lo_s
        dcg_gap = gains[hi_l] - gains[lo_l]
        paired_disc = jnp.abs(discount[:, None] - discount[None, :])
        delta_ndcg = dcg_gap * paired_disc * imd
        delta_ndcg = jnp.where((hi_l != lo_l) & (best != worst),
                               delta_ndcg / (0.01 + jnp.abs(delta)),
                               delta_ndcg)
        sig = 2.0 / (1.0 + jnp.exp(2.0 * delta * sigmoid))
        p_hess = sig * (2.0 - sig)
        lam = jnp.where(pair, -sig * delta_ndcg, 0.0)
        hes = jnp.where(pair, 2.0 * delta_ndcg * p_hess, 0.0)

        lam_sorted = jnp.sum(lam, axis=1) - jnp.sum(lam, axis=0)
        hes_sorted = jnp.sum(hes, axis=1) + jnp.sum(hes, axis=0)
        # unsort back to in-query doc order
        lam_out = jnp.zeros_like(lam_sorted).at[order].set(lam_sorted)
        hes_out = jnp.zeros_like(hes_sorted).at[order].set(hes_sorted)
        return lam_out, hes_out

    def block_fn(args):
        s_b, l_b, imd_b = args
        return jax.vmap(one_query)(s_b, l_b, imd_b)

    lam_b, hes_b = jax.lax.map(
        block_fn, (reshape(scores_padded), reshape(labels),
                   pad0(inv_max_dcg).reshape(blocks, block)))
    lam = lam_b.reshape(-1, qmax)[:nq]
    hes = hes_b.reshape(-1, qmax)[:nq]

    flat_idx = doc_index.reshape(-1)
    flat_valid = valid.reshape(-1)
    lambdas = jnp.zeros((num_data,), jnp.float32).at[flat_idx].add(
        jnp.where(flat_valid, lam.reshape(-1), 0.0))
    hessians = jnp.zeros((num_data,), jnp.float32).at[flat_idx].add(
        jnp.where(flat_valid, hes.reshape(-1), 0.0))
    return lambdas, hessians
