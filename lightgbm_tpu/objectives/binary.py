"""Binary logloss objective (/root/reference/src/objective/binary_objective.hpp:13-102)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..utils import log


class BinaryLogloss:
    # chunk_params are all row-aligned [N, ...] arrays or scalars —
    # shardable over the data axis for data-parallel chunked training
    rows_aligned_params = True
    def __init__(self, config):
        self.is_unbalance = config.is_unbalance
        self._sigmoid = float(config.sigmoid)
        if self._sigmoid <= 0.0:
            log.fatal("Sigmoid parameter %f :should greater than zero"
                      % self._sigmoid)
        self.weights = None

    def init(self, metadata, num_data: int) -> None:
        label = np.asarray(metadata.label)
        cnt_positive = int((label == 1).sum())
        cnt_negative = num_data - cnt_positive
        # (the reference's own log line misspells "postive",
        # binary_objective.hpp — fixed here, not parity-relevant)
        log.info("Number of positive:%d,  number of negative:%d"
                 % (cnt_positive, cnt_negative))
        if cnt_positive == 0 or cnt_negative == 0:
            log.fatal("Input training data only contains one class")
        # labels → {−1, +1}; unbalance reweights negatives by pos/neg
        # (binary_objective.hpp:42-52)
        self.label_sign = jnp.asarray(np.where(label == 1, 1.0, -1.0),
                                      jnp.float32)
        neg_weight = (cnt_positive / cnt_negative if self.is_unbalance else 1.0)
        self.label_weight = jnp.asarray(
            np.where(label == 1, 1.0, neg_weight), jnp.float32)
        if metadata.weights is not None:
            self.weights = jnp.asarray(metadata.weights, jnp.float32)

    def get_gradients(self, score: jax.Array):
        """response = −2·l·σ/(1+exp(2·l·σ·s)); hess = |r|(2σ−|r|)
        (binary_objective.hpp:55-81)."""
        return _binary_gradients(self.chunk_params(), score)

    def chunk_spec(self):
        """(key, params, fn) for the fused-chunk trainer: fn is a module-
        level pure function (dataset state rides in params as runtime
        inputs), so compiled chunk programs are shared across boosters and
        datasets of the same shape."""
        return (("binary", self.weights is not None), self.chunk_params(),
                _binary_gradients)

    def chunk_params(self):
        return {"sigmoid": jnp.float32(self._sigmoid),
                "label_sign": self.label_sign,
                "label_weight": self.label_weight,
                "weights": self.weights}

    def globalize(self, make_global) -> None:
        """Multi-process: lift row-aligned state to global sharded arrays.
        Padded rows get label_sign=0 -> zero response/hessian, so they
        cannot contribute even without masking."""
        self.label_sign = make_global(self.label_sign)
        self.label_weight = make_global(self.label_weight)
        if self.weights is not None:
            self.weights = make_global(self.weights)

    @property
    def sigmoid(self) -> float:
        return self._sigmoid

    @property
    def num_class(self) -> int:
        return 1


def _binary_gradients(params, score):
    # named_scope: profile_dir= traces label the gradient ops with the
    # objective (matches the telemetry "gradient" phase; ISSUE 2)
    with jax.named_scope("gradient_binary"):
        sig = params["sigmoid"]
        ls = params["label_sign"]
        response = -2.0 * ls * sig / (1.0 + jnp.exp(2.0 * ls * sig * score))
        abs_response = jnp.abs(response)
        grad = response * params["label_weight"]
        hess = (abs_response * (2.0 * sig - abs_response)
                * params["label_weight"])
        if params["weights"] is not None:
            grad = grad * params["weights"]
            hess = hess * params["weights"]
        return grad, hess
