"""L2 regression objective (/root/reference/src/objective/regression_objective.hpp:10-53)."""
from __future__ import annotations

import jax
import jax.numpy as jnp


class RegressionL2Loss:
    # chunk_params are all row-aligned [N, ...] arrays or scalars —
    # shardable over the data axis for data-parallel chunked training
    rows_aligned_params = True
    def __init__(self, config):
        self.weights = None

    def init(self, metadata, num_data: int) -> None:
        self.label = jnp.asarray(metadata.label, jnp.float32)
        if metadata.weights is not None:
            self.weights = jnp.asarray(metadata.weights, jnp.float32)

    def get_gradients(self, score: jax.Array):
        """grad = score − label, hess = 1 (×weight)
        (regression_objective.hpp:24-39)."""
        return _regression_gradients(self.chunk_params(), score)

    def chunk_spec(self):
        return (("regression", self.weights is not None),
                self.chunk_params(), _regression_gradients)

    def chunk_params(self):
        return {"label": self.label, "weights": self.weights}

    def globalize(self, make_global) -> None:
        """Multi-process: lift row-aligned state to global sharded arrays
        (the data-parallel chunk shards them over the mesh data axis)."""
        self.label = make_global(self.label)
        if self.weights is not None:
            self.weights = make_global(self.weights)

    @property
    def sigmoid(self) -> float:
        return -1.0

    @property
    def num_class(self) -> int:
        return 1


def _regression_gradients(params, score):
    # named_scope: profile_dir= traces label the gradient ops with the
    # objective (matches the telemetry "gradient" phase; ISSUE 2)
    with jax.named_scope("gradient_regression"):
        grad = score.astype(jnp.float32) - params["label"]
        hess = jnp.ones_like(grad)
        if params["weights"] is not None:
            grad = grad * params["weights"]
            hess = hess * params["weights"]
        return grad, hess
