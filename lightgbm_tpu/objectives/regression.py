"""L2 regression objective (/root/reference/src/objective/regression_objective.hpp:10-53)."""
from __future__ import annotations

import jax
import jax.numpy as jnp


class RegressionL2Loss:
    def __init__(self, config):
        self.weights = None

    def init(self, metadata, num_data: int) -> None:
        self.label = jnp.asarray(metadata.label, jnp.float32)
        if metadata.weights is not None:
            self.weights = jnp.asarray(metadata.weights, jnp.float32)

    def get_gradients(self, score: jax.Array):
        """grad = score − label, hess = 1 (×weight)
        (regression_objective.hpp:24-39)."""
        grad = score.astype(jnp.float32) - self.label
        hess = jnp.ones_like(grad)
        if self.weights is not None:
            grad = grad * self.weights
            hess = hess * self.weights
        return grad, hess

    @property
    def sigmoid(self) -> float:
        return -1.0

    @property
    def num_class(self) -> int:
        return 1
