"""Multiclass softmax objective (/root/reference/src/objective/multiclass_objective.hpp:13-92)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..utils import log


class MulticlassLogloss:
    # chunk_params are all row-aligned [N, ...] arrays or scalars —
    # shardable over the data axis for data-parallel chunked training
    rows_aligned_params = True
    def __init__(self, config):
        self._num_class = int(config.num_class)
        self.weights = None

    def init(self, metadata, num_data: int) -> None:
        label = np.asarray(metadata.label).astype(np.int32)
        if ((label < 0) | (label >= self._num_class)).any():
            log.fatal("Label must be in [0, %d)" % self._num_class)
        self.label_int = jnp.asarray(label)
        self.onehot = jnp.asarray(
            np.eye(self._num_class, dtype=np.float32)[label])  # [N, K]
        if metadata.weights is not None:
            self.weights = jnp.asarray(metadata.weights, jnp.float32)

    def get_gradients(self, score: jax.Array):
        """score layout [K, N]; softmax per row; grad = p − 1[y=k],
        hess = 2p(1−p) (multiclass_objective.hpp:37-75)."""
        return _multiclass_gradients(self.chunk_params(), score)

    def chunk_spec(self):
        return (("multiclass", self._num_class, self.weights is not None),
                self.chunk_params(), _multiclass_gradients)

    def chunk_params(self):
        return {"onehot": self.onehot, "weights": self.weights}

    def globalize(self, make_global) -> None:
        """Multi-process: lift row-aligned state to global sharded arrays."""
        self.label_int = make_global(self.label_int)
        self.onehot = make_global(self.onehot)
        if self.weights is not None:
            self.weights = make_global(self.weights)

    @property
    def sigmoid(self) -> float:
        return -1.0

    @property
    def num_class(self) -> int:
        return self._num_class


def _multiclass_gradients(params, score):
    # named_scope: profile_dir= traces label the gradient ops with the
    # objective (matches the telemetry "gradient" phase; ISSUE 2)
    with jax.named_scope("gradient_multiclass"):
        p = jax.nn.softmax(score.astype(jnp.float32), axis=0)  # [K, N]
        grad = p - params["onehot"].T
        hess = 2.0 * p * (1.0 - p)
        if params["weights"] is not None:
            grad = grad * params["weights"][None, :]
            hess = hess * params["weights"][None, :]
        return grad, hess
