"""Device mesh construction and multi-host bootstrap.

Replaces the reference's Linkers bootstrap
(/root/reference/src/network/linkers_socket.cpp:20-110: machine-list parse,
rank inference, TCP mesh) with jax.distributed + a 1-D
``jax.sharding.Mesh``.  A "machine" in the reference maps to a mesh slot
(one TPU device — or one device per host in multi-host runs); collective
traffic rides ICI/DCN via XLA instead of raw sockets.
"""
from __future__ import annotations

import os
from typing import Optional

import numpy as np
import jax
from jax.sharding import Mesh

from ..utils import log

DATA_AXIS = "data"
FEATURE_AXIS = "feature"
# serving-side tree-sharded ensembles (ISSUE 13): the [T, max_nodes] node
# tables shard along this axis, rows (codes) are replicated
TREE_AXIS = "tree"


def init_distributed(config=None) -> None:
    """Multi-host bootstrap (linkers_socket.cpp equivalent).

    Uses jax.distributed when coordinator env vars are present; single-host
    multi-device needs no bootstrap.  Must run before anything touches the
    XLA backend — so the already-initialized check reads the distributed
    client state directly instead of jax.process_count() (which would
    itself initialize the backend and make initialize() impossible).
    """
    from .. import hatches
    coordinator = hatches.raw("LGBM_TPU_COORDINATOR")
    if not coordinator:
        return
    try:
        # private probe — there is no public "is the distributed client
        # up?" API; tolerate its removal in future JAX versions
        from jax._src import distributed as _distributed
        if _distributed.global_state.client is not None:
            return
    except Exception:
        pass
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=hatches.int_value("LGBM_TPU_NUM_PROCS", 1),
            process_id=hatches.int_value("LGBM_TPU_PROC_ID", 0))
    except RuntimeError as e:
        # the public double-initialization signal ("distributed.initialize
        # should only be called once." in jax 0.9; older builds said
        # "already initialized"); anything else is a real bootstrap failure
        if not any(s in str(e).lower() for s in ("already", "once")):
            raise
    clock_handshake()


def clock_handshake() -> float:
    """Cross-host clock-offset handshake (ISSUE 5), recorded at mesh
    setup: every process allgathers its ``time.time()`` sample and
    installs the leader-relative offset into telemetry, so per-process
    JSONL shard timestamps can be merged onto ONE job clock by
    scripts/timeline_report.py (cross-host skew attribution is
    meaningless on uncorrected clocks).

    The offset is accurate to ~one collective round-trip (the gathered
    samples are taken within the allgather's skew window); the RTT is
    recorded beside it as the error bar.  COLLECTIVE — every process of
    a multi-process job reaches init_distributed, which calls it.
    Single-process runs (and backends without multi-process collectives)
    record offset 0.  Returns the installed offset."""
    import time as _time
    from .. import telemetry
    if jax.process_count() <= 1:
        telemetry.set_clock_offset(0.0)
        return 0.0
    try:
        from jax.experimental import multihost_utils
        t0 = _time.perf_counter()
        gathered = np.asarray(multihost_utils.process_allgather(
            np.asarray(_time.time(), np.float64))).reshape(-1)
        rtt = _time.perf_counter() - t0
        offset = float(gathered[0] - gathered[jax.process_index()])
        telemetry.set_clock_offset(offset, rtt_s=rtt)
        return offset
    except Exception as e:  # pragma: no cover - backend capability gap
        log.warning("clock handshake unavailable (%s); shard timestamps "
                    "stay on local clocks" % e)
        telemetry.set_clock_offset(0.0)
        return 0.0


def get_mesh(num_machines: Optional[int] = None,
             axis_name: str = DATA_AXIS,
             device_type: str = "") -> Mesh:
    """1-D mesh over the first ``num_machines`` devices.

    ``device_type`` (config.py device_type: "cpu"/"tpu"/"gpu") selects the
    backend to draw mesh slots from in mixed-backend processes; empty means
    the default platform.

    Multi-process runs use EVERY device of the distributed job (a
    "machine" in the reference maps to a process; each contributes all its
    local devices as mesh slots — jax.devices() is globally ordered by
    process index, which make_global_rows relies on)."""
    devices = jax.devices(device_type) if device_type else jax.devices()
    if jax.process_count() > 1:
        return Mesh(np.array(devices), (axis_name,))
    if num_machines is None or num_machines <= 0:
        num_machines = len(devices)
    if num_machines > len(devices):
        log.warning(
            "num_machines=%d exceeds available devices (%d); shrinking "
            "world size to match (linkers_socket.cpp:106-109 behavior)"
            % (num_machines, len(devices)))
        num_machines = len(devices)
    return Mesh(np.array(devices[:num_machines]), (axis_name,))


def factor_machines(num_machines: int, feature_shards: int = 0,
                    voting: bool = False) -> "tuple[int, int]":
    """Factor ``num_machines`` into ``(data_shards, feature_shards)`` for
    the 2-D hybrid mesh (ISSUE 9).

    ``feature_shards > 0`` (the config knob) is honored exactly and must
    divide num_machines (loud error otherwise — a silent re-factor would
    change the wire bytes the perf gate tracks).  ``feature_shards == 0``
    resolves automatically:

    - hybrid: the largest divisor of num_machines that is <= sqrt(
      num_machines) — rows get at least as many shards as features (the
      histogram's row dimension is the one that grows with data), e.g.
      4 -> (2, 2), 8 -> (4, 2), 6 -> (3, 2), primes -> (n, 1).
    - voting: (num_machines, 1) — the reference's voting design is pure
      data-parallel (top-k votes over row shards); feature sharding
      composes only when asked for explicitly.

    A factoring with feature_shards == 1 degenerates to pure data
    parallelism on the ``data`` axis (documented fallback: hybrid then
    records the same wire bytes as tree_learner=data/psum)."""
    n = max(int(num_machines), 1)
    if feature_shards > 0:
        if n % feature_shards:
            log.fatal("feature_shards=%d does not divide num_machines=%d"
                      % (feature_shards, n))
        return n // feature_shards, feature_shards
    if voting:
        return n, 1
    fs = 1
    for d in range(2, int(n ** 0.5) + 1):
        if n % d == 0:
            fs = d
    return n // fs, fs


def get_mesh2d(num_machines: Optional[int] = None,
               feature_shards: int = 0, device_type: str = "",
               voting: bool = False) -> Mesh:
    """Explicit 2-D ``(data, feature)`` mesh over the first
    ``num_machines`` devices (ISSUE 9): rows shard over the ``data``
    axis, feature-block ownership lives on the ``feature`` axis, so the
    histogram reduce (psum over ``data`` restricted to owned blocks) and
    the SplitInfo allreduce (over ``feature``) ride different axes of
    one mesh — the hybrid data x feature plan the reference names but
    never implements (SURVEY.md "Voting-parallel: named but absent").

    Multi-process hybrid runs are not supported in this revision: the
    row-shard lift (make_global_rows) assumes the 1-D process-ordered
    mesh — fail loudly instead of training on a wrong layout."""
    if jax.process_count() > 1:
        log.fatal("tree_learner=hybrid/voting is single-process in this "
                  "revision (multi-process keeps the 1-D data mesh)")
    devices = jax.devices(device_type) if device_type else jax.devices()
    if num_machines is None or num_machines <= 0:
        num_machines = len(devices)
    if num_machines > len(devices):
        log.warning(
            "num_machines=%d exceeds available devices (%d); shrinking "
            "world size to match (linkers_socket.cpp:106-109 behavior)"
            % (num_machines, len(devices)))
        num_machines = len(devices)
    ds, fs = factor_machines(num_machines, feature_shards, voting=voting)
    grid = np.array(devices[:ds * fs]).reshape(ds, fs)
    return Mesh(grid, (DATA_AXIS, FEATURE_AXIS))


def get_serving_mesh(shards: int, device_type: str = "") -> Mesh:
    """1-D ``("tree",)`` mesh over the first ``shards`` devices for the
    tree-sharded serving engine (ISSUE 13): ``FlatEnsemble``'s
    [T, max_nodes] node tables shard contiguously along the tree axis —
    each device's HBM holds ONLY its tree block, which is what lifts the
    multi-GB-ensemble regime — while the codes batch is replicated.

    Loud error when ``shards`` exceeds the available devices: a silent
    shrink (the training meshes' linkers_socket behavior) would change
    the documented shard layout AND the serve/tree_* wire bytes the
    telemetry interconnect block prices, mid-deployment."""
    devices = jax.devices(device_type) if device_type else jax.devices()
    shards = int(shards)
    if shards < 1:
        log.fatal("serve_shards must be >= 1 to build a serving mesh "
                  "(got %d)" % shards)
    if shards > len(devices):
        log.fatal("serve_shards=%d exceeds available devices (%d) — the "
                  "tree-sharded engine never silently shrinks its mesh"
                  % (shards, len(devices)))
    return Mesh(np.array(devices[:shards]), (TREE_AXIS,))


def dataset_row_sharding(num_rows: int, shard_rows: bool = False,
                         num_machines: Optional[int] = None,
                         device_type: str = "",
                         parallel_consumer: bool = False):
    """Explicit placement for a streamed ``[F, N]`` bin matrix (ISSUE 8):
    a NamedSharding over the ``(data,)`` mesh axis.

    ``shard_rows=True`` (a single-process data-parallel consumer) shards
    the row axis across the CONSUMING LEARNER's mesh — ``get_mesh(
    num_machines)``, the exact device set the learner's jit(shard_map)
    programs run over — when the row count divides it (their bins
    in_spec is ``P(None, 'data')``, so the shards are picked up in
    place).  A non-dividing row count, or ``parallel_consumer=True``
    without ``shard_rows`` (the single-process feature-parallel
    learner), commits the matrix REPLICATED on that same learner mesh:
    a committed array's device set must equal the consuming program's,
    so a one-device placement would make the learner's multi-device
    shard_map raise "incompatible devices".  Only the serial consumer
    (neither flag) gets the one-device ``(data,)`` mesh — still an
    explicit placement, and numerically identical to the resident
    loader's default-device array (a multi-device input would let GSPMD
    repartition the serial grower's reductions and break
    bit-identity)."""
    from jax.sharding import NamedSharding, PartitionSpec
    if shard_rows or parallel_consumer:
        mesh = get_mesh(num_machines, DATA_AXIS, device_type)
        num_devices = int(mesh.devices.size)
        if (shard_rows and num_devices > 1 and num_rows > 0
                and num_rows % num_devices == 0):
            return NamedSharding(mesh, PartitionSpec(None, DATA_AXIS))
        return NamedSharding(mesh, PartitionSpec())
    devices = jax.devices(device_type) if device_type else jax.devices()
    mesh = Mesh(np.array(devices[:1]), (DATA_AXIS,))
    return NamedSharding(mesh, PartitionSpec())


def get_rank() -> int:
    """Process rank for host-side data sharding (Network::rank)."""
    return jax.process_index()


def get_num_machines() -> int:
    return jax.process_count()


def global_row_layout(n_local: int):
    """Agree on a per-process padded row-block size for multi-host arrays.

    The reference's data-parallel mode gives each PROCESS an uneven random
    row shard (dataset.cpp:172-216); jax sharded arrays need equal
    per-device blocks, so every process pads its shard to the global max
    (rounded up to its local device count).  Returns (max_n, counts) with
    counts[p] = process p's true row count."""
    from jax.experimental import multihost_utils
    counts = multihost_utils.process_allgather(np.asarray(n_local))
    counts = np.atleast_1d(np.asarray(counts)).reshape(-1)
    d_local = jax.local_device_count()
    max_n = int(counts.max())
    max_n = -(-max_n // d_local) * d_local
    return max_n, counts


def make_global_rows(local, max_n: int, mesh: Mesh, row_axis: int = 0,
                     axis_name: str = DATA_AXIS):
    """One process's row shard -> the global row-sharded jax.Array.

    Pads ``local`` to ``max_n`` rows along ``row_axis`` and assembles the
    [P * max_n, ...] global array via
    ``jax.make_array_from_process_local_data`` — the glue between host
    shards and the shard_map programs (rows land on the owning process's
    devices; no cross-host transfer)."""
    from jax.sharding import NamedSharding, PartitionSpec
    local = np.asarray(local)
    pad = max_n - local.shape[row_axis]
    assert pad >= 0
    if pad:
        widths = [(0, 0)] * local.ndim
        widths[row_axis] = (0, pad)
        local = np.pad(local, widths)
    spec = [None] * local.ndim
    spec[row_axis] = axis_name
    sharding = NamedSharding(mesh, PartitionSpec(*spec))
    global_shape = list(local.shape)
    global_shape[row_axis] = max_n * jax.process_count()
    return jax.make_array_from_process_local_data(
        sharding, local, tuple(global_shape))


def gather_ragged_rows(local) -> np.ndarray:
    """Every process's host array, concatenated along axis 0 in process
    order — the host-side complement of make_global_rows for UNEVEN
    per-process lengths (row shards, per-query count vectors).  Used to
    rebuild GLOBAL metric metadata (labels/weights/query layout) on every
    process so distributed metric evaluation sees the same rows as a
    serial run (gbdt.cpp:225-259 evaluates every iteration in parallel
    mode too)."""
    from jax.experimental import multihost_utils
    local = np.asarray(local)
    lengths = np.asarray(multihost_utils.process_allgather(
        np.asarray(local.shape[0]))).reshape(-1)
    max_len = int(lengths.max())
    pad = max_len - local.shape[0]
    if pad:
        local = np.pad(local, [(0, pad)] + [(0, 0)] * (local.ndim - 1))
    full = np.asarray(multihost_utils.process_allgather(local))
    full = full.reshape((-1, max_len) + local.shape[1:])
    return np.concatenate([full[p, :int(lengths[p])]
                           for p in range(lengths.size)], axis=0)


def sync_up_by_min(value):
    """GlobalSyncUpByMin (application.cpp:275-302): align seeds/fractions to
    the global minimum across processes for deterministic distributed runs."""
    if jax.process_count() == 1:
        return value
    from jax.experimental import multihost_utils
    gathered = multihost_utils.process_allgather(np.asarray(value))
    return type(value)(np.min(gathered))
