"""Device mesh construction and multi-host bootstrap.

Replaces the reference's Linkers bootstrap
(/root/reference/src/network/linkers_socket.cpp:20-110: machine-list parse,
rank inference, TCP mesh) with jax.distributed + a 1-D
``jax.sharding.Mesh``.  A "machine" in the reference maps to a mesh slot
(one TPU device — or one device per host in multi-host runs); collective
traffic rides ICI/DCN via XLA instead of raw sockets.
"""
from __future__ import annotations

import os
from typing import Optional

import numpy as np
import jax
from jax.sharding import Mesh

from ..utils import log

DATA_AXIS = "data"
FEATURE_AXIS = "feature"


def init_distributed(config=None) -> None:
    """Multi-host bootstrap (linkers_socket.cpp equivalent).

    Uses jax.distributed when coordinator env vars are present; single-host
    multi-device needs no bootstrap.
    """
    coordinator = os.environ.get("LGBM_TPU_COORDINATOR")
    if coordinator and jax.process_count() == 1:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=int(os.environ.get("LGBM_TPU_NUM_PROCS", "1")),
            process_id=int(os.environ.get("LGBM_TPU_PROC_ID", "0")))


def get_mesh(num_machines: Optional[int] = None,
             axis_name: str = DATA_AXIS,
             device_type: str = "") -> Mesh:
    """1-D mesh over the first ``num_machines`` devices.

    ``device_type`` (config.py device_type: "cpu"/"tpu"/"gpu") selects the
    backend to draw mesh slots from in mixed-backend processes; empty means
    the default platform."""
    devices = jax.devices(device_type) if device_type else jax.devices()
    if num_machines is None or num_machines <= 0:
        num_machines = len(devices)
    if num_machines > len(devices):
        log.warning(
            "num_machines=%d exceeds available devices (%d); shrinking "
            "world size to match (linkers_socket.cpp:106-109 behavior)"
            % (num_machines, len(devices)))
        num_machines = len(devices)
    mesh = Mesh(np.array(devices[:num_machines]), (axis_name,))
    _mesh = mesh
    return mesh


def get_rank() -> int:
    """Process rank for host-side data sharding (Network::rank)."""
    return jax.process_index()


def get_num_machines() -> int:
    return jax.process_count()


def sync_up_by_min(value):
    """GlobalSyncUpByMin (application.cpp:275-302): align seeds/fractions to
    the global minimum across processes for deterministic distributed runs."""
    if jax.process_count() == 1:
        return value
    from jax.experimental import multihost_utils
    gathered = multihost_utils.process_allgather(np.asarray(value))
    return type(value)(np.min(gathered))
