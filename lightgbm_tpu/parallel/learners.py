"""Parallel tree learners over the device mesh.

Re-designs of /root/reference/src/treelearner/{data,feature}_parallel_tree_learner.cpp
with XLA collectives inside ``shard_map``:

- **data-parallel** (rows sharded over the ``data`` axis): every shard builds
  local histograms, a ``psum`` produces the identical global histograms on
  all shards, and the replicated split search yields bit-identical trees —
  the reference's invariant (data_parallel_tree_learner.cpp:237-243: every
  worker ends each split with the identical global best split) enforced by
  construction.  The reference's ReduceScatter+owned-feature-search+Allgather
  schedule (lines 135-235) is a bandwidth optimization of the same reduction;
  psum is its all-to-all equivalent on ICI.
- **feature-parallel** (feature ownership sharded over the ``feature`` axis,
  rows replicated): each shard histograms and searches ONLY its owned
  feature slice, then a packed SplitInfo argmax-allreduce picks the global
  winner (feature_parallel_tree_learner.cpp:46-79, SplitInfo::MaxReducer
  split_info.hpp:56-72: max gain, ties → smaller feature index); the split
  itself is applied locally on the replicated bin matrix.
- **hybrid** (ISSUE 9: rows sharded over ``data`` AND feature blocks owned
  over ``feature`` on one explicit 2-D mesh, ``num_machines = data_shards
  x feature_shards``): histograms build local-rows x owned-features, the
  reduction is a data-axis psum restricted to the owned block — per-shard
  wire bytes O(F·B / feature_shards) — and the SplitInfo allreduce rides
  the feature axis.
- **voting** (ISSUE 9: the reference NAMES this learner but Fatals on it,
  src/io/config.cpp:311-313 — the PV-tree design realized): per-shard
  top-k split voting, full histograms exchanged only for the <= 2·top_k
  globally-voted features — per-split wire bytes O(min(2k, F/fs)·B).

All four learners drive the ONE schedule-parameterized grower
(models/grower_unified.py): a growth policy (leafwise / depthwise /
leafcompact) plus a declarative SeamSchedule built here.
"""
from __future__ import annotations

import functools
from typing import Callable

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .. import telemetry
from ..models.grower_unified import (SeamSchedule, TreeArrays, _GrowState,
                                     grow_tree_unified)
from ..models.gbdt import _effective_num_leaves, _tuning_kwargs
from ..ops.split import (SplitResult, find_best_split,
                         per_feature_best_scores)
from ..io.binning import BinMapper
from ..utils import log
from .mesh import (DATA_AXIS, FEATURE_AXIS, factor_machines, get_mesh,
                   get_mesh2d)


def aggregate_telemetry() -> None:
    """Fold every host's kernel-route counters — and its peak-memory
    watermark — into the leader's registry (``allhosts/<name>`` counter
    keys; ``allhosts_peak_bytes_in_use`` in the memory block) so the
    leader's JSONL summary speaks for the whole job, not just process 0.
    Health anomaly totals ride the counters (``health/*``,
    health.HealthMonitor.apply_policy mirrors every anomaly there), so
    they aggregate with no extra machinery.

    COLLECTIVE: every multi-process run must call it on EVERY process
    (gbdt.run_training does, at end of training) — including processes
    with telemetry disabled, whose counters are simply empty; gating
    participation on local telemetry state would hang the enabled hosts
    in the allgather.  Hosts may also disagree on which counters exist (a
    per-process LGBM_TPU_NO_PALLAS trip, a warm persistent compile cache
    skipping recompiles), so each host ships its payload as a JSON blob
    in a fixed-size byte buffer and counters are summed BY NAME — a
    fixed-order value allgather would silently add other hosts' values to
    the wrong keys whenever key sets differ with equal cardinality.
    Memory peaks reduce by max (a watermark, not a flow).
    Single-process runs return immediately."""
    if jax.process_count() <= 1:
        return
    blob_cap = 1 << 14
    try:
        import json
        from jax.experimental import multihost_utils
        items = sorted(telemetry.counters().items())
        payload = {"c": dict(items),
                   "mem_peak": telemetry.mem_peak_bytes()}
        raw = json.dumps(payload).encode()
        while len(raw) > blob_cap and items:  # pragma: no cover - 100s of keys
            items = items[:len(items) // 2]
            payload["c"] = dict(items)
            raw = json.dumps(payload).encode()
            log.warning("telemetry counters exceed the %d-byte aggregation "
                        "buffer; cross-host sums cover only this host's "
                        "first %d keys" % (blob_cap, len(items)))
        buf = np.zeros(blob_cap, np.uint8)
        buf[:len(raw)] = np.frombuffer(raw, np.uint8)
        gathered = np.asarray(multihost_utils.process_allgather(buf))
        totals: dict = {}
        peak = 0
        for row in gathered:
            blob = json.loads(bytes(row).rstrip(b"\x00").decode() or "{}")
            for k, v in blob.get("c", {}).items():
                totals[k] = totals.get(k, 0) + int(v)
            peak = max(peak, int(blob.get("mem_peak", 0)))
        if telemetry.enabled():
            telemetry.merge_host_counters(totals)
            if peak:
                telemetry.merge_host_memory(peak)
    except Exception as e:  # pragma: no cover - collective failure
        log.warning("telemetry cross-host aggregation failed: %s" % e)

try:
    from jax import shard_map as _shard_map  # JAX >= 0.7 name

    def shard_map(f, mesh, in_specs, out_specs, check_rep=False):
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=check_rep)
except ImportError:  # pragma: no cover - older JAX
    from jax.experimental.shard_map import shard_map as _shard_map_old

    def shard_map(f, mesh, in_specs, out_specs, check_rep=False):
        return _shard_map_old(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=check_rep)


def allreduce_best_split(res: SplitResult, axis_name: str,
                         site: str = None, loop: int = 1,
                         phase: str = None) -> SplitResult:
    """SplitInfo::MaxReducer as an argmax allreduce (split_info.hpp:56-104):
    max gain wins; ties broken by the smaller (global) feature index.
    ``site`` files the traced collective in the telemetry wire-metrics
    registry (ISSUE 5) — payload is the packed SplitInfo struct."""
    if site is not None:
        telemetry.record_collective(site, "all_gather", axis_name,
                                    telemetry._tree_nbytes(res),
                                    loop=loop, phase=phase)
    stacked = jax.tree.map(lambda x: jax.lax.all_gather(x, axis_name), res)
    gain = stacked.gain
    max_gain = jnp.max(gain)
    is_max = (gain == max_gain) & jnp.isfinite(max_gain)
    feat_key = jnp.where(is_max, stacked.feature, jnp.int32(1 << 30))
    pick = jnp.argmin(feat_key)
    return jax.tree.map(lambda x: x[pick], stacked)


def ownership_finder(own_s, axis_name, site: str = None, loop: int = 1,
                     phase: str = None):
    """Owned-block split finder shared by the feature-parallel learner and
    the data-parallel reduce_scatter schedule: local FindBestThreshold over
    the owned feature block, block-local -> global feature remap, then the
    SplitInfo MaxReducer allreduce (split_info.hpp:56-104)."""
    def finder(hist, sg, sh, cnt, nb, fm, mind, minh):
        local = find_best_split(hist, sg, sh, cnt, nb, fm, mind, minh)
        local = local._replace(
            feature=own_s[local.feature].astype(jnp.int32))
        return allreduce_best_split(local, axis_name, site=site,
                                    loop=loop, phase=phase)
    return finder


def _owned_block(F: int, num_shards: int, axis_name: str):
    """Contiguous-feature-block ownership, the ONE home of the layout
    shared by every ownership schedule (dp reduce_scatter, hybrid,
    voting): ``(Fb, Fpad, ids)`` where ``Fb`` is the per-shard block
    width, ``Fpad`` the padded feature count, and ``ids()`` — called
    inside the traced shard context — returns ``(idx, ownok, own_s)``:
    this shard's global feature ids, their validity (padding blocks
    clamp to duplicates of feature F-1, masked out), and the clamped
    gather indices."""
    Fb = -(-F // num_shards)
    Fpad = Fb * num_shards

    def ids():
        rank = jax.lax.axis_index(axis_name)
        idx = rank * Fb + jnp.arange(Fb, dtype=jnp.int32)
        return idx, idx < F, jnp.minimum(idx, F - 1)
    return Fb, Fpad, ids


def dp_ownership_seams(F: int, num_shards: int, site_prefix: str = "dp_rs",
                       loop: int = 1, phase: str = "grow",
                       root_loop: int = 1):
    """Contiguous-feature-block ownership seams for the data-parallel
    reduce_scatter schedule (data_parallel_tree_learner.cpp:135-235),
    shared by the masked and COMPACTED leaf-wise shard closures: returns
    a traced-context function (fmask, nbins) ->
    (fmask_own, nbins_own, SeamSchedule) — the owned mask/bin slices to
    pass positionally plus the declarative schedule for
    grow_tree_unified (models/grower_unified.py).

    ``site_prefix``/``loop``/``phase`` label the wire-metrics sites
    (telemetry.collective_span, ISSUE 5): per-split seams run inside the
    grower's split loop, so the caller passes its executed-calls-per-
    trace estimate as ``loop`` (e.g. num_leaves-1 for the leaf-wise
    fori_loop, x chunk length on the fused path)."""
    Fb, Fpad, block_ids = _owned_block(F, num_shards, DATA_AXIS)
    _c = functools.partial(telemetry.collective_span, axis=DATA_AXIS,
                           phase=phase)

    def seams(fmask, nbins):
        idx, ownok, own_s = block_ids()
        rank = jax.lax.axis_index(DATA_AXIS)

        def pad_f(x):
            if Fpad == F:
                return x
            widths = [(0, 0)] * x.ndim
            widths[0] = (0, Fpad - F)
            return jnp.pad(x, widths)

        def scatter0(h):
            # per-split [F, B, ...] histogram (f32) or [F, B, lanes]
            # INT accumulator — both carry features on axis 0
            return jax.lax.psum_scatter(
                pad_f(h), DATA_AXIS, scatter_dimension=0, tiled=True)

        def own_slice(h):
            # replicated full root histogram -> this shard's block
            return jax.lax.dynamic_slice_in_dim(
                pad_f(h), rank * Fb, Fb, axis=0)

        scat = _c(site_prefix + "/hist_scatter", scatter0,
                  kind="psum_scatter", loop=loop)
        schedule = SeamSchedule(
            hist_axis=DATA_AXIS,
            hist_reduce=scat, int_hist_reduce=scat,
            stat_reduce=_c(site_prefix + "/root_stats",
                           lambda s: jax.lax.psum(s, DATA_AXIS),
                           kind="psum", loop=root_loop),
            root_hist_reduce=_c(site_prefix + "/root_hist",
                                lambda h: jax.lax.psum(h, DATA_AXIS),
                                kind="psum", loop=root_loop),
            own_slice=own_slice,
            split_finder=ownership_finder(
                own_s, DATA_AXIS, site=site_prefix + "/splitinfo_allreduce",
                loop=loop, phase=phase))
        return fmask[own_s] & ownok, jnp.take(nbins, own_s), schedule
    return seams


def hybrid_ownership_seams(F: int, feature_shards: int, site_prefix: str,
                           loop: int = 1, phase: str = "grow",
                           root_loop: int = 1, slice_hist: bool = False,
                           pack=None):
    """``dp_ownership_seams`` generalized to the 2-D ``(data, feature)``
    mesh (ISSUE 9): contiguous feature-block ownership lives on the
    FEATURE axis and the histogram reduction runs over the DATA axis,
    RESTRICTED to the owned block — per-shard wire bytes drop from
    O(F·B) to O(F·B / feature_shards).  The split search runs on owned
    features and the packed SplitInfo allreduce rides the feature axis.

    ``slice_hist=False``: the caller pre-slices ``bins`` to the owned
    block (local-rows × owned-features histogram compute — the hybrid
    plan's compute saving), so the hist seam is a plain data-axis psum.
    ``slice_hist=True`` (the compact pane keeps all F features): local
    histograms are full-F and the seam cuts the owned block out BEFORE
    the psum, so the wire still carries only the block.

    ``pack`` (io/binning.BlockedPackSpec, masked closures only): the
    block-local mixed-bin layout — the owned slice's histogram rows are
    then in PACKED (bin-width-class) order, and the split finder gathers
    them back to canonical block order before the search, so split
    results, argmax tie-breaks and the packed-SplitInfo allreduce are
    bit-identical to the uniform layout.  The psum seams ride unchanged:
    the permutation never crosses the block boundary, so the reduced
    payload is the same feature set either way.  The compact closures
    (``slice_hist=True``) pass ``pack=None`` — their histograms assemble
    canonically inside the histogram op (global blocked ranges).

    Returns a traced-context fn (fmask, nbins) ->
    (own_s, fmask_own, nbins_own, SeamSchedule)."""
    Fb, Fpad, block_ids = _owned_block(F, feature_shards, FEATURE_AXIS)
    _c = functools.partial(telemetry.collective_span, axis=DATA_AXIS,
                           phase=phase)

    def seams(fmask, nbins):
        idx, ownok, own_s = block_ids()
        rank = jax.lax.axis_index(FEATURE_AXIS)

        def own_block(x):
            if Fpad == F:
                return jax.lax.dynamic_slice_in_dim(x, rank * Fb, Fb,
                                                    axis=0)
            widths = [(0, 0)] * x.ndim
            widths[0] = (0, Fpad - F)
            return jax.lax.dynamic_slice_in_dim(jnp.pad(x, widths),
                                                rank * Fb, Fb, axis=0)

        if slice_hist:
            hist_reduce = _c(site_prefix + "/own_block_allreduce",
                             lambda h: jax.lax.psum(own_block(h),
                                                    DATA_AXIS),
                             kind="psum", loop=loop)
            # int accumulators ([F, B, lanes], features on axis 0) slice
            # identically, keeping the int-domain exactness chain
            int_hist_reduce = _c(site_prefix + "/own_block_int_allreduce",
                                 lambda a: jax.lax.psum(own_block(a),
                                                        DATA_AXIS),
                                 kind="psum", loop=loop)
            root_hist_reduce = _c(site_prefix + "/root_hist",
                                  lambda h: jax.lax.psum(h, DATA_AXIS),
                                  kind="psum", loop=root_loop)
            own_slice = own_block
        else:
            hist_reduce = _c(site_prefix + "/hist_allreduce",
                             lambda h: jax.lax.psum(h, DATA_AXIS),
                             kind="psum", loop=loop)
            # the quantized path's INT accumulators ride build_histogram's
            # internal default data-axis psum (axis_name=DATA_AXIS); the
            # leaf-wise policies' ONE root exchange files at its own
            # root_loop site (wire-metrics accuracy, values identical)
            int_hist_reduce = None
            root_hist_reduce = _c(site_prefix + "/root_hist",
                                  lambda h: jax.lax.psum(h, DATA_AXIS),
                                  kind="psum", loop=root_loop)
            own_slice = None
        schedule = SeamSchedule(
            hist_axis=DATA_AXIS,
            hist_reduce=hist_reduce, int_hist_reduce=int_hist_reduce,
            stat_reduce=_c(site_prefix + "/root_stats",
                           lambda st: jax.lax.psum(st, DATA_AXIS),
                           kind="psum", loop=root_loop),
            root_hist_reduce=root_hist_reduce, own_slice=own_slice,
            hist_feat_gather=_block_feat_gather(pack, own_s, rank, Fb),
            split_finder=ownership_finder(
                own_s, FEATURE_AXIS,
                site=site_prefix + "/splitinfo_allreduce", loop=loop,
                phase=phase))
        return own_s, fmask[own_s] & ownok, jnp.take(nbins, own_s), schedule
    return seams


def _block_feat_gather(pack, own_s, rank, Fb: int):
    """The grower's ``hist_feat_gather`` seam for a block-locally PACKED
    owned slice (io/binning.BlockedPackSpec): TRACED [Fb] indices mapping
    canonical block position -> within-block storage position, handed to
    every histogram build (ops/histogram feat_gather) so the kernels
    restore canonical order IN THE INT DOMAIN (before dequantize/psum)
    — the hist cache, int8-derived root stats, sibling subtraction and
    split search are then all canonical, and the f32 graph downstream is
    shape-identical to the uniform layout's, so packed-vs-uniform stays
    bit-identical including argmax tie-breaks and XLA FMA-contraction
    choices.  Derived from the shard's rank against the global
    canonical->storage map, so the SPMD program is shard-uniform even
    though each block's inner permutation differs.  None when ``pack``
    is None (uniform layout).  Padding lanes clamp; they are masked out
    of the search by fmask_own & ownok either way."""
    if pack is None:
        return None
    c2p = jnp.asarray(pack.c2p, jnp.int32)
    return jnp.clip(jnp.take(c2p, own_s) - rank * Fb, 0, Fb - 1)


def voting_seams(F: int, feature_shards: int, top_k: int, int8: bool,
                 site_prefix: str, loop: int = 1, phase: str = "grow",
                 root_loop: int = 1, lanes: int = 1, pack=None):
    """Voting-parallel seams (ISSUE 9) — the reference NAMES this learner
    but Fatals on it (src/io/config.cpp:311-313); this realizes the
    PV-tree design on the 2-D mesh's data axis:

    1. every data shard histograms ALL its owned-block features over its
       LOCAL rows (caches stay local; parent-minus-smaller subtraction
       is exact locally),
    2. each shard proposes its top-k features by local split gain — the
       vote allgather moves k int32s, not histograms,
    3. full histograms are psum'd over the data axis ONLY for the
       <= 2·top_k globally-voted features (votes desc, feature id asc,
       deterministic), so the per-split exchange drops from
       O(F·B / feature_shards) to O(min(2k, F/fs)·B),
    4. the owned-block winner joins the packed SplitInfo allreduce over
       the feature axis, exactly like the hybrid schedule.

    Voting is exact whenever the voted set covers the true best feature
    — guaranteed when 2·top_k >= the owned block width (the voted set is
    then the whole block and the schedule degenerates to hybrid's),
    PV-tree's accuracy argument otherwise.

    int8: the quantized path's int accumulators ride build_histogram's
    internal data-axis psum UNREDUCED exactness chain (local caches
    would break the int-domain bit-identity guarantee), so int8 voting
    restricts only the SEARCH, not the exchange — the wire saving
    applies to the f32/bfloat16 paths; documented in PROFILE.md.

    Wire accounting: the voted exchange rides the FINDER, which the
    leaf-wise policies run once per CHILD (no subtraction trick is
    possible across distinct voted sets), so the per-split leaf-wise
    exchange is 2·min(2k, Fb)·B·3·4 bytes and voting beats hybrid's
    single Fb-block psum only when 4k < F/fs.  ``loop``/``root_loop``
    are the executed-calls estimates for the body and root finder
    variants; ``lanes`` scales recorded bytes when the caller batches
    the finder with jax.vmap (the compact pair call: the collective
    moves every lane but the tracer only sees one lane's shape —
    depthwise's per-level slot-vmapped finder has no static lane count,
    so its voting est undercounts; the gated smoke rides leaf-wise
    where est == executed)."""
    Fb, Fpad, block_ids = _owned_block(F, feature_shards, FEATURE_AXIS)
    k = min(top_k, Fb)
    V = min(2 * top_k, Fb)
    _c = functools.partial(telemetry.collective_span, axis=DATA_AXIS,
                           phase=phase)

    def seams(fmask, nbins):
        idx, ownok, own_s = block_ids()
        # block-local mixed-bin layout (the masked closures pre-slice
        # ``bins`` in packed storage order): the histogram kernels gather
        # the accumulators back to canonical block order in the int
        # domain (_block_feat_gather), so the vote scoring, tie-breaks
        # and exchanged payloads below match the uniform layout bit for
        # bit
        feat_gather = _block_feat_gather(
            pack, own_s,
            jax.lax.axis_index(FEATURE_AXIS) if pack is not None else 0,
            Fb)

        def make_finder(tag, loop_est, lane_scale):
          # tag distinguishes the root sites: a telemetry site carries ONE
          # executed-calls loop estimate, so the root finder (1 execution)
          # and the per-split body finder cannot share site names
          def finder(hist, sg, sh, cnt, nb, fm, mind, minh):
            # hist: [Fb, B, 3] when the caller pre-sliced ``bins`` to the
            # owned block (the masked policies — histogram compute and
            # cache never touch un-owned features), else [F, B, 3] local
            # full-F (the compact pane keeps all features for the
            # partition; int8: already int-psum'd global) — static
            # shapes, so the slice resolves at trace time
            if hist.shape[0] == Fb:
                hist_own, nb_own, fm_own = hist, nb, fm
            else:
                hist_own = jnp.take(hist, own_s, axis=0)
                nb_own = jnp.take(nb, own_s)
                fm_own = fm[own_s] & ownok
            # 1. local per-feature best gains over the owned block.  The
            # leaf totals for the vote scoring come from the HISTOGRAM
            # ITSELF (any one feature's bins sum to the leaf's rows), not
            # the carried sg/sh/cnt: in f32 the histogram is shard-LOCAL
            # while sg/sh/cnt are global, and mixing them skews every
            # right-child stat by ~the other shards' mass — worse, a leaf
            # whose LOCAL row count falls below min_data_in_leaf would
            # score every feature -inf and the vote would silently
            # degenerate to the lowest feature ids.  PV-tree votes on
            # local evidence: local left/right sums against local totals.
            # (int8: hist is already global, so the bin sums are the
            # global totals and the vote ranking matches a global scorer.)
            tot = jnp.sum(hist_own[0], axis=0)           # [3] g, h, count
            scores = per_feature_best_scores(hist_own, tot[0], tot[1],
                                             tot[2], nb_own, fm_own,
                                             mind, minh)
            # 2. top-k vote (argsort is stable: gain ties resolve to the
            # smaller feature id, matching SplitInfo::MaxReducer)
            order = jnp.argsort(-scores)
            top_local = order[:k]
            top_ids = jnp.where(jnp.isfinite(scores[top_local]),
                                idx[top_local], jnp.int32(Fpad))
            telemetry.record_collective(
                site_prefix + "/%svotes_allgather" % tag, "all_gather",
                DATA_AXIS, telemetry._tree_nbytes(top_ids) * lane_scale,
                loop=loop_est, phase=phase)
            votes = jax.lax.all_gather(top_ids, DATA_AXIS)     # [ds, k]
            # 3. voted set: top-V features by vote count (stable argsort
            # → ties by smaller id), exchanged in ascending feature order
            counts = jnp.sum(votes.reshape(-1)[None, :] == idx[:, None],
                             axis=1)
            voted = jnp.sort(jnp.argsort(-counts)[:V])
            vh = jnp.take(hist_own, voted, axis=0)             # [V, B, 3]
            if not int8:
                telemetry.record_collective(
                    site_prefix + "/%svoted_hist_allreduce" % tag, "psum",
                    DATA_AXIS, telemetry._tree_nbytes(vh) * lane_scale,
                    loop=loop_est, phase=phase)
                vh = jax.lax.psum(vh, DATA_AXIS)
            # 4. owned-block search over the voted set only, then the
            # packed SplitInfo allreduce across feature blocks
            local = find_best_split(vh, sg, sh, cnt,
                                    jnp.take(nb_own, voted),
                                    fm_own[voted], mind, minh)
            gid = jnp.take(own_s, voted)[local.feature]
            local = local._replace(feature=gid.astype(jnp.int32))
            return allreduce_best_split(
                local, FEATURE_AXIS,
                site=site_prefix + "/%ssplitinfo_allreduce" % tag,
                loop=loop_est, phase=phase)
          return finder

        return SeamSchedule(
            hist_axis=DATA_AXIS,
            stat_reduce=_c(site_prefix + "/root_stats",
                           lambda st: jax.lax.psum(st, DATA_AXIS),
                           kind="psum", loop=root_loop),
            hist_feat_gather=feat_gather,
            split_finder=make_finder("", loop, lanes),
            # the ONE root search files its exchange on root_-tagged
            # sites at root_loop (the body finder traces inside the
            # split loop and carries its per-split estimate)
            root_split_finder=make_finder("root_", root_loop, 1),
            # f32/bf16 caches stay local (the voted exchange lives in the
            # finder); int8's internal int-psum makes them global already
            hist_local=not int8)
    return seams


def _tree_out_specs(data_axis=None):
    """TreeArrays out_specs: everything replicated except the row-sharded
    leaf-id vector."""
    return TreeArrays(
        num_leaves=P(), split_feature=P(), threshold_bin=P(), split_gain=P(),
        left_child=P(), right_child=P(), leaf_parent=P(), leaf_value=P(),
        leaf_count=P(), leaf_ids=P(data_axis))


def create_parallel_learner(config) -> Callable:
    """TreeLearner::CreateTreeLearner (tree_learner.cpp:8-17) for the
    parallel variants; returns a callable with the GBDT learner contract."""
    kind = config.boosting_config.tree_learner
    if kind == "data":
        return DataParallelLearner(config)
    if kind == "feature":
        return FeatureParallelLearner(config)
    if kind == "hybrid":
        return HybridLearner(config)
    if kind == "voting":
        return VotingLearner(config)
    log.fatal("Tree learner type error")


class _ParallelLearnerBase:
    def __init__(self, config):
        self.config = config
        self.tree_config = config.boosting_config.tree_config
        self._jitted = None

    def _grow_kwargs(self, gbdt):
        return dict(
            num_leaves=_effective_num_leaves(self.tree_config),
            num_bins_max=gbdt.num_bins_max,
            min_data_in_leaf=self.tree_config.min_data_in_leaf,
            min_sum_hessian_in_leaf=self.tree_config.min_sum_hessian_in_leaf,
            max_depth=self.tree_config.max_depth,
            # mixed-bin layout spec (None for the feature-parallel
            # learner — gbdt.init resolves packing off there).  The
            # per-class histograms reassemble into canonical feature
            # order BEFORE any reduction, so the ownership psum_scatter
            # and owned-slice seams below ride unchanged.
            packing=getattr(gbdt, "_pack_spec", None),
            **_tuning_kwargs(self.tree_config.hist_chunk,
                             self.tree_config.hist_dtype,
                             self.tree_config.quant_rounding))

    @property
    def _depthwise(self) -> bool:
        return self.tree_config.grow_policy == "depthwise"


# Compiled data-parallel k-iteration chunk programs, shared process-wide
# (keyed on static config only, like models/gbdt._CHUNK_PROGRAMS).
_DP_CHUNK_PROGRAMS: dict = {}


class DataParallelLearner(_ParallelLearnerBase):
    """Rows sharded; histograms psum'd (data_parallel_tree_learner.cpp).

    Two histogram-reduction schedules (tree_config.dp_schedule):

    - ``psum`` (default): full-histogram allreduce + replicated split
      search — the all-to-all equivalent of the reference's reduction,
      simplest and proven.
    - ``reduce_scatter``: the reference's bandwidth-optimal ownership
      schedule (data_parallel_tree_learner.cpp:135-235) as XLA
      collectives — psum_scatter the level histograms by contiguous
      feature block, search only owned features, allreduce the packed
      SplitInfo (SplitInfo::MaxReducer semantics).  Halves the collective
      bytes per level and divides split-search compute by the shard
      count; trees are identical (bit-identical under int8)."""

    def _schedule(self) -> str:
        """Resolve dp_schedule: 'auto' (the config default) follows the
        reference — its N-machine data-parallel mode IS the ReduceScatter
        ownership schedule (data_parallel_tree_learner.cpp:135-235) — so
        true multi-process runs default to reduce_scatter, while
        single-process meshes keep psum (simplest, measured equivalent at
        small shard counts, PROFILE.md)."""
        s = getattr(self.tree_config, "dp_schedule", "psum")
        if s == "auto":
            return ("reduce_scatter" if jax.process_count() > 1
                    else "psum")
        return s

    def _mesh(self):
        """The learner's device mesh — the 1-D ``(data,)`` mesh here;
        the 2-D hybrid subclass overrides with ``(data, feature)``."""
        return get_mesh(self.config.network_config.num_machines, DATA_AXIS,
                        getattr(self.config, 'device_type', ''))

    def _key_extra(self) -> tuple:
        """Extra chunk/jit cache-key components (the hybrid subclass adds
        its mesh factoring and voting knobs)."""
        return ()

    def _scatter_grow_fn_leafwise(self, kwargs, F: int, num_shards: int):
        """Per-shard leaf-wise grow closure for the reduce_scatter
        ownership schedule: every histogram (smaller child per split) is
        psum_scatter'd by contiguous feature block — int domain for the
        quantized path — the hist cache holds only the owned block, the
        split search runs on owned features, and the packed SplitInfo
        allreduce picks the global winner.  This is the reference's
        N-machine mode in its native growth order
        (data_parallel_tree_learner.cpp:135-235 driving
        serial_tree_learner.cpp:119-153)."""
        # per-split seams run in the grower's fori_loop: traced once,
        # executed once per split (wire-metrics loop estimate)
        seams = dp_ownership_seams(F, num_shards,
                                   site_prefix="dp_rs/leafwise",
                                   loop=kwargs["num_leaves"] - 1)

        def shard_grow(bins_s, grad_s, hess_s, mask_s, fmask, nbins,
                       **extra):
            fmask_own, nbins_own, schedule = seams(fmask, nbins)
            return grow_tree_unified(
                bins_s, grad_s, hess_s, mask_s, fmask_own, nbins_own,
                policy="leafwise", schedule=schedule,
                partition_bins=bins_s, **kwargs, **extra)
        return shard_grow

    def _scatter_grow_fn(self, kwargs, F: int, num_shards: int,
                         phase: str = "train_chunk", loop_scale: int = 1):
        """Per-shard DEPTHWISE grow closure for the reduce_scatter
        schedule.  ``loop_scale`` multiplies the wire-metrics
        executed-calls estimate (the fused chunk traces once, executes k
        times)."""
        Fb, Fpad, block_ids = _owned_block(F, num_shards, DATA_AXIS)
        _c = functools.partial(telemetry.collective_span, axis=DATA_AXIS,
                               phase=phase, loop=loop_scale)

        def shard_grow(bins_s, grad_s, hess_s, mask_s, fmask, nbins):
            idx, ownok, own_s = block_ids()
            rank = jax.lax.axis_index(DATA_AXIS)
            fmask_own = fmask[own_s] & ownok
            nbins_own = jnp.take(nbins, own_s)

            def pad_f(x, axis):
                if Fpad == F:
                    return x
                widths = [(0, 0)] * x.ndim
                widths[axis] = (0, Fpad - F)
                return jnp.pad(x, widths)

            def int_reduce(acc):
                # INT accumulators, feature axis 0 — int-domain scatter
                # keeps the serial == distributed bit-exactness chain
                return jax.lax.psum_scatter(
                    pad_f(acc, 0), DATA_AXIS, scatter_dimension=0,
                    tiled=True)

            def hist_scatter(h):
                # f32 [C, F, B, 3] level histogram, feature axis 1
                return jax.lax.psum_scatter(
                    pad_f(h, 1), DATA_AXIS, scatter_dimension=1, tiled=True)

            def own_slice(h):
                # replicated full root histogram -> this shard's block
                return jax.lax.dynamic_slice_in_dim(
                    pad_f(h, 1), rank * Fb, Fb, axis=1)

            schedule = SeamSchedule(
                hist_axis=DATA_AXIS,
                hist_reduce=_c("dp_rs/depthwise/root_hist",
                               lambda h: jax.lax.psum(h, DATA_AXIS),
                               kind="psum"),
                stat_reduce=_c("dp_rs/depthwise/root_stats",
                               lambda s: jax.lax.psum(s, DATA_AXIS),
                               kind="psum"),
                split_finder=ownership_finder(
                    own_s, DATA_AXIS,
                    site="dp_rs/depthwise/splitinfo_allreduce",
                    loop=loop_scale, phase=phase),
                hist_reduce_level=_c("dp_rs/depthwise/level_hist_scatter",
                                     hist_scatter, kind="psum_scatter"),
                int_reduce_level=_c("dp_rs/depthwise/level_int_scatter",
                                    int_reduce, kind="psum_scatter"),
                own_slice=own_slice)
            return grow_tree_unified(
                bins_s, grad_s, hess_s, mask_s, fmask_own, nbins_own,
                policy="depthwise", schedule=schedule, **kwargs)
        return shard_grow

    def chunk_program(self, gbdt, obj_key, grad_fn, obj_params,
                      has_bag: bool, has_ff: bool,
                      train_metric_fns=(), valid_metric_fns=(),
                      n_valid: int = 0, shard_layout=None,
                      needs_global_score: bool = False,
                      health: bool = False, goss=None):
        """Fused k-iteration training program under shard_map: the whole
        gradients → grow(psum'd histograms) → score-update scan runs sharded
        over the mesh, one dispatch per chunk (the data-parallel analog of
        models/gbdt._get_chunk_program), INCLUDING in-program metric
        evaluation: train metrics see the all_gathered global score (the
        reference evaluates metrics every iteration in parallel mode too,
        gbdt.cpp:225-259 — here AUC's global sort runs on the gathered
        scores inside every shard), and validation sets ride replicated
        (each shard replays trees on the full valid bins; identical values
        on all shards).

        Returns (program, num_shards).  The caller pads rows to a multiple
        of num_shards and passes ``valid_rows`` (False on padding) so padded
        rows never enter histograms, root stats or gathered-score metrics
        (metric fns slice to the true row count).  The program's call/return
        contract matches the serial chunk program:
        (score, bins, num_bins, valid_rows, row_masks, feat_masks,
        obj_params, train_mparams, valid_bins, valid_scores, valid_mparams)
        -> (score, vscores, stacked_trees, mvals)."""
        mesh = self._mesh()
        num_shards = mesh.shape[DATA_AXIS]
        num_class = gbdt.num_class
        lr = float(gbdt.gbdt_config.learning_rate)
        kwargs = self._grow_kwargs(gbdt)
        depthwise = self._depthwise
        n_true = gbdt.num_data
        max_nodes = max(_effective_num_leaves(self.tree_config) - 1, 1)
        # reduce_scatter in the fused depthwise chunk; the leaf-wise
        # per-iteration path has its own scatter closure (__call__)
        use_scatter = self._schedule() == "reduce_scatter" and depthwise
        # the compacted grower covers BOTH schedules (_compact_grow_fn
        # dispatches): no masked-grower fall-through under reduce_scatter
        use_compact = not depthwise and self._leafwise_compact_enabled()
        num_features = gbdt.num_features
        # in-program health vector: local reductions + psum/pmax over the
        # data axis, so every shard carries the identical global vector
        # (lightgbm_tpu/health.py; the [8] extra output rides replicated)
        health_fn = None
        if health:
            from ..health import make_health_fn
            health_fn = make_health_fn(
                self.tree_config.hist_dtype == "int8", DATA_AXIS)
        # the RESOLVED pallas-partition and DMA-overlap bits and the
        # backend/device identity are part of the program key:
        # __graft_entry__ flips LGBM_TPU_NO_PALLAS mid-process (and
        # steers onto virtual CPU meshes), PROFILE.md's A/B flips
        # LGBM_TPU_PARTITION_NO_OVERLAP, and a stale program would keep
        # the old kernel routing either way
        from ..ops.compact import pallas_partition_ok, partition_overlap_on
        use_pp = use_compact and pallas_partition_ok(num_features)
        key = (obj_key, id(grad_fn), num_shards, num_class, lr, depthwise,
               tuple(sorted(kwargs.items())), has_bag, has_ff, n_true,
               shard_layout, needs_global_score, use_scatter, use_compact,
               goss, self._schedule(), use_pp,
               use_pp and partition_overlap_on(), jax.default_backend(),
               getattr(self.config, 'device_type', ''),
               num_features, bool(health), self._key_extra(),
               tuple(id(f) for f in train_metric_fns),
               tuple(tuple(id(f) for f in fns) for fns in valid_metric_fns))
        prog = _DP_CHUNK_PROGRAMS.get(key)
        if prog is not None:
            return prog, num_shards

        lrf = jnp.float32(lr)
        # wire-metrics loop estimate: the scan body traces ONCE but runs k
        # times per chunk; shard_chunk fills in k (row_masks.shape[0])
        # before anything inside the body is traced
        chunk_k = [1]

        def _gather_compact(vec, site):
            """all_gather row-aligned values over the data axis and
            compact out the per-process padding — the ONE home of the
            padded-global -> true-row rule (the in-program train metrics
            AND the in-chunk GOSS row scores both ride it).
            Single-process runs pad only at the tail (slice to n_true);
            multi-process runs pad each process block, so the static
            shard_layout ((start, len) per process) concatenates the
            true row ranges in process order — matching the order the
            global metric metadata was gathered in (gbdt.init).
            Returns ``(compacted, padded_row_count)``."""
            telemetry.record_collective(
                site, "all_gather", DATA_AXIS,
                telemetry._tree_nbytes(vec), loop=chunk_k[0],
                phase="train_chunk")
            full = jax.lax.all_gather(vec, DATA_AXIS, axis=-1, tiled=True)
            if shard_layout is None:
                return full[..., :n_true], full.shape[-1]
            return jnp.concatenate(
                [jax.lax.slice_in_dim(full, st, st + ln, axis=-1)
                 for st, ln in shard_layout], axis=-1), full.shape[-1]

        def gathered(f):
            # train metrics need the GLOBAL score
            def g(p, s):
                comp, _ = _gather_compact(s, "dp/metric_score_allgather")
                return f(p, comp)
            return g

        train_fns = tuple(gathered(f) for f in train_metric_fns)

        goss_fn = None
        if goss is not None:
            # in-chunk GOSS on the data-sharded layout (ISSUE 12): the
            # per-row |grad| scores are all_gathered over the data axis,
            # the draw runs on the COMPACTED true-row layout (exactly
            # the serial/per-iteration selection — same key, same row
            # count, bit-identical), and each shard slices its own
            # rows' mask/weights back out.  Selection is a pure function
            # of the globally-identical gradients, so every shard — and
            # every process in a multi-process job — computes the
            # identical selection.
            g_seed, g_top, g_other, g_amp = goss
            from ..ops import sampling as _sampling

            def goss_fn(it, grad, hess):
                absg = _sampling.goss_row_scores(grad)       # [n_local]
                absg_true, n_pad = _gather_compact(
                    absg, "dp/goss_score_allgather")

                def expand(vec_true, fill):
                    # compacted true-row vector -> padded global layout
                    if shard_layout is None:
                        return jnp.pad(vec_true, (0, n_pad - n_true),
                                       constant_values=fill)
                    pm = np.full(n_pad, n_true, np.int32)
                    off = 0
                    for st, ln in shard_layout:
                        pm[st:st + ln] = off + np.arange(ln)
                        off += ln
                    ext = jnp.concatenate(
                        [vec_true,
                         jnp.full((1,), fill, vec_true.dtype)])
                    return jnp.take(ext, jnp.asarray(pm))

                key = jax.random.fold_in(jax.random.PRNGKey(g_seed), it)
                mask_t, w_t = _sampling.goss_mask_weights(
                    key, absg_true, g_top, g_other, g_amp)
                mask_pad = expand(mask_t, False)
                w_pad = expand(w_t, 1.0)
                rows = grad.shape[-1]
                i = jax.lax.axis_index(DATA_AXIS)
                msl = jax.lax.dynamic_slice_in_dim(mask_pad, i * rows,
                                                   rows)
                wsl = jax.lax.dynamic_slice_in_dim(w_pad, i * rows, rows)
                return grad * wsl, hess * wsl, msl

        if needs_global_score:
            # global-score objectives (lambdarank): pairwise lambdas need
            # every row of every query, and only PROCESS shards are
            # query-atomic — device-level row blocks cut queries.  Gather
            # the score shards (same collective the in-program train
            # metrics ride), compute the full lambda vector replicated,
            # and slice this shard's rows back out.  The reference's
            # per-machine formulation (rank_objective.hpp:68-192) is the
            # compute-distributed special case; this stays exact under any
            # row blocking.
            base_grad_fn = grad_fn

            def grad_fn(params, score):
                telemetry.record_collective(
                    "dp/grad_score_allgather", "all_gather", DATA_AXIS,
                    telemetry._tree_nbytes(score), loop=chunk_k[0],
                    phase="train_chunk")
                full = jax.lax.all_gather(score, DATA_AXIS, axis=-1,
                                          tiled=True)
                g, h = base_grad_fn(params, full)
                rows = score.shape[-1]
                i = jax.lax.axis_index(DATA_AXIS)
                sl = functools.partial(
                    jax.lax.dynamic_slice_in_dim,
                    start_index=i * rows, slice_size=rows, axis=-1)
                return sl(g), sl(h)

        def shard_chunk(score, bins, num_bins, valid_rows, row_masks,
                        feat_masks, obj_params, train_mparams, valid_bins,
                        valid_scores, valid_mparams, goss_iters=None):
            from ..models.gbdt import make_chunk_body
            chunk_k[0] = int(row_masks.shape[0])
            grow_fn = self._chunk_grow_fn(kwargs, num_features, num_shards,
                                          depthwise, use_compact,
                                          use_scatter, chunk_k[0])
            body = make_chunk_body(
                grad_fn=grad_fn, obj_params=obj_params, num_class=num_class,
                lrf=lrf,
                grow_fn=grow_fn,
                has_bag=has_bag, has_ff=has_ff, bins=bins,
                num_bins=num_bins, base_mask=valid_rows,
                max_nodes=max_nodes, valid_bins=valid_bins,
                valid_mparams=valid_mparams,
                train_metric_fns=train_fns, train_mparams=train_mparams,
                valid_metric_fns=valid_metric_fns, health_fn=health_fn,
                goss_fn=goss_fn)
            xs = ((row_masks, feat_masks) if goss_fn is None
                  else (row_masks, feat_masks, goss_iters))
            (score, vscores), (stacked, mvals, hvals) = jax.lax.scan(
                body, (score, tuple(valid_scores)), xs)
            return score, vscores, stacked, mvals, hvals

        def param_spec(leaf):
            # row-aligned arrays ride the data axis; scalars are replicated;
            # global-score objectives' per-query tables ride replicated
            if not needs_global_score and getattr(leaf, "ndim", 0) >= 1:
                return P(DATA_AXIS, *([None] * (leaf.ndim - 1)))
            return P()

        pspecs = jax.tree.map(param_spec, obj_params)
        in_specs = (P(None, DATA_AXIS), P(None, DATA_AXIS), P(),
                    P(DATA_AXIS),
                    P(None, None, DATA_AXIS) if has_bag else P(),
                    P(), pspecs,
                    # metric params / valid sets are replicated (a single
                    # P() broadcasts over the whole subtree)
                    P(), P(), P(), P())
        if goss is not None:
            in_specs = in_specs + (P(),)     # goss_iters, replicated
        from .. import costmodel
        prog = costmodel.instrument("chunk/dp", jax.jit(shard_map(
            shard_chunk, mesh=mesh,
            in_specs=in_specs,
            out_specs=(P(None, DATA_AXIS),
                       tuple(P() for _ in range(n_valid)),
                       _tree_out_specs(None), P(), P()))),
            phase="train_chunk")
        _DP_CHUNK_PROGRAMS[key] = prog
        return prog, num_shards

    # the dispatch-segmentation seam (grower.grow_tree_segmented) exists
    # under this learner: distributed leaf-wise training survives
    # per-dispatch execution watchdogs at bench scale (VERDICT r4 #4)
    supports_leafwise_segments = True

    def _leafwise_compact_enabled(self) -> bool:
        from ..models.gbdt import leafwise_compact_on
        return leafwise_compact_on(self.tree_config)

    def _compact_grow_fn(self, kwargs, F: int, num_shards: int,
                         phase: str = "grow", loop_scale: int = 1):
        """Per-shard COMPACTED leaf-wise closure for the ACTIVE schedule:
        each shard keeps its local rows physically partitioned
        (grower_leafcompact.py) and the per-split smaller-child
        histograms are reduced globally — distributed parity-mode
        training at the geometric-series cost instead of full sweeps.
        The histogram tier is pmax-synced inside the grower so the
        collectives stay uniform across shards.

        Under ``psum`` the whole histogram is allreduced; under
        ``reduce_scatter`` the reference's ownership schedule
        (data_parallel_tree_learner.cpp:135-235) composes onto the same
        grower: feature-block psum_scatter (int domain for the quantized
        path), owned-slice hist cache and split search, packed SplitInfo
        allreduce — the multi-process default (dp_schedule=auto) no
        longer falls back to the masked N·(L-1)-sweep grower."""
        from ..ops.compact import pallas_partition_ok, partition_overlap_on
        use_pallas = pallas_partition_ok(F)
        overlap = partition_overlap_on()
        # per-split seams run once per split; x the fused-chunk length on
        # the chunk path (wire-metrics executed-calls estimate)
        split_loop = (kwargs["num_leaves"] - 1) * loop_scale

        if self._schedule() == "reduce_scatter":
            seams = dp_ownership_seams(F, num_shards,
                                       site_prefix="dp_rs/leafcompact",
                                       loop=split_loop, phase=phase,
                                       root_loop=loop_scale)

            def shard_grow(bins_s, grad_s, hess_s, mask_s, fmask, nbins):
                fmask_own, nbins_own, schedule = seams(fmask, nbins)
                return grow_tree_unified(
                    bins_s, grad_s, hess_s, mask_s, fmask_own, nbins_own,
                    policy="leafcompact", schedule=schedule,
                    use_pallas_partition=use_pallas,
                    partition_overlap=overlap, **kwargs)
            return shard_grow

        _c = functools.partial(telemetry.collective_span, axis=DATA_AXIS,
                               phase=phase)
        schedule = SeamSchedule(
            hist_axis=DATA_AXIS,
            hist_reduce=_c("dp_psum/leafcompact/hist_allreduce",
                           lambda h: jax.lax.psum(h, DATA_AXIS),
                           kind="psum", loop=split_loop),
            stat_reduce=_c("dp_psum/leafcompact/root_stats",
                           lambda s: jax.lax.psum(s, DATA_AXIS),
                           kind="psum", loop=loop_scale))

        def shard_grow(bins_s, grad_s, hess_s, mask_s, fmask, nbins):
            return grow_tree_unified(
                bins_s, grad_s, hess_s, mask_s, fmask, nbins,
                policy="leafcompact", schedule=schedule,
                use_pallas_partition=use_pallas,
                partition_overlap=overlap, **kwargs)
        return shard_grow

    def _psum_grow_fn(self, kwargs, F: int, policy: str,
                      phase: str = "grow", loop_scale: int = 1):
        """Per-shard grow closure for the plain-psum schedule, ANY growth
        policy: full-histogram allreduce over the data axis + replicated
        split search.  The one home of the psum seam set — the hybrid
        subclass overrides this with the 2-D owned-block schedule and
        the voting subclass with the top-k voted exchange, so every
        (policy x learner) cell flows through a single dispatch point
        instead of per-policy copies (ISSUE 9)."""
        _c = functools.partial(telemetry.collective_span, axis=DATA_AXIS,
                               phase=phase)
        # depthwise traces its level reduce per (unrolled) level; the
        # leaf-wise/compact fori_loop traces hist_reduce ONCE but runs it
        # once per split (wire-metrics executed-calls estimate)
        hist_loop = loop_scale * (1 if policy == "depthwise"
                                  else kwargs["num_leaves"] - 1)
        schedule = SeamSchedule(
            hist_axis=DATA_AXIS,
            hist_reduce=_c("dp_psum/%s/hist_allreduce" % policy,
                           lambda h: jax.lax.psum(h, DATA_AXIS),
                           kind="psum", loop=hist_loop),
            # the leaf-wise policies' ONE root histogram exchange files
            # at its own loop=loop_scale site (riding hist_reduce would
            # inflate the wire series by the per-split loop factor)
            root_hist_reduce=None if policy == "depthwise" else _c(
                "dp_psum/%s/root_hist" % policy,
                lambda h: jax.lax.psum(h, DATA_AXIS),
                kind="psum", loop=loop_scale),
            stat_reduce=_c("dp_psum/%s/root_stats" % policy,
                           lambda s: jax.lax.psum(s, DATA_AXIS),
                           kind="psum", loop=loop_scale))

        def shard_grow(bins_s, grad_s, hess_s, mask_s, fmask, nbins,
                       **extra):
            return grow_tree_unified(
                bins_s, grad_s, hess_s, mask_s, fmask, nbins,
                policy=policy, schedule=schedule, **kwargs, **extra)
        return shard_grow

    def _grow_fn(self, kwargs, F: int, num_shards: int):
        """Per-shard leaf-wise grow closure for the active schedule."""
        if self._schedule() == "reduce_scatter":
            return self._scatter_grow_fn_leafwise(kwargs, F, num_shards)
        return self._psum_grow_fn(kwargs, F, "leafwise")

    def _chunk_grow_fn(self, kwargs, F: int, num_shards: int,
                       depthwise: bool, use_compact: bool,
                       use_scatter: bool, k: int):
        """Policy x schedule dispatch for the fused chunk body — the one
        home of what the chunk builder used to re-derive inline; ``k``
        scales the wire-metrics executed-calls estimates (the scan body
        traces once, executes k times per chunk)."""
        if use_compact:
            # same grower (and the same schedule dispatch) on the chunk
            # path as on __call__'s per-iteration path
            return self._compact_grow_fn(kwargs, F, num_shards,
                                         phase="train_chunk", loop_scale=k)
        if use_scatter:
            return self._scatter_grow_fn(kwargs, F, num_shards,
                                         phase="train_chunk", loop_scale=k)
        return self._psum_grow_fn(kwargs, F,
                                  "depthwise" if depthwise else "leafwise",
                                  phase="train_chunk", loop_scale=k)

    def _state_specs(self):
        """shard_map specs of the carried _GrowState: leaf_ids row-sharded,
        the hist cache feature-sharded under the ownership schedule (each
        shard holds its owned block), everything else replicated."""
        cache = (P(None, DATA_AXIS)
                 if self._schedule() == "reduce_scatter" else P())
        rep = P()
        return _GrowState(
            tree=_tree_out_specs(DATA_AXIS), hist_cache=cache,
            cand_gain=rep, cand_feature=rep, cand_threshold=rep,
            cand_left_out=rep, cand_right_out=rep, cand_left_cnt=rep,
            cand_right_cnt=rep, cand_left_g=rep, cand_left_h=rep,
            cand_right_g=rep, cand_right_h=rep, leaf_sum_g=rep,
            leaf_sum_h=rep, leaf_cnt=rep, leaf_depth=rep, done=rep)

    def _segmented_grow(self, gbdt, bins, grad, hess, row_mask,
                        feature_mask, mesh, num_shards, segments: int):
        """grow_tree_segmented under shard_map: the split fori_loop runs as
        ceil((L-1)/segments) dispatches with the _GrowState carried
        device-resident (and donated) between them — program-identical
        trees, just short dispatches, exactly like the serial seam.  The
        reference's N-machine leaf-wise mode has no dispatch-length
        constraint to start with (serial_tree_learner.cpp:119-153); this
        restores that property under runtime watchdogs."""
        F, _ = bins.shape
        kwargs = self._grow_kwargs(gbdt)
        L = kwargs["num_leaves"]
        total = max(L - 1, 1)
        per = -(-total // max(segments, 1))
        cache = getattr(self, "_seg_progs", None)
        # the resolved mixed-bin layout rides the key like the jit_key in
        # __call__ (graftlint R2: the traced per-class pass structure is
        # baked into the segment programs)
        seg_key = (F, num_shards, per, getattr(gbdt, "_pack_spec", None))
        if cache is None or cache[0] != seg_key:
            grow_fn = self._grow_fn(kwargs, F, num_shards)
            in_specs = (P(None, DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS),
                        P(DATA_AXIS), P(), P())
            sspec = self._state_specs()

            def shard_init(bins_s, grad_s, hess_s, mask_s, fmask, nbins):
                return grow_fn(bins_s, grad_s, hess_s, mask_s, fmask,
                               nbins, loop_count=0, return_state=True)

            init_p = jax.jit(shard_map(shard_init, mesh=mesh,
                                       in_specs=in_specs, out_specs=sspec))
            seg_ps = {}
            for n in {per, total - per * (total // per)} - {0}:
                def shard_seg(bins_s, grad_s, hess_s, mask_s, fmask,
                              nbins, state, _n=n):
                    return grow_fn(bins_s, grad_s, hess_s, mask_s, fmask,
                                   nbins, init_state=state, loop_count=_n,
                                   return_state=True)
                seg_ps[n] = jax.jit(
                    shard_map(shard_seg, mesh=mesh,
                              in_specs=in_specs + (sspec,),
                              out_specs=sspec),
                    donate_argnums=(6,))
            cache = (seg_key, init_p, seg_ps)
            self._seg_progs = cache
        _, init_p, seg_ps = cache
        args = (bins, grad, hess, row_mask, feature_mask,
                gbdt.num_bins_device)
        state = init_p(*args)
        done = 0
        while done < total:
            n = min(per, total - done)
            state = seg_ps[n](*args, state)
            done += n
        return state.tree

    # telemetry route tag ("dp"; the 2-D subclasses say "hybrid"/"voting")
    route_name = "dp"

    def __call__(self, gbdt, bins, grad, hess, row_mask, feature_mask):
        mesh = self._mesh()
        num_shards = mesh.shape[DATA_AXIS]
        F, N = bins.shape
        pad = (-N) % num_shards
        if pad:
            bins = jnp.pad(bins, ((0, 0), (0, pad)))
            grad = jnp.pad(grad, (0, pad))
            hess = jnp.pad(hess, (0, pad))
            row_mask = jnp.pad(row_mask, (0, pad))

        # compacted leaf-wise (EITHER schedule — _compact_grow_fn
        # dispatches) subsumes segmentation: per-split dispatches are
        # short by construction.  Only the masked-grower segmented path
        # remains schedule-split.
        use_compact = (not self._depthwise
                       and self._leafwise_compact_enabled())
        segments = getattr(self.tree_config, "leafwise_segments", 1)
        rt = self.route_name
        if (not self._depthwise and segments > 1 and not use_compact
                and self.supports_leafwise_segments):
            telemetry.count_route("learner_" + rt,
                                  "learner/%s_segmented" % rt)
            tree = self._segmented_grow(gbdt, bins, grad, hess, row_mask,
                                        feature_mask, mesh, num_shards,
                                        segments)
            if pad:
                tree = tree._replace(leaf_ids=tree.leaf_ids[:N])
            return tree
        telemetry.count_route(
            "learner_" + rt, "learner/%s_" % rt + (
                "depthwise" if self._depthwise
                else ("compact_rs" if self._schedule() == "reduce_scatter"
                      else "compact") if use_compact
                else "leafwise"))

        # the per-iteration program must track the resolved
        # pallas-partition/DMA-overlap bits and backend/device identity,
        # exactly like the chunk-program caches: __graft_entry__ flips
        # LGBM_TPU_NO_PALLAS mid-process (PROFILE.md's A/B flips
        # LGBM_TPU_PARTITION_NO_OVERLAP) and a stale program would keep
        # the old kernel routing
        from ..ops.compact import pallas_partition_ok, partition_overlap_on
        use_pp = use_compact and pallas_partition_ok(F)
        # the resolved mixed-bin layout spec is a cache-key bit exactly
        # like the kernel-routing flags (graftlint R2): the traced
        # program bakes the per-class pass structure AND the canonical
        # reorder gathers in, so a booster with a different ``_pack_spec``
        # must not reuse this learner's jitted program
        jit_key = (use_pp, use_pp and partition_overlap_on(),
                   jax.default_backend(),
                   getattr(self.config, 'device_type', ''),
                   getattr(gbdt, "_pack_spec", None),
                   self._key_extra())
        if self._jitted is None or getattr(self, "_jit_key", None) != jit_key:
            self._jit_key = jit_key
            kwargs = self._grow_kwargs(gbdt)
            if self._depthwise:
                shard_fn = self._psum_grow_fn(kwargs, F, "depthwise")
            elif use_compact:
                shard_fn = self._compact_grow_fn(kwargs, F, num_shards)
            else:
                # schedule-dispatching leaf-wise closure shared with the
                # segmented path
                shard_fn = self._grow_fn(kwargs, F, num_shards)

            from .. import costmodel
            self._jitted = costmodel.instrument("grow/dp", jax.jit(shard_map(
                shard_fn, mesh=mesh,
                in_specs=(P(None, DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS),
                          P(DATA_AXIS), P(), P()),
                out_specs=_tree_out_specs(DATA_AXIS))), phase="grow")

        tree = self._jitted(bins, grad, hess, row_mask, feature_mask,
                            gbdt.num_bins_device)
        if pad:
            tree = tree._replace(leaf_ids=tree.leaf_ids[:N])
        return tree


class HybridLearner(DataParallelLearner):
    """Hybrid 2-D ``(data, feature)`` learner (ISSUE 9): rows sharded
    over the ``data`` mesh axis, contiguous feature-block ownership over
    the ``feature`` axis — ``num_machines = data_shards x feature_shards``
    (parallel/mesh.factor_machines; ``feature_shards=0`` auto-factors).

    Histograms build local-rows x owned-features; the histogram
    reduction is a data-axis psum RESTRICTED to the owned block (int
    domain on the quantized path), so per-shard wire bytes drop from
    O(F·B) per split to O(F·B / feature_shards); the split search runs
    on owned features only and the packed SplitInfo argmax-allreduce
    rides the FEATURE axis (hybrid_ownership_seams).  Degenerates to
    pure data parallelism at feature_shards=1.  All per-iteration and
    fused-chunk contracts are inherited from DataParallelLearner — rows
    pad to the DATA-axis size, bins ride replicated over the feature
    axis — so every growth policy x chunk path works unchanged."""

    route_name = "hybrid"
    # mixed-bin packing composes with feature-block ownership via the
    # BLOCK-LOCAL layout (ISSUE 12, io/binning.BlockedPackSpec): the
    # bin-width-class permutation never crosses an ownership block
    # boundary, so the owned-block psum and packed-SplitInfo allreduce
    # ride unchanged.  gbdt.init plans with ``pack_layout(F)``.
    feature_block_packing = True
    voting = False

    def pack_layout(self, num_features: int):
        """``(block, feature_shards)`` the block-local mixed-bin plan
        must respect — ``block`` == _owned_block's Fb for this mesh; the
        shard count lets the plan refuse meshes where a shard owns only
        ownership padding."""
        fs = self._feature_shards()
        return -(-num_features // fs), fs

    @staticmethod
    def _split_pack(kwargs):
        """(grow-call kwargs, pack) for a masked shard closure: under the
        block-local layout the owned slice's histogram passes use the
        shard-uniform ``block_view`` while split application translates
        through the GLOBAL canonical->storage map (partition_packing)."""
        pk = kwargs.get("packing")
        if pk is None or not hasattr(pk, "block_view"):
            return kwargs, None
        kw = dict(kwargs)
        kw["packing"] = pk.block_view
        kw["partition_packing"] = pk
        return kw, pk

    def _mesh(self):
        return get_mesh2d(self.config.network_config.num_machines,
                          getattr(self.tree_config, "feature_shards", 0),
                          getattr(self.config, 'device_type', ''),
                          voting=self.voting)

    def _feature_shards(self) -> int:
        return int(self._mesh().shape[FEATURE_AXIS])

    def _schedule(self) -> str:
        # dp_schedule is a 1-D knob; the 2-D ownership schedule REPLACES
        # the psum/reduce_scatter split (resolving "psum" here keeps the
        # base-class dispatch off the 1-D scatter closures)
        return "psum"

    def _key_extra(self) -> tuple:
        m = self._mesh()
        return (self.route_name, int(m.shape[DATA_AXIS]),
                int(m.shape[FEATURE_AXIS]),
                int(getattr(self.tree_config, "top_k", 0))
                if self.voting else 0)

    def _psum_grow_fn(self, kwargs, F: int, policy: str,
                      phase: str = "grow", loop_scale: int = 1):
        """Masked-policy closure on the 2-D mesh: pre-slice ``bins`` to
        the owned feature block (the histogram pass never touches
        un-owned features — the hybrid compute saving) and apply splits
        on the full-F local rows via ``partition_bins``."""
        fs = self._feature_shards()
        loop = loop_scale * (1 if policy == "depthwise"
                             else kwargs["num_leaves"] - 1)
        kw, pack = self._split_pack(kwargs)
        seams = hybrid_ownership_seams(
            F, fs, site_prefix="hybrid/%s" % policy, loop=loop,
            phase=phase, root_loop=loop_scale, pack=pack)

        def shard_grow(bins_s, grad_s, hess_s, mask_s, fmask, nbins,
                       **extra):
            own_s, fmask_own, nbins_own, schedule = seams(fmask, nbins)
            bins_own = jnp.take(bins_s, own_s, axis=0)
            return grow_tree_unified(
                bins_own, grad_s, hess_s, mask_s, fmask_own, nbins_own,
                policy=policy, schedule=schedule, partition_bins=bins_s,
                **kw, **extra)
        return shard_grow

    def _compact_grow_fn(self, kwargs, F: int, num_shards: int,
                         phase: str = "grow", loop_scale: int = 1):
        """Compacted leaf-wise on the 2-D mesh: the plane pane packs ALL
        features (the partition needs them), so the seam slices the
        owned block out BEFORE the data-axis psum — the wire still
        carries only the O(F·B / feature_shards) block."""
        from ..ops.compact import pallas_partition_ok, partition_overlap_on
        fs = self._feature_shards()
        split_loop = (kwargs["num_leaves"] - 1) * loop_scale
        seams = hybrid_ownership_seams(
            F, fs, site_prefix="hybrid/leafcompact", loop=split_loop,
            phase=phase, root_loop=loop_scale, slice_hist=True)
        use_pallas = pallas_partition_ok(F)
        overlap = partition_overlap_on()

        def shard_grow(bins_s, grad_s, hess_s, mask_s, fmask, nbins):
            _, fmask_own, nbins_own, schedule = seams(fmask, nbins)
            return grow_tree_unified(
                bins_s, grad_s, hess_s, mask_s, fmask_own, nbins_own,
                policy="leafcompact", schedule=schedule,
                use_pallas_partition=use_pallas,
                partition_overlap=overlap, **kwargs)
        return shard_grow

    def _state_specs(self):
        # the leaf-wise segmented carrier: the hist cache holds each
        # shard's owned feature block -> sharded over the FEATURE axis
        return super()._state_specs()._replace(
            hist_cache=P(None, FEATURE_AXIS))


class VotingLearner(HybridLearner):
    """Voting-parallel learner (ISSUE 9) — realizes the reference's
    named-but-absent ``tree_learner=voting`` (src/io/config.cpp:311-313
    Fatals on it; the PV-tree design): each data shard proposes its
    ``top_k`` features by LOCAL split gain, and full histograms are
    exchanged only for the <= 2·top_k globally-voted features per owned
    block — per-split wire bytes O(min(2·top_k, F/fs)·B) instead of the
    hybrid O(F·B / fs) (voting_seams).

    Pure data-parallel by default (factor_machines(voting=True) ->
    feature_shards=1); 2-D feature sharding composes via the
    feature_shards knob.  Voting is EXACT whenever the voted set covers
    the true best feature — guaranteed when 2·top_k >= the owned block
    width (the schedule then degenerates to a full exchange of the
    block); the PV-tree accuracy argument holds otherwise.  int8 keeps
    the int-domain global exchange (the bit-identity chain) and
    restricts only the search — the wire saving applies to f32/bf16."""

    route_name = "voting"
    voting = True
    # f32 voting keeps LOCAL histogram caches (the voted exchange lives
    # inside the finder), so the carried segmented _GrowState is not
    # representable as one sharded global array — whole-tree dispatches
    # only (gbdt warns and ignores leafwise_segments)
    supports_leafwise_segments = False

    def _voting_seams(self, kwargs, F: int, site: str, loop: int,
                      phase: str, root_loop: int, lanes: int = 1,
                      pack=None):
        int8 = str(kwargs.get("compute_dtype", "")).startswith("int8")
        return voting_seams(F, self._feature_shards(),
                            int(getattr(self.tree_config, "top_k", 20)),
                            int8, site_prefix=site, loop=loop,
                            phase=phase, root_loop=root_loop,
                            lanes=lanes, pack=pack)

    def _psum_grow_fn(self, kwargs, F: int, policy: str,
                      phase: str = "grow", loop_scale: int = 1):
        loop = loop_scale * (1 if policy == "depthwise"
                             else kwargs["num_leaves"] - 1)
        kw, pack = self._split_pack(kwargs)
        seams = self._voting_seams(kwargs, F, "voting/%s" % policy, loop,
                                   phase, loop_scale, pack=pack)
        _, _, block_ids = _owned_block(F, self._feature_shards(),
                                       FEATURE_AXIS)

        def shard_grow(bins_s, grad_s, hess_s, mask_s, fmask, nbins,
                       **extra):
            # pre-slice ``bins`` to the owned feature block (same as the
            # hybrid masked path): histogram compute and the [L, F, B, 3]
            # cache never touch un-owned features — the local caches and
            # the voted exchange inside the split finder both live on the
            # block — while splits apply on the full-F local rows via
            # ``partition_bins``.  Block-local packing rides the same
            # slice: the permutation never crosses the block boundary,
            # and the finder restores canonical order (voting_seams pack)
            schedule = seams(fmask, nbins)
            _, ownok, own_s = block_ids()
            bins_own = jnp.take(bins_s, own_s, axis=0)
            return grow_tree_unified(
                bins_own, grad_s, hess_s, mask_s,
                fmask[own_s] & ownok, jnp.take(nbins, own_s),
                policy=policy, schedule=schedule, partition_bins=bins_s,
                **kw, **extra)
        return shard_grow

    def _compact_grow_fn(self, kwargs, F: int, num_shards: int,
                         phase: str = "grow", loop_scale: int = 1):
        from ..ops.compact import pallas_partition_ok, partition_overlap_on
        split_loop = (kwargs["num_leaves"] - 1) * loop_scale
        # the compact split body batches BOTH children into one vmapped
        # finder call (best_of_pair) — the collective moves 2 lanes per
        # execution while the tracer records one lane's shape
        seams = self._voting_seams(kwargs, F, "voting/leafcompact",
                                   split_loop, phase, loop_scale, lanes=2)
        use_pallas = pallas_partition_ok(F)
        overlap = partition_overlap_on()

        def shard_grow(bins_s, grad_s, hess_s, mask_s, fmask, nbins):
            schedule = seams(fmask, nbins)
            return grow_tree_unified(
                bins_s, grad_s, hess_s, mask_s, fmask, nbins,
                policy="leafcompact", schedule=schedule,
                use_pallas_partition=use_pallas,
                partition_overlap=overlap, **kwargs)
        return shard_grow


def balanced_ownership(num_bins, num_shards: int):
    """Bin-count-balanced feature ownership (the reference re-balances
    ownership by bin count, feature_parallel_tree_learner.cpp:27-44):
    LPT greedy — features sorted by bin count, each assigned to the
    lightest shard with capacity.  Returns (own [S, Fs] i32 feature ids,
    ownmask [S, Fs] bool); padded slots point at feature 0 and are masked.
    """
    num_bins = np.asarray(num_bins)
    F = len(num_bins)
    Fs = -(-F // num_shards)
    order = np.argsort(-num_bins, kind="stable")
    loads = np.zeros(num_shards, np.int64)
    buckets = [[] for _ in range(num_shards)]
    for f in order:
        s = min((s for s in range(num_shards) if len(buckets[s]) < Fs),
                key=lambda s: (loads[s], s))
        buckets[s].append(int(f))
        loads[s] += int(num_bins[f])
    own = np.zeros((num_shards, Fs), np.int32)
    ownmask = np.zeros((num_shards, Fs), bool)
    for s, b in enumerate(buckets):
        own[s, :len(b)] = sorted(b)
        ownmask[s, :len(b)] = True
    return own, ownmask


def static_ownership(num_features: int, num_shards: int):
    """Contiguous-slice ownership (no balancing) — kept for the A/B in
    scripts/fp_ownership_bench.py."""
    Fs = -(-num_features // num_shards)
    own = np.minimum(np.arange(num_shards)[:, None] * Fs + np.arange(Fs),
                     num_features - 1).astype(np.int32)
    ownmask = (np.arange(num_shards)[:, None] * Fs
               + np.arange(Fs)) < num_features
    return own, ownmask


# Compiled feature-parallel k-iteration chunk programs, shared process-wide
_FP_CHUNK_PROGRAMS: dict = {}


class FeatureParallelLearner(_ParallelLearnerBase):
    """Feature ownership sharded, data replicated
    (feature_parallel_tree_learner.cpp).  Ownership is bin-count balanced
    like the reference (lines 27-44; ``balanced_ownership``) — the result
    is invariant to ownership, only load balance differs.  Both the
    per-iteration path and the fused k-iteration chunk program exist; the
    chunk runs the whole gradients → grow(SplitInfo allreduce) →
    score-update scan under shard_map with everything except feature
    ownership replicated."""

    ownership = staticmethod(balanced_ownership)

    def _ownership(self, gbdt, num_shards):
        # constant for the dataset's lifetime: compute/upload once (the
        # per-iteration path calls this every tree)
        cache = getattr(self, "_own_cache", None)
        if cache is not None and cache[0] == num_shards:
            return cache[1], cache[2]
        own, ownmask = type(self).ownership(
            np.asarray(gbdt.num_bins_device), num_shards)
        own, ownmask = jnp.asarray(own), jnp.asarray(ownmask)
        self._own_cache = (num_shards, own, ownmask)
        return own, ownmask

    def _shard_grow_fn(self, policy, kwargs, own, ownmask,
                       phase: str = "grow", loop_scale: int = 1):
        """Per-shard grow closure: slice owned features, allreduce the
        packed SplitInfo, apply splits on the replicated full matrix.
        ``phase``/``loop_scale`` label the SplitInfo-allreduce wire-
        metrics site (per split on the leaf-wise fori_loop, per traced
        level depth-wise; x chunk length on the fused path)."""
        loop = loop_scale * (1 if policy == "depthwise"
                             else kwargs["num_leaves"] - 1)

        def shard_grow(bins_full, grad_s, hess_s, mask_s, fmask, nbins):
            rank = jax.lax.axis_index(FEATURE_AXIS)
            own_s = own[rank]
            ownok = ownmask[rank]
            bins_own = jnp.take(bins_full, own_s, axis=0)
            nbins_own = jnp.take(nbins, own_s)
            fmask_own = fmask[own_s] & ownok

            schedule = SeamSchedule(split_finder=ownership_finder(
                own_s, FEATURE_AXIS,
                site="fp/splitinfo_allreduce", loop=loop, phase=phase))
            return grow_tree_unified(
                bins_own, grad_s, hess_s, mask_s, fmask_own, nbins_own,
                policy=policy, schedule=schedule,
                partition_bins=bins_full, **kwargs)
        return shard_grow

    def chunk_program(self, gbdt, obj_key, grad_fn, obj_params,
                      has_bag: bool, has_ff: bool,
                      train_metric_fns=(), valid_metric_fns=(),
                      n_valid: int = 0, health: bool = False, goss=None):
        """Fused k-iteration feature-parallel chunk (same contract as the
        data-parallel chunk_program / serial chunk program).  Rows are
        replicated, so metric evaluation needs no gathering — and neither
        does the health vector (every shard computes the identical
        full-row reductions)."""
        mesh = get_mesh(self.config.network_config.num_machines,
                        FEATURE_AXIS, getattr(self.config, 'device_type', ''))
        num_shards = mesh.shape[FEATURE_AXIS]
        num_class = gbdt.num_class
        lr = float(gbdt.gbdt_config.learning_rate)
        kwargs = self._grow_kwargs(gbdt)
        policy = "depthwise" if self._depthwise else "leafwise"
        max_nodes = max(_effective_num_leaves(self.tree_config) - 1, 1)
        health_fn = None
        if health:
            from ..health import make_health_fn
            health_fn = make_health_fn(
                self.tree_config.hist_dtype == "int8", None)
        # backend + device_type join the key like the DP/serial chunk
        # caches (graftlint R2): num_shards alone cannot distinguish two
        # same-sized meshes on different backends, and trace-time kernel
        # routing (ops/histogram._pallas_hist_ok, LGBM_TPU_NO_PALLAS
        # flips) bakes the backend into the program
        key = (obj_key, id(grad_fn), num_shards, num_class, lr,
               self._depthwise, tuple(sorted(kwargs.items())), has_bag,
               has_ff, bool(health), goss, jax.default_backend(),
               getattr(self.config, 'device_type', ''),
               tuple(id(f) for f in train_metric_fns),
               tuple(tuple(id(f) for f in fns) for fns in valid_metric_fns))
        prog = _FP_CHUNK_PROGRAMS.get(key)
        if prog is not None:
            return prog, num_shards

        lrf = jnp.float32(lr)
        # rows are replicated under feature ownership, so in-chunk GOSS
        # is the serial full-row draw (every shard computes the identical
        # selection from the identical gradients)
        from ..models.gbdt import make_goss_fn
        goss_fn = make_goss_fn(goss) if goss is not None else None

        def shard_chunk(score, bins, num_bins, own, ownmask, row_masks,
                        feat_masks, obj_params, train_mparams, valid_bins,
                        valid_scores, valid_mparams, goss_iters=None):
            from ..models.gbdt import make_chunk_body
            body = make_chunk_body(
                grad_fn=grad_fn, obj_params=obj_params, num_class=num_class,
                lrf=lrf,
                grow_fn=self._shard_grow_fn(
                    policy, kwargs, own, ownmask, phase="train_chunk",
                    loop_scale=int(row_masks.shape[0])),
                has_bag=has_bag, has_ff=has_ff, bins=bins,
                num_bins=num_bins, max_nodes=max_nodes,
                valid_bins=valid_bins, valid_mparams=valid_mparams,
                train_metric_fns=train_metric_fns,
                train_mparams=train_mparams,
                valid_metric_fns=valid_metric_fns, health_fn=health_fn,
                goss_fn=goss_fn)
            xs = ((row_masks, feat_masks) if goss_fn is None
                  else (row_masks, feat_masks, goss_iters))
            (score, vscores), (stacked, mvals, hvals) = jax.lax.scan(
                body, (score, tuple(valid_scores)), xs)
            return score, vscores, stacked, mvals, hvals

        from .. import costmodel
        prog = costmodel.instrument("chunk/fp", jax.jit(shard_map(
            shard_chunk, mesh=mesh,
            in_specs=(P(),) * (13 if goss is not None else 12),
            out_specs=(P(), tuple(P() for _ in range(n_valid)),
                       _tree_out_specs(None), P(), P()))),
            phase="train_chunk")
        _FP_CHUNK_PROGRAMS[key] = prog
        return prog, num_shards

    def chunk_args(self, gbdt, num_shards):
        """Extra leading inputs the FP chunk program takes after num_bins."""
        return self._ownership(gbdt, num_shards)

    def __call__(self, gbdt, bins, grad, hess, row_mask, feature_mask):
        mesh = get_mesh(self.config.network_config.num_machines, FEATURE_AXIS,
                        getattr(self.config, 'device_type', ''))
        num_shards = mesh.shape[FEATURE_AXIS]
        telemetry.count_route(
            "learner_fp", "learner/fp_" + ("depthwise" if self._depthwise
                                           else "leafwise"))

        if self._jitted is None:
            kwargs = self._grow_kwargs(gbdt)
            policy = "depthwise" if self._depthwise else "leafwise"

            def shard_fn(bins_full, grad_s, hess_s, mask_s, fmask, nbins,
                         own, ownmask):
                return self._shard_grow_fn(policy, kwargs, own, ownmask)(
                    bins_full, grad_s, hess_s, mask_s, fmask, nbins)

            from .. import costmodel
            self._jitted = costmodel.instrument("grow/fp", jax.jit(shard_map(
                shard_fn, mesh=mesh,
                in_specs=(P(),) * 8,
                out_specs=_tree_out_specs(None))), phase="grow")

        own, ownmask = self._ownership(gbdt, num_shards)
        tree = self._jitted(bins, grad, hess, row_mask, feature_mask,
                            gbdt.num_bins_device, own, ownmask)
        return tree


def distributed_bin_finder(config):
    """Distributed bin finding (dataset.cpp:353-415).

    Each process computes BinMappers for a contiguous feature slice from the
    (identical) global sample and allgathers the results.  Single-process
    runs return None → local bin finding (identical output, the distribution
    is purely a speed optimization since every worker holds the same
    sample)."""
    if jax.process_count() == 1:
        return None

    def finder(sample: np.ndarray, max_bin: int):
        from jax.experimental import multihost_utils
        nproc = jax.process_count()
        rank = jax.process_index()
        F = sample.shape[1]
        step = -(-F // nproc)
        lo, hi = rank * step, min((rank + 1) * step, F)
        blobs = []
        for j in range(lo, hi):
            mapper = BinMapper()
            mapper.find_bin(sample[:, j], max_bin)
            blobs.append(mapper.to_bytes())
        # fixed-size padding like BinMapper::SizeForSpecificBin
        # (dataset.cpp:371-376) so the gather is a dense array
        max_len = 16 + 8 * (max_bin + 1)
        buf = np.zeros((step, max_len), dtype=np.uint8)
        for i, blob in enumerate(blobs):
            buf[i, :len(blob)] = np.frombuffer(blob, dtype=np.uint8)
        gathered = multihost_utils.process_allgather(buf)  # [nproc, step, max_len]
        mappers = []
        for j in range(F):
            r, i = divmod(j, step)
            mappers.append(BinMapper.from_bytes(gathered[r, i].tobytes()))
        return mappers

    return finder
