"""Distributed tree learning over a jax.sharding.Mesh.

Replaces the reference's Network layer (/root/reference/src/network/ —
Bruck allgather, recursive-halving reduce-scatter over sockets/MPI) with XLA
collectives inside ``shard_map``:

- data-parallel  (data_parallel_tree_learner.cpp)  → rows sharded over the
  ``data`` mesh axis, histograms ``psum``/``psum_scatter``'d, split decisions
  replicated → bit-identical trees on every shard.
- feature-parallel (feature_parallel_tree_learner.cpp) → per-shard feature
  ownership masks + packed argmax allreduce of SplitInfo.
- distributed bin finding (dataset.cpp:353-415) → feature-sliced FindBin +
  allgather.

Multi-host bootstrap (socket mlist / MPI ranks, linkers_socket.cpp) maps to
``jax.distributed.initialize`` + the global device mesh.
"""
from __future__ import annotations

from .mesh import (get_mesh, get_rank, get_num_machines, sync_up_by_min)
from .learners import create_parallel_learner, distributed_bin_finder
