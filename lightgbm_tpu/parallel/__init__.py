"""Distributed tree learning over a jax.sharding.Mesh.

Replaces the reference's Network layer (/root/reference/src/network/ —
Bruck allgather, recursive-halving reduce-scatter over sockets/MPI) with XLA
collectives inside ``shard_map``, on a 1-D ``(data,)`` or explicit 2-D
``(data, feature)`` mesh (ISSUE 9):

- data-parallel  (data_parallel_tree_learner.cpp)  → rows sharded over the
  ``data`` mesh axis, histograms ``psum``/``psum_scatter``'d, split decisions
  replicated → bit-identical trees on every shard.
- feature-parallel (feature_parallel_tree_learner.cpp) → per-shard feature
  ownership masks + packed argmax allreduce of SplitInfo.
- hybrid → rows on ``data`` AND feature blocks on ``feature`` of one 2-D
  mesh (``num_machines = data_shards × feature_shards``); the histogram
  reduce is a data-axis psum restricted to the owned block, so per-shard
  wire bytes drop to O(F·B / feature_shards).
- voting → the reference's named-but-absent PV-tree mode realized: top-k
  per-shard split voting over the data axis; full histograms exchanged
  only for the ≤2·top_k globally-voted features.
- distributed bin finding (dataset.cpp:353-415) → feature-sliced FindBin +
  allgather.

All four learners drive the ONE schedule-parameterized grower
(models/grower_unified.py) by handing it a declarative ``SeamSchedule``
— the learners differ only in which collectives the seams wrap.

Multi-host bootstrap (socket mlist / MPI ranks, linkers_socket.cpp) maps to
``jax.distributed.initialize`` + the global device mesh.
"""
from __future__ import annotations

from .mesh import (get_mesh, get_rank, get_num_machines, sync_up_by_min)
from .learners import create_parallel_learner, distributed_bin_finder
