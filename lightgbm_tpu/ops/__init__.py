"""Device ops: histogram build, split search, scoring."""
from .histogram import build_histogram
from .split import find_best_split, SplitResult
