"""Exact small-table row lookup without XLA's TPU gather.

``values[ids]`` with a [L] table and [N] ids lowers to an XLA gather that
costs ~85 ms at N=11M on v5e — per-row scalar addressing is the one thing
a vector machine cannot do.  The TPU-native formulation is a one-hot
matmul; to keep it BIT-exact for f32 tables at default (bf16-operand) MXU
precision, the table is byte-split: each f32 value rides as 4 integer
bytes (0..255, bf16-exact), and the gathered bytes are reassembled by
bit-ops.  Exactly one one-hot entry matches per row, so no accumulation
error exists by construction.  ~1.5 ms at 11M (55x faster than gather).

The reference's equivalent is a plain indexed read in the score updater
(score_updater.hpp:49-66); this is its systolic-array inversion.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def exact_table_lookup(values: jax.Array, ids: jax.Array) -> jax.Array:
    """values[ids], bit-exact, for f32 ``values`` [L] and int ``ids`` [N]
    with every id in [0, L).  Uses the one-hot matmul on accelerators and
    the native gather on CPU (where gathers are cheap and bf16 is not)."""
    if jax.default_backend() == "cpu":
        return values[ids]
    L = values.shape[0]
    u = jax.lax.bitcast_convert_type(values.astype(jnp.float32), jnp.uint32)
    byte_tbl = jnp.stack(
        [(u >> s) & jnp.uint32(0xFF) for s in (0, 8, 16, 24)],
        axis=1).astype(jnp.bfloat16)                         # [L, 4]
    oh = (ids[None, :] == jnp.arange(L, dtype=jnp.int32)[:, None]
          ).astype(jnp.bfloat16)                             # [L, N]
    parts = jnp.einsum("ln,lk->kn", oh, byte_tbl,
                       preferred_element_type=jnp.float32)   # [4, N]
    b = parts.astype(jnp.uint32)
    out = b[0] | (b[1] << 8) | (b[2] << 16) | (b[3] << 24)
    return jax.lax.bitcast_convert_type(out, jnp.float32)


def batched_int8_table_lookup(values: jax.Array, ids: jax.Array) -> jax.Array:
    """Per-tree table read ``values[t, ids[t, n]]`` → f32 [T, N], exact,
    for an int8 ``values`` [T, L] and int ``ids`` [T, N] with every id in
    [0, L).

    The serving engine's quantized-leaf read (ops/scoring int8 variant):
    int8 magnitudes (≤ 127) are bf16-exact, so the byte-split trick above
    collapses to a SINGLE one-hot matmul pass per tree — a quarter of the
    f32 table's operand traffic, which is the whole point of the int8
    ensemble on memory-bound serving shapes.  Exactly one one-hot entry
    matches per (tree, row), so there is no accumulation error by
    construction.  CPU keeps the native gather (same contract as
    exact_table_lookup)."""
    if jax.default_backend() == "cpu":
        return jnp.take_along_axis(
            values.astype(jnp.float32), ids, axis=1)
    L = values.shape[1]
    oh = (ids[:, None, :] == jnp.arange(L, dtype=jnp.int32)[None, :, None]
          ).astype(jnp.bfloat16)                             # [T, L, N]
    return jnp.einsum("tln,tl->tn", oh, values.astype(jnp.bfloat16),
                      preferred_element_type=jnp.float32)    # [T, N]
