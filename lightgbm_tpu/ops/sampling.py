"""Device-side row sampling: bagging mask draws and GOSS selection.

The reference draws bagging indices on the host with a serial RNG
(GBDT::Bagging, gbdt.cpp:106-157) — our boosting loop inherited that and
paid a full-N ``bool`` host→device upload every ``bagging_freq``
iterations (ISSUE 8 satellite: models/gbdt.py ``_bagging``).  This module
moves the draw itself on-device:

- **Bagging** (``bag_mask_for_draw``): one threefry key per redraw
  (``fold_in(PRNGKey(bagging_seed), draw_index)``), exact in-bag count
  like the reference (``int(bagging_fraction * n)`` rows without
  replacement, via one uniform draw + argsort).  A redraw becomes a key
  bump — no host RNG, no full-N transfer.  The draw is a pure function of
  ``(seed, draw_index, n, bag_cnt)``, so the pipelined/chunked rollback
  machinery replays it exactly by rewinding an integer counter instead of
  copying numpy RNG state.  The legacy host path stays behind
  ``LGBM_TPU_HOST_BAGGING=1`` (and ``bagging_device=false``) for A/B.

- **GOSS** (``goss_select``): gradient-based one-side sampling (the
  headline trick of the later LightGBM paper — PAPERS.md): keep the
  ``top_rate`` fraction of rows by gradient magnitude, sample an
  ``other_rate`` fraction of the remainder uniformly, and amplify the
  sampled remainder's gradients AND hessians by
  ``(1 - top_rate) / other_rate`` so split gains stay unbiased.  Rows are
  scored by the summed absolute gradient across classes; everything —
  sort, sample, amplification — runs on-device and feeds the existing
  histogram kernels through the row-mask seam, so a sampled iteration
  never materializes a full-row host intermediate.

Both draws are deterministic given their key inputs; the oracle tests in
tests/test_streaming.py replay the same formulas host-side.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("num_rows", "bag_cnt"))
def _bag_mask(key, num_rows: int, bag_cnt: int):
    # exact-count draw without replacement: rank one uniform per row and
    # keep the bag_cnt smallest ranks (argsort is stable, so the mask is
    # fully determined by the key even under tied uniforms)
    u = jax.random.uniform(key, (num_rows,))
    order = jnp.argsort(u)
    return jnp.zeros((num_rows,), jnp.bool_).at[order[:bag_cnt]].set(True)


def bag_key(bagging_seed: int):
    """The base key of the device bagging stream."""
    return jax.random.PRNGKey(bagging_seed)


def bag_mask_for_draw(base_key, draw_index: int, num_rows: int,
                      bag_cnt: int):
    """[num_rows] bool in-bag mask for the ``draw_index``-th redraw of the
    stream rooted at ``base_key`` — exactly ``bag_cnt`` rows in-bag."""
    return _bag_mask(jax.random.fold_in(base_key, draw_index),
                     num_rows, bag_cnt)


def goss_row_scores(grad):
    """The GOSS row score: summed absolute gradient across classes —
    single-homed so the per-iteration jit and the fused chunk programs
    (serial scan body, DP shard closures) compute the identical f32
    values row for row."""
    return jnp.sum(jnp.abs(grad.astype(jnp.float32)), axis=0)


def goss_mask_weights(key, absg, top_cnt: int, other_cnt: int,
                      amp: float):
    """The traced GOSS draw over row scores: top_cnt rows by score,
    other_cnt uniform remainder rows, amplification weights.  The exact
    formula ``_goss_select`` jits — factored out so the fused chunk
    programs (ISSUE 12: serial scan body, DP shard_map with gathered
    global scores, FP replicated rows) trace the identical selection and
    a sampled iteration is bit-identical across dispatch paths given the
    same key and row count.  Returns ``(mask [n] bool, w [n] f32)`` with
    ``w`` = amp on the sampled remainder, 1 elsewhere."""
    n = absg.shape[0]
    # descending gradient-magnitude order (stable: ties resolve by row
    # index, deterministically)
    order = jnp.argsort(-absg)
    mask = jnp.zeros((n,), jnp.bool_).at[order[:top_cnt]].set(True)
    rest = order[top_cnt:]
    # uniform sample of other_cnt remainder rows, one key per iteration
    u = jax.random.uniform(key, (n - top_cnt,))
    pick = rest[jnp.argsort(u)[:other_cnt]]
    mask = mask.at[pick].set(True)
    w = jnp.ones((n,), jnp.float32).at[pick].set(jnp.float32(amp))
    return mask, w


@functools.partial(jax.jit,
                   static_argnames=("top_cnt", "other_cnt", "amp"))
def _goss_select(key, grad, hess, top_cnt: int, other_cnt: int,
                 amp: float):
    mask, w = goss_mask_weights(key, goss_row_scores(grad), top_cnt,
                                other_cnt, amp)
    return grad * w, hess * w, mask


def goss_select(key, grad, hess, top_cnt: int, other_cnt: int,
                amp: float):
    """GOSS row selection over per-class gradients.

    Parameters
    ----------
    key : per-iteration PRNG key (``fold_in(PRNGKey(seed), iteration)``)
    grad, hess : [num_class, num_rows] float arrays
    top_cnt : rows kept by gradient magnitude (``int(top_rate * n)``)
    other_cnt : remainder rows sampled uniformly (``int(other_rate * n)``)
    amp : amplification of the sampled remainder,
        ``(1 - top_rate) / other_rate``

    Returns ``(grad', hess', mask)`` where grad'/hess' carry the
    amplification on the sampled remainder (unselected rows' values are
    irrelevant — the mask excludes them from histograms and root stats)
    and ``mask`` is the [num_rows] bool selection.
    """
    return _goss_select(key, grad, hess, int(top_cnt), int(other_cnt),
                        float(amp))


def goss_counts(num_rows: int, top_rate: float, other_rate: float):
    """The static (top_cnt, other_cnt, amp) triple for a dataset size —
    single-homed so gbdt and the tests agree on rounding."""
    top_cnt = int(top_rate * num_rows)
    other_cnt = int(other_rate * num_rows)
    amp = (1.0 - top_rate) / other_rate
    return top_cnt, other_cnt, amp
