"""Streaming stable row-partition — the compacted leaf-wise grower's core op.

The reference keeps every leaf's rows CONTIGUOUS in a permuted index array
and partitions the parent's range at each split
(/root/reference/src/io/data_partition.hpp:93-139); its histogram then
touches only the leaf's own rows (dense_bin.hpp:46-112 ConstructHistogram
over an ordered index list).  A TPU can't follow row indices (XLA lowers
small-table gathers to per-row scalar addressing — measured ~85 ms per [N]
f32 gather at 11M rows, PROFILE.md), so this module moves the DATA instead
of the indices: the [R, N] int8 plane matrix (bin rows + grad/hess
bit-planes + validity) is kept physically partitioned, and each split
stably partitions the parent's lane range in one streaming sweep.

The Pallas kernel (TPU): grid = (lane blocks,), sequential; BOTH streams
(left rows, then right rows) run inside each grid step, so one sweep over
the data compacts both sides.  Per block the lane compaction is pure MXU:
an exclusive prefix-sum of the selection mask via a strict-lower-
triangular int8 matmul, a one-hot selection matrix built by an iota
compare, and an int8 x int8 -> int32 selection matmul that moves whole
[R, block] panes (f32 grad/hess travel bit-exactly as 4 int8 planes).
Each stream's compacted lanes are DMA'd to the output through a
read-modify-write window at a running lane offset carried in SMEM.  By
default the per-block window DMAs are OVERLAPPED (both window reads
issue up front and the left write-back flies under the right blend): the
two streams' fresh lane ranges are always disjoint, but their
128-aligned RMW padding can overlap, so the right blend patches this
block's fresh left lanes in VMEM from a third selection matmul instead
of re-reading them through HBM — only the two write-backs stay ordered.
Cost per partitioned row: block x R int8 MACs (x1.5 with the overlap
patch) + ~3 bytes of HBM traffic per plane — ~0.6% of the histogram MACs
the compaction saves (PROFILE.md).

The XLA oracle (CPU/tests): a stable argsort formulation with identical
semantics — the kernel is differentially tested against it.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# JAX-version compat: the TPU host runs a newer JAX where these carry
# their current names; older releases (this CPU test container) spell
# them pltpu.ANY / pltpu.TPUCompilerParams.  ANY-vs-HBM only matters to
# real Mosaic lowering (see the out_specs comment below) — interpret
# mode treats them alike.
_HBM_SPACE = getattr(pltpu, "HBM", pltpu.ANY)
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))

BLOCK = 2048  # partition lane block; the kernel's VMEM working set at
              # this block (pane slices, the [2176, 2048] one-hot
              # selection matrix, the RMW window buffers and blend
              # temporaries) is priced by partition_vmem_bytes below,
              # which gates eligibility at PARTITION_VMEM_BUDGET


# VMEM ceiling for the partition kernel's working set.  Past it Mosaic
# fails to ALLOCATE (wide-F datasets), so eligibility must be gated here
# rather than discovered as a compile error.  12 MiB of the ~16 MiB/core
# leaves headroom for Mosaic's own spills; with the overlap schedule's
# temporary count the estimate admits pane heights up to R≈88 (F≈79) at
# the default block.  Deliberately conservative: the fallback (XLA
# argsort oracle) is correctness-neutral, an on-device allocation
# failure is not.
PARTITION_VMEM_BUDGET = 12 << 20


def partition_vmem_bytes(num_features: int, block: int = BLOCK) -> int:
    """Working-set estimate (bytes) of the partition kernel at this pane
    height: double-buffered input blocks, the matmul operand matrices,
    the RMW window buffers and the i32 shifted/keep/blend temporaries.
    Sized for the default OVERLAP schedule, whose right-blend merge
    keeps more [R, win] i32 temporaries live at once (merged/keep_lr/
    shifted_r/keep_r around the blend) than the serialized kernel's
    three."""
    R = pane_rows(num_features)
    win = block + 128
    return (2 * (R + 1) * block     # pipelined seg+mask input blocks, int8
            + block * block         # strict-lower-triangular operand, int8
            + win * block           # one-hot selection matrix, int8
            + 2 * R * win           # RMW window buffers, int8
            + 4 * 4 * R * win)      # i32 temporaries live around the blend


def pallas_partition_ok(num_features: int | None = None) -> bool:
    """Eligibility of the Pallas partition kernel: TPU default backend,
    unless LGBM_TPU_NO_PALLAS=1 — the escape hatch a mixed-backend
    process (TPU backend up, computation steered onto virtual CPU
    devices, e.g. __graft_entry__.dryrun_multichip) sets so kernels
    never land on a CPU mesh.  ``num_features`` (when the caller knows
    it) additionally gates on the kernel's VMEM working set: wide-F
    datasets whose plane pane exceeds PARTITION_VMEM_BUDGET fall back to
    the XLA argsort oracle instead of failing to compile.  Every outcome
    is counted (telemetry) — the runtime record of which partition route
    the process baked into its programs."""
    from .. import hatches, telemetry
    if hatches.flag("LGBM_TPU_NO_PALLAS"):
        # count_route: this rule is re-evaluated per tree by host code, so
        # counting per outcome CHANGE keeps the counter at per-decision
        # magnitude like the trace-time counters
        telemetry.count_route("partition_ok", "partition/env_no_pallas")
        return False
    if (num_features is not None
            and partition_vmem_bytes(num_features) > PARTITION_VMEM_BUDGET):
        telemetry.count_route("partition_ok", "partition/wide_f_fallback")
        return False
    ok = jax.default_backend() == "tpu"
    telemetry.count_route("partition_ok",
                          "partition/pallas_eligible" if ok
                          else "partition/pallas_ineligible")
    return ok


def _partition_kernel(mask_ref, scal_ref, seg_ref, out_ref, win_ref,
                      offs_ref, sem_ref, *, R, block):
    """Grid (nblocks,): both streams (left then right) per lane block.

    Mosaic requires dynamic DMA lane offsets to be 128-aligned, so each
    stream writes a read-modify-write WINDOW at the aligned-down offset:
    the compacted rows are shifted to their exact in-window position by a
    one-hot shift matmul, blended with the window's current content, and
    the whole aligned window written back.  Fully serialized DMAs keep
    the left write visible to the right read (their windows may
    overlap)."""
    j = pl.program_id(0)

    @pl.when(j == 0)
    def _():
        offs_ref[0] = 0
        offs_ref[1] = 0

    delta = scal_ref[0]
    plcnt = scal_ref[1]
    win = block + 128

    # mask3 lanes: 1 = left, 0 = right, -1 = outside the segment.  All
    # compares/arithmetic run wide (int32) — Mosaic has no 8-bit vector
    # math — and cast to int8 only at the MXU operands.
    m = mask_ref[...].astype(jnp.int32)                    # [1, block]
    iota_s = jax.lax.broadcasted_iota(jnp.int32, (block, block), 0)
    iota_t = jax.lax.broadcasted_iota(jnp.int32, (win, block), 0)
    lt = (iota_s < jax.lax.broadcasted_iota(
        jnp.int32, (block, block), 1)).astype(jnp.int8)
    lane_w = jax.lax.broadcasted_iota(jnp.int32, (R, win), 1)
    pane = seg_ref[...]                                    # [R, block] int8

    for p in (0, 1):
        mi = (m == 1 - p).astype(jnp.int32)                # [1, block]
        used = jnp.sum(mi)
        # exclusive prefix sum over lanes as a strict-lower matmul
        pos = jax.lax.dot_general(
            mi.astype(jnp.int8), lt,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)              # [1, block]
        # compact + shift in ONE one-hot matmul: source lane s lands at
        # window lane pos[s] + shift
        base = delta + p * plcnt + offs_ref[p]
        p0 = (base // 128) * 128                           # aligned window
        shift = base - p0
        sel = ((jnp.broadcast_to(pos, (win, block)) + shift == iota_t)
               & jnp.broadcast_to(mi == 1, (win, block))).astype(jnp.int8)
        shifted = jax.lax.dot_general(
            pane, sel, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32)              # [R, win] i32
        # RMW: read the aligned window, blend lanes [shift, shift+used)
        dma_in = pltpu.make_async_copy(
            out_ref.at[:, pl.ds(p0, win)], win_ref, sem_ref)
        dma_in.start()
        dma_in.wait()
        keep = ((lane_w >= shift) & (lane_w < shift + used)).astype(
            jnp.int32)
        blended = (shifted * keep
                   + win_ref[...].astype(jnp.int32) * (1 - keep))
        win_ref[...] = blended.astype(jnp.int8)
        dma_out = pltpu.make_async_copy(
            win_ref, out_ref.at[:, pl.ds(p0, win)], sem_ref)
        dma_out.start()
        dma_out.wait()
        offs_ref[p] = offs_ref[p] + used


def _partition_kernel_overlap(mask_ref, scal_ref, seg_ref, out_ref,
                              winl_ref, winr_ref, offs_ref,
                              seml_ref, semr_ref, *, R, block):
    """Grid (nblocks,): both streams per lane block, window DMAs
    OVERLAPPED.

    The serialized kernel round-trips through HBM between the streams
    (in-L → out-L → in-R → out-R) because the right window's read must
    see the left window's write wherever their 128-aligned RMW paddings
    overlap.  Here both window READS issue up front (each sees pre-step
    HBM bytes) and overlap the selection matmuls; the left write-back
    flies under the right stream's compute; and the right blend patches
    this block's fresh left lanes VMEM-side — a third one-hot matmul
    places the SAME left rows at their right-window coordinates — so it
    never needs the post-left-write HBM state.  Only the two write-backs
    stay ordered (their paddings can carry differing bytes; the merged
    right window must win).  Bit-identical output to the serialized
    kernel by construction: the fresh lane ranges are disjoint and every
    patched byte equals what the HBM round-trip would have returned."""
    j = pl.program_id(0)

    @pl.when(j == 0)
    def _():
        offs_ref[0] = 0
        offs_ref[1] = 0

    delta = scal_ref[0]
    plcnt = scal_ref[1]
    win = block + 128

    m = mask_ref[...].astype(jnp.int32)                    # [1, block]
    iota_t = jax.lax.broadcasted_iota(jnp.int32, (win, block), 0)
    lt = (jax.lax.broadcasted_iota(jnp.int32, (block, block), 0)
          < jax.lax.broadcasted_iota(
              jnp.int32, (block, block), 1)).astype(jnp.int8)
    lane_w = jax.lax.broadcasted_iota(jnp.int32, (R, win), 1)
    pane = seg_ref[...]                                    # [R, block] int8

    base_l = delta + offs_ref[0]
    base_r = delta + plcnt + offs_ref[1]
    p0l = (base_l // 128) * 128
    p0r = (base_r // 128) * 128

    # both RMW window reads start immediately and fly under the matmuls;
    # neither depends on the other stream's write
    in_l = pltpu.make_async_copy(out_ref.at[:, pl.ds(p0l, win)], winl_ref,
                                 seml_ref)
    in_l.start()
    in_r = pltpu.make_async_copy(out_ref.at[:, pl.ds(p0r, win)], winr_ref,
                                 semr_ref)
    in_r.start()

    def stats(p):
        mi = (m == 1 - p).astype(jnp.int32)                # [1, block]
        used = jnp.sum(mi)
        pos = jax.lax.dot_general(
            mi.astype(jnp.int8), lt,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)              # [1, block]
        return mi, used, pos

    def place(mi, used, pos, shift):
        """Land stream rows at window lanes pos + shift (negative shifts
        simply match no lane: rows below the window never select)."""
        sel = ((jnp.broadcast_to(pos, (win, block)) + shift == iota_t)
               & jnp.broadcast_to(mi == 1, (win, block))).astype(jnp.int8)
        shifted = jax.lax.dot_general(
            pane, sel, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32)              # [R, win] i32
        keep = ((lane_w >= shift) & (lane_w < shift + used)).astype(
            jnp.int32)
        return shifted, keep

    mi_l, used_l, pos_l = stats(0)
    mi_r, used_r, pos_r = stats(1)
    shifted_l, keep_l = place(mi_l, used_l, pos_l, base_l - p0l)
    # the SAME left rows at their RIGHT-window coordinates: the VMEM-side
    # merge operand for wherever [base_l, base_l+used_l) intersects the
    # right window (whose HBM read predates the left write)
    merged_l, keep_lr = place(mi_l, used_l, pos_l, base_l - p0r)
    shifted_r, keep_r = place(mi_r, used_r, pos_r, base_r - p0r)

    in_l.wait()
    blended_l = (shifted_l * keep_l
                 + winl_ref[...].astype(jnp.int32) * (1 - keep_l))
    winl_ref[...] = blended_l.astype(jnp.int8)
    # the right read may cover lanes the left write is about to touch:
    # it must have landed before that write starts
    in_r.wait()
    out_l = pltpu.make_async_copy(winl_ref, out_ref.at[:, pl.ds(p0l, win)],
                                  seml_ref)
    out_l.start()
    # right blend (overlapping the left write-back): right rows where
    # they land, this block's fresh left rows where THEY land, pre-step
    # HBM bytes everywhere else.  keep_r and keep_lr are disjoint — all
    # fresh left lanes precede delta + plcnt <= base_r.
    patched = (merged_l * keep_lr
               + winr_ref[...].astype(jnp.int32) * (1 - keep_lr))
    blended_r = shifted_r * keep_r + patched * (1 - keep_r)
    winr_ref[...] = blended_r.astype(jnp.int8)
    # ordered write-backs: overlapping aligned paddings may carry
    # differing bytes (stale left-window tail vs merged right window) —
    # the right window's bytes must win
    out_l.wait()
    out_r = pltpu.make_async_copy(winr_ref, out_ref.at[:, pl.ds(p0r, win)],
                                  semr_ref)
    out_r.start()
    out_r.wait()

    offs_ref[0] = offs_ref[0] + used_l
    offs_ref[1] = offs_ref[1] + used_r


def partition_overlap_on() -> bool:
    """Resolved DMA-overlap schedule bit (the
    LGBM_TPU_PARTITION_NO_OVERLAP=1 A/B hatch).  Resolved OUTSIDE every
    jit boundary — partition_segment's non-jitted wrapper reads it per
    call/trace, and the program-cache key builders (gbdt/learners)
    include it so a mid-process flip retraces instead of silently
    reusing the other schedule's kernel."""
    from .. import hatches
    return not hatches.flag("LGBM_TPU_PARTITION_NO_OVERLAP")


def partition_segment(seg, mask3, delta, cnt, plcnt, *, block: int = BLOCK,
                      use_pallas: bool = False, interpret: bool = False,
                      overlap: bool = True):
    """Stable in-segment partition of ``seg``'s lanes [delta, delta+cnt).

    seg : [R, W] int8 plane pane (W a multiple of ``block``)
    mask3 : [W] int8 — 1 = goes left, 0 = goes right, -1 = outside the
        segment (those lanes are preserved untouched)
    delta, cnt, plcnt : i32 scalars — segment offset within the pane, its
        lane count, and the number of mask3==1 lanes

    Returns the pane with lanes [delta, delta+plcnt) holding the left rows
    in original relative order, [delta+plcnt, delta+cnt) the right rows,
    everything else byte-identical to the input.

    ``overlap`` (Pallas path only): overlapped window DMAs (default; the
    serialized schedule remains as the A/B reference and the
    LGBM_TPU_PARTITION_NO_OVERLAP=1 escape hatch).  Both schedules are
    bit-identical — tests/test_leafcompact.py's regression proves it
    against the oracle.

    This wrapper is deliberately NOT jitted: the env hatch must resolve
    per call/trace, and a jitted body would bake the first resolution
    into the trace cache (jit-under-jit reuses the traced jaxpr without
    re-running the python body, so an env flip would be ignored even
    when the OUTER program retraces).
    """
    from .. import costmodel, telemetry
    if use_pallas:
        overlap = overlap and partition_overlap_on()
    telemetry.count("partition/pallas" if use_pallas else "partition/xla")
    if use_pallas:
        telemetry.count("partition/dma_overlap" if overlap
                        else "partition/dma_serial")
    if costmodel.enabled():
        # analytic per-pass cost (the Pallas kernel is a custom call XLA
        # cost analysis cannot see into): the pane is read and written
        # once per partition pass — plus the selection matmuls' MACs
        # (R x W x block one-hot contractions; 3 per block overlapped,
        # 2 serialized)
        R, W = seg.shape
        costmodel.note_traced_pass(
            "partition", ("pane", R, W, block, bool(use_pallas),
                          bool(overlap)),
            bytes_moved=2.0 * R * W,
            macs=float(R) * W * block * (3 if overlap else 2))
    with telemetry.span("partition") as sp:
        return sp.fence(_partition_segment_jit(
            seg, mask3, delta, cnt, plcnt, block=block,
            use_pallas=use_pallas, interpret=interpret, overlap=overlap))


def _partition_segment_fn(seg, mask3, delta, cnt, plcnt, *, block,
                          use_pallas, interpret, overlap):
    return _partition_segment_impl(
        seg, mask3, delta, cnt, plcnt, block=block,
        use_pallas=use_pallas, interpret=interpret, overlap=overlap)


# jitted + wrapped in the cost registry: standalone (eager) partition
# calls — tests, micro-benchmarks — self-report compile seconds and
# memory analysis; under an outer trace the wrapper passes through
from .. import costmodel as _costmodel_mod  # noqa: E402

_partition_segment_jit = _costmodel_mod.instrument(
    "partition/kernel",
    jax.jit(_partition_segment_fn,
            static_argnames=("block", "use_pallas", "interpret",
                             "overlap")),
    phase="partition")


def _partition_segment_impl(seg, mask3, delta, cnt, plcnt, *, block,
                            use_pallas, interpret, overlap=True):
    # unconditional named_scope: profile_dir= traces label the kernel /
    # oracle ops "partition", matching the telemetry span and JSONL phase
    # key whether or not telemetry is armed (ISSUE 2 profiler alignment)
    with jax.named_scope("partition"):
        return _partition_segment_scoped(
            seg, mask3, delta, cnt, plcnt, block=block,
            use_pallas=use_pallas, interpret=interpret, overlap=overlap)


def _partition_segment_scoped(seg, mask3, delta, cnt, plcnt, *, block,
                              use_pallas, interpret, overlap=True):
    R, W = seg.shape
    assert W % block == 0, (W, block)
    lane = jnp.arange(W, dtype=jnp.int32)
    inseg = (lane >= delta) & (lane < delta + cnt)

    if use_pallas:
        scal = jnp.stack([delta, plcnt]).astype(jnp.int32)
        if overlap:
            kernel = functools.partial(_partition_kernel_overlap,
                                       R=R, block=block)
            scratch = [
                pltpu.VMEM((R, block + 128), jnp.int8),
                pltpu.VMEM((R, block + 128), jnp.int8),
                pltpu.SMEM((2,), jnp.int32),
                pltpu.SemaphoreType.DMA(()),
                pltpu.SemaphoreType.DMA(()),
            ]
        else:
            kernel = functools.partial(_partition_kernel, R=R, block=block)
            scratch = [
                pltpu.VMEM((R, block + 128), jnp.int8),
                pltpu.SMEM((2,), jnp.int32),
                pltpu.SemaphoreType.DMA(()),
            ]
        out = pl.pallas_call(
            kernel,
            grid=(W // block,),
            in_specs=[
                pl.BlockSpec((1, block), lambda j: (0, j)),
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec((R, block), lambda j: (0, j)),
            ],
            # HBM, not ANY: Mosaic may place ANY in VMEM, where dynamic
            # DMA lane offsets (128-aligned here) are disallowed
            out_specs=pl.BlockSpec(memory_space=_HBM_SPACE),
            out_shape=jax.ShapeDtypeStruct((R, W + block + 256), jnp.int8),
            scratch_shapes=scratch,
            compiler_params=_CompilerParams(
                dimension_semantics=("arbitrary",)),
            interpret=interpret,
        )(mask3[None, :], scal, seg)
        return jnp.where(inseg[None, :], out[:, :W], seg)

    # XLA oracle: stable sort by class (left 0, right 1, outside 2) puts
    # left+right compacted at the FRONT of the sorted pane; rolling by
    # ``delta`` aligns them with the segment's true position
    keys = jnp.where(mask3 == 1, 0, jnp.where(mask3 == 0, 1, 2))
    order = jnp.argsort(keys, stable=True)
    permuted = jnp.roll(jnp.take(seg, order, axis=1), delta, axis=1)
    return jnp.where(inseg[None, :], permuted, seg)


def pane_rows(num_features: int) -> int:
    """Plane-pane row count: F bin rows + 8 grad/hess bit-plane rows +
    validity, padded to the int8 sublane tile (Mosaic requires slices
    along the sublane dim to be 8-aligned)."""
    r = num_features + 9
    return -(-r // 8) * 8


def pack_planes(bins, grad, hess, row_mask, width: int) -> jax.Array:
    """[pane_rows(F), width] int8 plane pane: bin rows, grad/hess as 4
    int8 bit-planes each (bit-exact f32 transport through the int8
    selection matmul), validity, zero rows up to the sublane tile.  Lane
    padding beyond N is garbage — every consumer masks by segment
    extent."""
    F, N = bins.shape
    planes = [jax.lax.bitcast_convert_type(bins.astype(jnp.uint8),
                                           jnp.int8)]
    for v in (grad, hess):
        u = jax.lax.bitcast_convert_type(v.astype(jnp.float32), jnp.uint32)
        for k in range(4):
            planes.append(jax.lax.bitcast_convert_type(
                ((u >> (8 * k)) & 0xFF).astype(jnp.uint8), jnp.int8))
    planes.append(row_mask.astype(jnp.int8))
    pane = jnp.concatenate(
        [p if p.ndim == 2 else p[None, :] for p in planes], axis=0)
    return jnp.pad(pane, ((0, pane_rows(F) - (F + 9)), (0, width - N)))


def unpack_values(pane_slice, F: int):
    """(bins uint8 [F, W], grad f32 [W], hess f32 [W], valid bool [W])
    from a plane-pane slice."""
    bins = jax.lax.bitcast_convert_type(pane_slice[:F], jnp.uint8)

    def f32_of(rows):
        u = jnp.zeros(pane_slice.shape[1:], jnp.uint32)
        for k in range(4):
            b = jax.lax.bitcast_convert_type(rows[k], jnp.uint8)
            u = u | (b.astype(jnp.uint32) << (8 * k))
        return jax.lax.bitcast_convert_type(u, jnp.float32)

    grad = f32_of(pane_slice[F:F + 4])
    hess = f32_of(pane_slice[F + 4:F + 8])
    valid = pane_slice[F + 8] == 1
    return bins, grad, hess, valid


def bucket_table(n: int, block: int = BLOCK, min_width: int = 0):
    """Descending static slice widths W_0 > W_1 > ... >= max(block,
    min_width): W_0 covers the root, each next is ceil(W/2) rounded up to a
    block multiple (so a physically-smaller child of a bucket-k parent
    always fits bucket k+1)."""
    w = -(-n // block) * block
    floor_w = max(block, -(-min_width // block) * block)
    table = [w]
    while table[-1] > floor_w:
        w = -(-(table[-1] // 2) // block) * block
        table.append(max(w, floor_w))
    return tuple(table)
