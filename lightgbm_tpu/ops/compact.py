"""Streaming stable row-partition — the compacted leaf-wise grower's core op.

The reference keeps every leaf's rows CONTIGUOUS in a permuted index array
and partitions the parent's range at each split
(/root/reference/src/io/data_partition.hpp:93-139); its histogram then
touches only the leaf's own rows (dense_bin.hpp:46-112 ConstructHistogram
over an ordered index list).  A TPU can't follow row indices (XLA lowers
small-table gathers to per-row scalar addressing — measured ~85 ms per [N]
f32 gather at 11M rows, PROFILE.md), so this module moves the DATA instead
of the indices: the [R, N] int8 plane matrix (bin rows + grad/hess
bit-planes + validity) is kept physically partitioned, and each split
stably partitions the parent's lane range in one streaming sweep.

The Pallas kernel (TPU): grid = (2 passes, lane blocks), sequential.  Pass
0 compacts the left rows, pass 1 the right rows — two sweeps so a later
left write can never clobber earlier right data.  Per block the lane
compaction is pure MXU: an exclusive prefix-sum of the selection mask via
a strict-lower-triangular int8 matmul, a one-hot selection matrix built by
an iota compare, and an int8 x int8 -> int32 selection matmul that moves
whole [R, block] panes (f32 grad/hess travel bit-exactly as 4 int8
planes).  The compacted block is DMA'd to the output at a running lane
offset carried in SMEM; consecutive writes overlap-overwrite each other's
tails, so every write is a full aligned block.  Cost per partitioned row:
block x R int8 MACs + ~3 bytes of HBM traffic per plane — ~0.4% of the
histogram MACs the compaction saves (PROFILE.md).

The XLA oracle (CPU/tests): a stable argsort formulation with identical
semantics — the kernel is differentially tested against it.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

BLOCK = 2048  # partition lane block: [R<=64, 2048] int8 panes + a [2048,
              # 2048] int8 selection matrix = ~4.3 MB VMEM


def pallas_partition_ok() -> bool:
    """Eligibility of the Pallas partition kernel: TPU default backend,
    unless LGBM_TPU_NO_PALLAS=1 — the escape hatch a mixed-backend
    process (TPU backend up, computation steered onto virtual CPU
    devices, e.g. __graft_entry__.dryrun_multichip) sets so kernels
    never land on a CPU mesh.  Every outcome is counted (telemetry) —
    the runtime record of which partition route the process baked into
    its programs."""
    import os
    from .. import telemetry
    if os.environ.get("LGBM_TPU_NO_PALLAS", "") == "1":
        # count_route: this rule is re-evaluated per tree by host code, so
        # counting per outcome CHANGE keeps the counter at per-decision
        # magnitude like the trace-time counters
        telemetry.count_route("partition_ok", "partition/env_no_pallas")
        return False
    ok = jax.default_backend() == "tpu"
    telemetry.count_route("partition_ok",
                          "partition/pallas_eligible" if ok
                          else "partition/pallas_ineligible")
    return ok


def _partition_kernel(mask_ref, scal_ref, seg_ref, out_ref, win_ref,
                      offs_ref, sem_ref, *, R, block):
    """Grid (nblocks,): both streams (left then right) per lane block.

    Mosaic requires dynamic DMA lane offsets to be 128-aligned, so each
    stream writes a read-modify-write WINDOW at the aligned-down offset:
    the compacted rows are shifted to their exact in-window position by a
    one-hot shift matmul, blended with the window's current content, and
    the whole aligned window written back.  Fully serialized DMAs keep
    the left write visible to the right read (their windows may
    overlap)."""
    j = pl.program_id(0)

    @pl.when(j == 0)
    def _():
        offs_ref[0] = 0
        offs_ref[1] = 0

    delta = scal_ref[0]
    plcnt = scal_ref[1]
    win = block + 128

    # mask3 lanes: 1 = left, 0 = right, -1 = outside the segment.  All
    # compares/arithmetic run wide (int32) — Mosaic has no 8-bit vector
    # math — and cast to int8 only at the MXU operands.
    m = mask_ref[...].astype(jnp.int32)                    # [1, block]
    iota_s = jax.lax.broadcasted_iota(jnp.int32, (block, block), 0)
    iota_t = jax.lax.broadcasted_iota(jnp.int32, (win, block), 0)
    lt = (iota_s < jax.lax.broadcasted_iota(
        jnp.int32, (block, block), 1)).astype(jnp.int8)
    lane_w = jax.lax.broadcasted_iota(jnp.int32, (R, win), 1)
    pane = seg_ref[...]                                    # [R, block] int8

    for p in (0, 1):
        mi = (m == 1 - p).astype(jnp.int32)                # [1, block]
        used = jnp.sum(mi)
        # exclusive prefix sum over lanes as a strict-lower matmul
        pos = jax.lax.dot_general(
            mi.astype(jnp.int8), lt,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)              # [1, block]
        # compact + shift in ONE one-hot matmul: source lane s lands at
        # window lane pos[s] + shift
        base = delta + p * plcnt + offs_ref[p]
        p0 = (base // 128) * 128                           # aligned window
        shift = base - p0
        sel = ((jnp.broadcast_to(pos, (win, block)) + shift == iota_t)
               & jnp.broadcast_to(mi == 1, (win, block))).astype(jnp.int8)
        shifted = jax.lax.dot_general(
            pane, sel, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32)              # [R, win] i32
        # RMW: read the aligned window, blend lanes [shift, shift+used)
        dma_in = pltpu.make_async_copy(
            out_ref.at[:, pl.ds(p0, win)], win_ref, sem_ref)
        dma_in.start()
        dma_in.wait()
        keep = ((lane_w >= shift) & (lane_w < shift + used)).astype(
            jnp.int32)
        blended = (shifted * keep
                   + win_ref[...].astype(jnp.int32) * (1 - keep))
        win_ref[...] = blended.astype(jnp.int8)
        dma_out = pltpu.make_async_copy(
            win_ref, out_ref.at[:, pl.ds(p0, win)], sem_ref)
        dma_out.start()
        dma_out.wait()
        offs_ref[p] = offs_ref[p] + used


@functools.partial(jax.jit, static_argnames=("block", "use_pallas",
                                             "interpret"))
def partition_segment(seg, mask3, delta, cnt, plcnt, *, block: int = BLOCK,
                      use_pallas: bool = False, interpret: bool = False):
    """Stable in-segment partition of ``seg``'s lanes [delta, delta+cnt).

    seg : [R, W] int8 plane pane (W a multiple of ``block``)
    mask3 : [W] int8 — 1 = goes left, 0 = goes right, -1 = outside the
        segment (those lanes are preserved untouched)
    delta, cnt, plcnt : i32 scalars — segment offset within the pane, its
        lane count, and the number of mask3==1 lanes

    Returns the pane with lanes [delta, delta+plcnt) holding the left rows
    in original relative order, [delta+plcnt, delta+cnt) the right rows,
    everything else byte-identical to the input.
    """
    from .. import telemetry
    telemetry.count("partition/pallas" if use_pallas else "partition/xla")
    with telemetry.span("partition") as sp:
        return sp.fence(_partition_segment_impl(
            seg, mask3, delta, cnt, plcnt, block=block,
            use_pallas=use_pallas, interpret=interpret))


def _partition_segment_impl(seg, mask3, delta, cnt, plcnt, *, block,
                            use_pallas, interpret):
    # unconditional named_scope: profile_dir= traces label the kernel /
    # oracle ops "partition", matching the telemetry span and JSONL phase
    # key whether or not telemetry is armed (ISSUE 2 profiler alignment)
    with jax.named_scope("partition"):
        return _partition_segment_scoped(
            seg, mask3, delta, cnt, plcnt, block=block,
            use_pallas=use_pallas, interpret=interpret)


def _partition_segment_scoped(seg, mask3, delta, cnt, plcnt, *, block,
                              use_pallas, interpret):
    R, W = seg.shape
    assert W % block == 0, (W, block)
    lane = jnp.arange(W, dtype=jnp.int32)
    inseg = (lane >= delta) & (lane < delta + cnt)

    if use_pallas:
        scal = jnp.stack([delta, plcnt]).astype(jnp.int32)
        out = pl.pallas_call(
            functools.partial(_partition_kernel, R=R, block=block),
            grid=(W // block,),
            in_specs=[
                pl.BlockSpec((1, block), lambda j: (0, j)),
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec((R, block), lambda j: (0, j)),
            ],
            # HBM, not ANY: Mosaic may place ANY in VMEM, where dynamic
            # DMA lane offsets (128-aligned here) are disallowed
            out_specs=pl.BlockSpec(memory_space=pltpu.HBM),
            out_shape=jax.ShapeDtypeStruct((R, W + block + 256), jnp.int8),
            scratch_shapes=[
                pltpu.VMEM((R, block + 128), jnp.int8),
                pltpu.SMEM((2,), jnp.int32),
                pltpu.SemaphoreType.DMA(()),
            ],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("arbitrary",)),
            interpret=interpret,
        )(mask3[None, :], scal, seg)
        return jnp.where(inseg[None, :], out[:, :W], seg)

    # XLA oracle: stable sort by class (left 0, right 1, outside 2) puts
    # left+right compacted at the FRONT of the sorted pane; rolling by
    # ``delta`` aligns them with the segment's true position
    keys = jnp.where(mask3 == 1, 0, jnp.where(mask3 == 0, 1, 2))
    order = jnp.argsort(keys, stable=True)
    permuted = jnp.roll(jnp.take(seg, order, axis=1), delta, axis=1)
    return jnp.where(inseg[None, :], permuted, seg)


def pane_rows(num_features: int) -> int:
    """Plane-pane row count: F bin rows + 8 grad/hess bit-plane rows +
    validity, padded to the int8 sublane tile (Mosaic requires slices
    along the sublane dim to be 8-aligned)."""
    r = num_features + 9
    return -(-r // 8) * 8


def pack_planes(bins, grad, hess, row_mask, width: int) -> jax.Array:
    """[pane_rows(F), width] int8 plane pane: bin rows, grad/hess as 4
    int8 bit-planes each (bit-exact f32 transport through the int8
    selection matmul), validity, zero rows up to the sublane tile.  Lane
    padding beyond N is garbage — every consumer masks by segment
    extent."""
    F, N = bins.shape
    planes = [jax.lax.bitcast_convert_type(bins.astype(jnp.uint8),
                                           jnp.int8)]
    for v in (grad, hess):
        u = jax.lax.bitcast_convert_type(v.astype(jnp.float32), jnp.uint32)
        for k in range(4):
            planes.append(jax.lax.bitcast_convert_type(
                ((u >> (8 * k)) & 0xFF).astype(jnp.uint8), jnp.int8))
    planes.append(row_mask.astype(jnp.int8))
    pane = jnp.concatenate(
        [p if p.ndim == 2 else p[None, :] for p in planes], axis=0)
    return jnp.pad(pane, ((0, pane_rows(F) - (F + 9)), (0, width - N)))


def unpack_values(pane_slice, F: int):
    """(bins uint8 [F, W], grad f32 [W], hess f32 [W], valid bool [W])
    from a plane-pane slice."""
    bins = jax.lax.bitcast_convert_type(pane_slice[:F], jnp.uint8)

    def f32_of(rows):
        u = jnp.zeros(pane_slice.shape[1:], jnp.uint32)
        for k in range(4):
            b = jax.lax.bitcast_convert_type(rows[k], jnp.uint8)
            u = u | (b.astype(jnp.uint32) << (8 * k))
        return jax.lax.bitcast_convert_type(u, jnp.float32)

    grad = f32_of(pane_slice[F:F + 4])
    hess = f32_of(pane_slice[F + 4:F + 8])
    valid = pane_slice[F + 8] == 1
    return bins, grad, hess, valid


def bucket_table(n: int, block: int = BLOCK, min_width: int = 0):
    """Descending static slice widths W_0 > W_1 > ... >= max(block,
    min_width): W_0 covers the root, each next is ceil(W/2) rounded up to a
    block multiple (so a physically-smaller child of a bucket-k parent
    always fits bucket k+1)."""
    w = -(-n // block) * block
    floor_w = max(block, -(-min_width // block) * block)
    table = [w]
    while table[-1] > floor_w:
        w = -(-(table[-1] // 2) // block) * block
        table.append(max(w, floor_w))
    return tuple(table)
