"""Gradient/hessian histogram construction — the hottest kernel.

The reference's hottest loop is a CPU scatter-add over rows
(/root/reference/src/io/dense_bin.hpp:46-112, 4-way unrolled).  TPUs have no
fast scatter; the TPU-native formulation is a ONE-HOT × VALUES matmul on the
MXU:

    H[f*B + b, k] = Σ_rows  onehot(f*B + bin[f, row])[...]  ·  vals[row, k]

with ``vals = [grad, hess, 1] * mask``.  The one-hot is generated on the fly
per row-chunk (lax.scan) so it never lives in HBM at full size, and the
contraction runs over rows with fp32 accumulation (reference accumulates in
double, bin.h:15-17; fp32 + matmul tree-reduction is the deliberate TPU
precision choice).

A ``segment_sum`` backend exists for comparison/testing; matmul is default.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from .. import costmodel, hatches, telemetry

# transient one-hot working-set budget (bytes) for the chunked matmul
CHUNK_BYTE_BUDGET = 256 << 20
# virtual (pre-tiling) one-hot budget for the leaf-batched kernel
LEAFBATCH_VIRTUAL_BUDGET = 8 << 30


def _pallas_hist_ok(num_bins_max: int) -> bool:
    """THE Pallas-histogram eligibility rule, shared by the int8 and float
    dispatches: TPU backend and 8-bit bin ids (max_bin > 256 datasets
    carry int16 bins the kernel cannot ride).  Dataset WIDTH is unbounded:
    the kernel grids over VMEM-sized feature blocks
    (hist_pallas.feature_block).  LGBM_TPU_HIST_EINSUM=1 forces the XLA
    formulation for ALL dtypes (A/B timing escape hatch).

    Every outcome is counted (telemetry): routing decisions are trace-time
    events baked into the compiled program, so these counters are the
    runtime record of which kernels the process's programs actually use."""
    if hatches.flag("LGBM_TPU_HIST_EINSUM"):
        telemetry.count("hist/env_force_einsum")
        return False
    # LGBM_TPU_NO_PALLAS covers EVERY Pallas kernel (partition + these
    # histogram kernels, ops/compact.pallas_partition_ok) — the
    # mixed-backend escape hatch; HIST_EINSUM stays the A/B-timing hatch
    if hatches.flag("LGBM_TPU_NO_PALLAS"):
        telemetry.count("hist/env_no_pallas")
        return False
    ok = jax.default_backend() == "tpu" and num_bins_max <= 256
    telemetry.count("hist/pallas_eligible" if ok else "hist/pallas_ineligible")
    return ok


# ---------------------------------------------------------------------------
# Mixed-bin packing helpers (ISSUE 6).  A PackSpec (io/binning.py) says the
# [F, N] bin matrix is stored with features REORDERED into contiguous
# bin-width classes; every histogram route below then runs one pass per
# class at that class's width and reassembles the canonical feature order
# before anything downstream (split finding, subtraction caches, ownership
# scatters) sees the result.  Reassembly is zero-pad on the bin axis (a
# narrow feature's bins beyond its own num_bin are zero in the uniform
# pass too) + one gather on the feature axis — value-identical to the
# uniform single-pass histogram, cell for cell.


def _packing_active(packing) -> bool:
    return packing is not None and len(packing.widths) > 1


def _assemble_classes(parts, packing, B: int, feat_axis: int, bin_axis: int):
    """Concatenate per-class histograms (packed feature order) and gather
    back to canonical feature order.  ``parts[i]`` carries the class's
    features on ``feat_axis`` and ``widths[i]`` bins on ``bin_axis``."""
    padded = []
    for part, (_, _, width) in zip(parts, packing.ranges):
        if width < B:
            widths = [(0, 0)] * part.ndim
            widths[bin_axis] = (0, B - width)
            part = jnp.pad(part, widths)
        padded.append(part)
    packed = jnp.concatenate(padded, axis=feat_axis)
    c2p = jnp.asarray(packing.c2p, jnp.int32)
    return jnp.take(packed, c2p, axis=feat_axis)


def _unpack_bins(bins, packing):
    """[F, N] packed bin matrix -> canonical feature order (oracle paths:
    one F-row gather buys exact uniform-path semantics for free)."""
    return jnp.take(bins, jnp.asarray(packing.c2p, jnp.int32), axis=0)


def _einsum_chunk(chunk: int, F: int, B: int, itemsize: int, N: int) -> int:
    """The leaf-batched einsum's effective row-chunk resolution rule,
    factored out so the packed driver can pin every per-class pass to the
    UNIFORM pass's chunk boundaries: f32 per-cell sums accumulate across
    scan chunks, so identical chunking is what makes packed == uniform
    bit-identical on the XLA routes (a per-class budget would allow larger
    chunks — smaller F*B — and regroup the adds)."""
    budget_rows = max(LEAFBATCH_VIRTUAL_BUDGET // (F * B * itemsize), 256)
    chunk = min(chunk, -(-budget_rows // 256) * 256)
    return min(chunk, max(256, -(-N // 256) * 256))


def dense_pass_cost(N: int, F: int, B: int, num_cols: int):
    """Analytic cost of ONE leaf-batched histogram pass — the dense
    one-hot-matmul MAC count PROFILE.md's roofline derives by hand
    (N x F x B x lanes per group; the MXU tile floor makes <=42 leaf
    columns cost 128 lanes, 43-64 ride a 192-lane operand) and the HBM
    bytes streamed (int8 bins + the packed per-row side-band, re-read
    once per group, + the per-group accumulator write-back).  Wider
    levels are modeled on the PALLAS grouping rule — balanced groups of
    <=64 columns (hist_pallas._grouped(group_width=64); the XLA einsum
    fallback groups by 42, but the analytic note exists for the Pallas
    routes cost analysis cannot see into).  Filed per traced pass via
    costmodel.note_traced_pass."""
    if num_cols <= 42:
        groups, lanes = 1, 128.0
    elif num_cols <= 64:
        groups, lanes = 1, 192.0
    else:
        groups = -(-num_cols // 64)
        width = -(-num_cols // groups)
        lanes = 128.0 if width <= 42 else 192.0
    macs = float(N) * F * B * lanes * groups
    bytes_moved = (groups * (float(N) * F + 4.0 * N)
                   + groups * float(F) * B * lanes * 4.0)
    return macs, bytes_moved


def _note_hist_pass(bins, num_cols: int, num_bins_max: int,
                    compute_dtype, packing=None) -> None:
    """Analytic roofline note(s) for one leaf-batched pass.  Under mixed-bin
    packing the pass is really one pass PER bin-width class, so one note is
    filed per class (keyed ``binclass<width>``) — PROFILE.md's roofline rows
    then attribute narrow- and wide-class cost separately instead of
    pricing every feature at the uniform worst case."""
    if not costmodel.enabled():
        return
    F, N = bins.shape
    dt = getattr(compute_dtype, "__name__", None) or str(compute_dtype)
    if _packing_active(packing):
        for _, cnt, width in packing.ranges:
            macs, bytes_moved = dense_pass_cost(N, cnt, width, num_cols)
            costmodel.note_traced_pass(
                "histogram",
                ("pass", N, cnt, width, num_cols, dt,
                 "binclass%d" % width),
                macs=macs, bytes_moved=bytes_moved)
        return
    macs, bytes_moved = dense_pass_cost(N, F, num_bins_max, num_cols)
    costmodel.note_traced_pass(
        "histogram", ("pass", N, F, num_bins_max, num_cols, dt),
        macs=macs, bytes_moved=bytes_moved)


def _feat_take(hist, feat_gather, axis: int):
    """Apply the traced storage->canonical feature gather (block-local
    mixed-bin packing, ISSUE 12).  For float accumulators the placement
    is free — every cell is a finished sum — and for the quantized paths
    the gather runs IN THE INT DOMAIN inside the kernel drivers
    (ops/hist_pallas), so the dequantize->search f32 graph is
    shape-identical to the uniform layout's and XLA's FMA-contraction
    choices cannot diverge between the two programs."""
    if feat_gather is None:
        return hist
    return jnp.take(hist, feat_gather, axis=axis)


def histogram_matmul(bins: jax.Array, grad: jax.Array, hess: jax.Array,
                     mask: jax.Array, num_bins_max: int,
                     chunk: int = 16384,
                     compute_dtype=jnp.float32, packing=None,
                     feat_gather=None) -> jax.Array:
    """Build per-feature histograms for the masked row subset.

    Parameters
    ----------
    bins : [F, N] integer bin matrix
    grad, hess : [N] float32
    mask : [N] bool/float — row inclusion (leaf membership × bagging)
    num_bins_max : static B (histogram width per feature)

    Returns
    -------
    hist : [F, B, 3] float32 — (sum_grad, sum_hess, count) per bin, matching
    HistogramBinEntry (bin.h:20-42).
    """
    telemetry.count("hist/xla_matmul")
    with telemetry.span("histogram") as sp:
        if _packing_active(packing):
            # one pass per bin-width class; the per-class chunk is pinned
            # to the UNIFORM pass's resolved chunk so the scan's per-cell
            # f32 accumulation groups identically (bit-identity)
            F = bins.shape[0]
            budget_rows = max(
                CHUNK_BYTE_BUDGET // (F * num_bins_max * 4), 256)
            eff_chunk = min(chunk, -(-budget_rows // 256) * 256)
            telemetry.count("hist/mixedbin_matmul")
            parts = []
            for start, cnt, width in packing.ranges:
                parts.append(_histogram_matmul_impl(
                    jax.lax.slice_in_dim(bins, start, start + cnt, axis=0),
                    grad, hess, mask, width, eff_chunk, compute_dtype))
            return sp.fence(_feat_take(_assemble_classes(
                parts, packing, num_bins_max, feat_axis=0, bin_axis=1),
                feat_gather, 0))
        return sp.fence(_feat_take(_histogram_matmul_impl(
            bins, grad, hess, mask, num_bins_max, chunk, compute_dtype),
            feat_gather, 0))


def _histogram_matmul_impl(bins, grad, hess, mask, num_bins_max, chunk,
                           compute_dtype) -> jax.Array:
    # named_scope is UNCONDITIONAL (unlike the telemetry span wrapping the
    # caller): a profile_dir= Perfetto trace labels these ops "histogram"
    # whether or not telemetry is armed, and the scope is always present
    # so telemetry on/off cannot change the traced program's identity
    with jax.named_scope("histogram"):
        return _histogram_matmul_scoped(bins, grad, hess, mask,
                                        num_bins_max, chunk, compute_dtype)


def _histogram_matmul_scoped(bins, grad, hess, mask, num_bins_max, chunk,
                             compute_dtype) -> jax.Array:
    F, N = bins.shape
    B = num_bins_max
    # bound the transient one-hot working set ([F, chunk, B] floats) by a
    # byte budget so wide datasets don't OOM; the chunk arg is a ceiling
    budget_rows = max(CHUNK_BYTE_BUDGET // (F * B * 4), 256)
    chunk = min(chunk, -(-budget_rows // 256) * 256)
    maskf = mask.astype(compute_dtype)
    vals = jnp.stack([grad.astype(compute_dtype) * maskf,
                      hess.astype(compute_dtype) * maskf,
                      maskf], axis=1)  # [N, 3]

    if N <= chunk:
        return _onehot_chunk(bins.astype(jnp.int32), vals, B, compute_dtype)

    pad = (-N) % chunk
    if pad:
        bins = jnp.pad(bins, ((0, 0), (0, pad)))
        vals = jnp.pad(vals, ((0, pad), (0, 0)))
    n_chunks = (N + pad) // chunk
    bins_c = bins.reshape(F, n_chunks, chunk).transpose(1, 0, 2)  # [n, F, C]
    vals_c = vals.reshape(n_chunks, chunk, 3)

    def body(carry, xs):
        b_chunk, v_chunk = xs
        carry = carry + _onehot_chunk(b_chunk.astype(jnp.int32), v_chunk, B,
                                      compute_dtype)
        return carry, None

    # the cross-chunk accumulator stays f32 regardless of compute_dtype:
    # only the matmul OPERANDS are lowered (counts in the thousands are not
    # representable in bf16)
    init = jnp.zeros((F, B, 3), dtype=jnp.float32)
    hist, _ = jax.lax.scan(body, init, (bins_c, vals_c))
    return hist


def _onehot_chunk(bins_chunk: jax.Array, vals_chunk: jax.Array, B: int,
                  compute_dtype) -> jax.Array:
    """One chunk: [F, C] bins + [C, 3] vals -> [F, B, 3] f32 partial
    histogram (operands in compute_dtype, accumulation always f32).

    The einsum contracts over rows; output layout [F*B, 3] keeps the large
    dimension on the MXU lane axis.
    """
    F, C = bins_chunk.shape
    iota = jax.lax.broadcasted_iota(jnp.int32, (F, C, B), 2)
    onehot = (bins_chunk[:, :, None] == iota).astype(compute_dtype)  # [F, C, B]
    # [3, C] @ [C, F*B] -> [3, F*B]
    flat = onehot.transpose(1, 0, 2).reshape(C, F * B)
    out = jnp.dot(vals_chunk.astype(compute_dtype).T, flat,
                  preferred_element_type=jnp.float32)  # [3, F*B]
    return out.reshape(3, F, B).transpose(1, 2, 0)


def histogram_leafbatch(bins: jax.Array, grad: jax.Array, hess: jax.Array,
                        col_id: jax.Array, col_ok: jax.Array, num_cols: int,
                        num_bins_max: int, chunk: int = 65536,
                        compute_dtype=jnp.bfloat16,
                        axis_name=None, int_reduce=None,
                        salt=0, packing=None,
                        feat_gather=None) -> jax.Array:
    """Build histograms for MANY leaves in ONE matmul pass.

    The single-leaf one-hot matmul starves the MXU: the value operand has
    only 3 columns (grad/hess/count) of a 128-wide tile.  Batching C leaves
    widens it to 3·C columns, so one pass over the data builds C histograms
    for (measured) roughly the cost of one — the enabler for the depthwise
    grower, which needs all leaves of a tree level at once instead of the
    reference's one-leaf-at-a-time rebuild (serial_tree_learner.cpp:262-283).

    Parameters
    ----------
    bins : [F, N] integer bin matrix
    grad, hess : [N] f32
    col_id : [N] i32 — histogram column (leaf slot) per row
    col_ok : [N] bool — row participates (bagging mask ∧ slot-is-active)
    num_cols : static C — number of histogram columns

    Returns
    -------
    hist : [C, F, B, 3] f32

    ``packing`` (io/binning.PackSpec, static): mixed-bin layout — ``bins``
    is stored in packed (bin-width-class) feature order; every route below
    runs one pass per class at that class's width and returns the
    CANONICAL-order histogram, value-identical to the uniform pass.
    """
    if _packing_active(packing):
        telemetry.count("hist/mixedbin_leafbatch")
    _note_hist_pass(bins, num_cols, num_bins_max, compute_dtype,
                    packing=packing)
    if str(compute_dtype).startswith("int8"):
        # quantized-gradient path: Pallas int8-MXU kernel on TPU, the
        # bit-identical XLA formulation elsewhere (ops/hist_pallas.py).
        # The Pallas kernel carries bins as int8 bit-patterns, so bin ids
        # must fit 8 bits — max_bin > 256 datasets (int16 bins) take the
        # XLA int formulation instead.  "int8_sr" = unbiased stochastic
        # rounding (value-keyed deterministic bits).
        stochastic = compute_dtype == "int8_sr"
        from .hist_pallas import hist_pallas_leafbatch, hist_quant_xla
        if _pallas_hist_ok(num_bins_max):
            telemetry.count("hist/pallas_int8")
            with telemetry.span("histogram") as sp:
                return sp.fence(hist_pallas_leafbatch(
                    bins, grad, hess, col_id, col_ok, num_cols,
                    num_bins_max, axis_name=axis_name,
                    int_reduce=int_reduce, stochastic=stochastic,
                    salt=salt, packing=packing, feat_gather=feat_gather))
        telemetry.count("hist/xla_int8")
        with telemetry.span("histogram") as sp:
            return sp.fence(hist_quant_xla(
                bins, grad, hess, col_id, col_ok, num_cols, num_bins_max,
                chunk=chunk, axis_name=axis_name, int_reduce=int_reduce,
                stochastic=stochastic, salt=salt, packing=packing,
                feat_gather=feat_gather))
    # float dtypes on TPU: hand-scheduled Pallas kernel with bf16 operands
    # (f32 rides a hi/lo operand split — one 5-stat pass for narrow
    # levels, two 3-stat passes wider).  This routes AROUND the XLA
    # one-hot-einsum lowering, whose fast path regressed ~27x in this
    # environment (BASELINE.md round-3 addendum) — and is the faster
    # schedule even on a healthy runtime.  Width is handled inside the
    # kernel (VMEM-sized feature-block grid); max_bin > 256 datasets
    # carry int16 bins and stay on the einsum.  axis_name is deliberately
    # NOT handled here: float reductions ride the caller's hist_reduce
    # hook, exactly like the einsum branch below.
    if _pallas_hist_ok(num_bins_max):
        from .hist_pallas import hist_pallas_float_leafbatch
        precision = ("bf16" if compute_dtype == jnp.bfloat16 else "f32")
        telemetry.count("hist/pallas_" + precision)
        with telemetry.span("histogram") as sp:
            return sp.fence(_feat_take(hist_pallas_float_leafbatch(
                bins, grad, hess, col_id, col_ok, num_cols, num_bins_max,
                precision=precision, packing=packing), feat_gather, 1))
    telemetry.count("hist/xla_einsum")
    with jax.named_scope("histogram"), telemetry.span("histogram") as sp:
        if _packing_active(packing):
            # per-class einsum passes at the uniform pass's resolved chunk
            # (identical scan grouping -> bit-identical f32 cells)
            eff_chunk = _einsum_chunk(chunk, bins.shape[0], num_bins_max,
                                      jnp.dtype(compute_dtype).itemsize,
                                      bins.shape[1])
            parts = []
            for start, cnt, width in packing.ranges:
                parts.append(_leafbatch_einsum(
                    jax.lax.slice_in_dim(bins, start, start + cnt, axis=0),
                    grad, hess, col_id, col_ok, num_cols, width,
                    chunk=eff_chunk, compute_dtype=compute_dtype))
            return sp.fence(_feat_take(_assemble_classes(
                parts, packing, num_bins_max, feat_axis=1, bin_axis=2),
                feat_gather, 1))
        return sp.fence(_feat_take(_leafbatch_einsum(
            bins, grad, hess, col_id, col_ok, num_cols, num_bins_max,
            chunk=chunk, compute_dtype=compute_dtype), feat_gather, 1))


def _leafbatch_einsum(bins, grad, hess, col_id, col_ok, num_cols: int,
                      num_bins_max: int, chunk: int = 65536,
                      compute_dtype=jnp.bfloat16) -> jax.Array:
    """The XLA one-hot-einsum leaf-batched formulation (CPU / testing
    oracle and the forced-fallback route)."""
    F, N = bins.shape
    B = num_bins_max
    # cap the pass at ONE 128-lane tile of the value operand (42 histogram
    # columns × 3): a C=64 pass costs ~2x what two 42-wide passes do on v5e
    # (the conv-lowered kernel's cost grows superlinearly past a tile), so
    # wide levels loop single-tile groups, balanced so the last group is
    # never a nearly-empty full-row pass (128 -> 4x32, not 42/42/42/2)
    if num_cols > 42:
        n_groups = -(-num_cols // 42)
        width = -(-num_cols // n_groups)
        parts = []
        for base in range(0, num_cols, width):
            k = min(width, num_cols - base)
            ok = col_ok & (col_id >= base) & (col_id < base + k)
            parts.append(_leafbatch_einsum(
                bins, grad, hess, col_id - base, ok, k, num_bins_max,
                chunk=chunk, compute_dtype=compute_dtype))
        return jnp.concatenate(parts, axis=0)
    # keep the value operand >= ~126 columns so the MXU tile is full even
    # for small levels (cols are zero-padded; wasted cols are free compared
    # to a starved tile)
    C = max(num_cols, 42)
    okf = col_ok.astype(jnp.float32)
    vals = jnp.stack([grad.astype(jnp.float32) * okf,
                      hess.astype(jnp.float32) * okf,
                      okf], axis=1)  # [N, 3]

    # big chunks amortize per-scan-iteration launch overhead; small inputs
    # use a single chunk of their own (padded) size.  XLA tiles the one-hot
    # einsum operand rather than materializing [F, chunk, B] (validated at
    # 7.5 GB virtual on a 16 GB chip), but clamp the virtual size anyway so
    # very wide datasets degrade to smaller chunks instead of risking OOM.
    chunk = _einsum_chunk(chunk, F, B, jnp.dtype(compute_dtype).itemsize, N)
    pad = (-N) % chunk
    if pad:
        bins = jnp.pad(bins, ((0, 0), (0, pad)))
        vals = jnp.pad(vals, ((0, pad), (0, 0)))
        col_id = jnp.pad(col_id, (0, pad), constant_values=-1)
    n_chunks = (N + pad) // chunk
    bins_c = bins.astype(jnp.int32).reshape(F, n_chunks, chunk).transpose(1, 0, 2)
    vals_c = vals.astype(compute_dtype).reshape(n_chunks, chunk, 3)
    cid_c = col_id.astype(jnp.int32).reshape(n_chunks, chunk)
    ib = jnp.arange(B, dtype=jnp.int32)
    ic = jnp.arange(C, dtype=jnp.int32)

    def body(carry, xs):
        bc, vc, cc = xs
        oh = (bc[:, :, None] == ib).astype(compute_dtype)        # [F, C_rows, B]
        lsel = (cc[:, None] == ic).astype(compute_dtype)         # [C_rows, C]
        vL = (lsel[:, :, None] * vc[:, None, :]).reshape(chunk, C * 3)
        out = jnp.einsum("fcb,ck->fbk", oh, vL,
                         preferred_element_type=jnp.float32)     # [F, B, 3C]
        return carry + out, None

    init = jnp.zeros((F, B, C * 3), jnp.float32)
    # unroll: several chunks per loop iteration lets the scheduler overlap
    # the next chunk's HBM loads with the current chunk's compute
    hist, _ = jax.lax.scan(body, init, (bins_c, vals_c, cid_c),
                           unroll=min(4, n_chunks))
    hist = hist.reshape(F, B, C, 3).transpose(2, 0, 1, 3)        # [C, F, B, 3]
    return hist[:num_cols]


def histogram_leafbatch_segsum(bins, grad, hess, col_id, col_ok,
                               num_cols: int, num_bins_max: int,
                               chunk: int = 0, compute_dtype=None,
                               axis_name=None, int_reduce=None, salt=0,
                               packing=None, feat_gather=None):
    """Scatter-add leaf-batched histogram — CPU-fast oracle with the same
    [C, F, B, 3] contract as histogram_leafbatch (scatter beats the dense
    one-hot matmul off-TPU; summation ORDER differs, so f32 sums match the
    matmul only to reduction noise).  ``packing``: the oracle just
    un-permutes the packed bin matrix first — one F-row gather buys exact
    uniform-path semantics."""
    if _packing_active(packing):
        bins = _unpack_bins(bins, packing)
    F, N = bins.shape
    B = num_bins_max
    C = num_cols
    okf = col_ok.astype(jnp.float32)
    cid = jnp.where(col_ok, col_id, C).astype(jnp.int32)  # C = drop bucket
    ids = (cid[None, :] * F + jnp.arange(F, dtype=jnp.int32)[:, None]) * B \
        + bins.astype(jnp.int32)
    vals = jnp.stack([grad * okf, hess * okf, okf], axis=1)      # [N, 3]
    vals = jnp.broadcast_to(vals[None], (F, N, 3)).reshape(-1, 3)
    hist = jax.ops.segment_sum(vals, ids.reshape(-1),
                               num_segments=(C + 1) * F * B)
    return _feat_take(hist.reshape(C + 1, F, B, 3)[:C], feat_gather, 1)


def hist_quant_segsum(bins, grad, hess, col_id, col_ok, num_cols: int,
                      num_bins_max: int, chunk: int = 0, rng_bits=None,
                      compute_dtype=None, axis_name=None, int_reduce=None,
                      salt=0, packing=None, feat_gather=None):
    """Scatter-add variant of the quantized-gradient histogram — exact
    int32 accumulation, so it is bit-identical to hist_pallas/hist_quant_xla
    (ops/hist_pallas.py) at any summation order; the CPU-fast oracle for
    int8-path quality tests."""
    from .hist_pallas import quantize_values
    if _packing_active(packing):
        bins = _unpack_bins(bins, packing)
    F, N = bins.shape
    B = num_bins_max
    C = num_cols
    vals, scale = quantize_values(grad, hess, col_ok, rng_bits,
                                  axis_name=axis_name,
                                  stochastic=(compute_dtype == "int8_sr"),
                                  salt=salt)                # [3, N] i8
    cid = jnp.where(col_ok, col_id, C).astype(jnp.int32)
    ids = (cid[None, :] * F + jnp.arange(F, dtype=jnp.int32)[:, None]) * B \
        + bins.astype(jnp.int32)
    v = jnp.broadcast_to(vals.T.astype(jnp.int32)[None],
                         (F, N, 3)).reshape(-1, 3)
    hist = jax.ops.segment_sum(v, ids.reshape(-1),
                               num_segments=(C + 1) * F * B)
    if axis_name is not None:
        from .. import telemetry
        telemetry.record_collective("hist/int8_segsum_psum", "psum",
                                    axis_name, telemetry._tree_nbytes(hist))
        hist = jax.lax.psum(hist, axis_name)   # int-domain cross-shard sum
    hist = _feat_take(hist.reshape(C + 1, F, B, 3)[:C], feat_gather, 1)
    return hist.astype(jnp.float32) * scale


def histogram_segsum(bins: jax.Array, grad: jax.Array, hess: jax.Array,
                     mask: jax.Array, num_bins_max: int,
                     packing=None, feat_gather=None) -> jax.Array:
    """Scatter-add backend (CPU-friendly, used by tests as an oracle)."""
    if _packing_active(packing):
        bins = _unpack_bins(bins, packing)
    F, N = bins.shape
    B = num_bins_max
    maskf = mask.astype(jnp.float32)
    ids = bins.astype(jnp.int32) + (jnp.arange(F, dtype=jnp.int32) * B)[:, None]
    ids = ids.reshape(-1)  # [F*N]
    vals = jnp.stack([grad * maskf, hess * maskf, maskf], axis=1)  # [N, 3]
    vals = jnp.broadcast_to(vals[None], (F, N, 3)).reshape(-1, 3)
    hist = jax.ops.segment_sum(vals, ids, num_segments=F * B)
    return _feat_take(hist.reshape(F, B, 3), feat_gather, 0)


def build_histogram(bins, grad, hess, mask, num_bins_max, *,
                    backend: str = "matmul", chunk: int = 16384,
                    compute_dtype=jnp.float32, axis_name=None,
                    int_reduce=None, salt=0, packing=None,
                    feat_gather=None) -> jax.Array:
    """``int_reduce``: optional int-domain cross-shard reduction for the
    quantized path (feature axis 0) — the data-parallel reduce_scatter
    ownership schedule passes a psum_scatter here so the accumulators are
    scattered WITHOUT leaving the exact int domain.  ``packing``: static
    mixed-bin layout spec (see histogram_leafbatch)."""
    if str(compute_dtype).startswith("int8"):
        # single-leaf quantized pass == leaf-batched with one column
        N = bins.shape[1]
        cid = jnp.zeros((N,), jnp.int32)
        out = histogram_leafbatch(bins, grad, hess, cid, mask, 1,
                                  num_bins_max, chunk=chunk,
                                  compute_dtype=compute_dtype,
                                  axis_name=axis_name,
                                  int_reduce=int_reduce, salt=salt,
                                  packing=packing, feat_gather=feat_gather)
        return out[0]
    if backend == "matmul":
        if _pallas_hist_ok(num_bins_max):
            # single-leaf float pass on TPU: one-column leafbatch hits the
            # Pallas kernel (the leaf-wise f32 path rides the same einsum
            # the regression broke; MXU cost is identical either way — the
            # value tile is 128 lanes minimum)
            cid = jnp.zeros((bins.shape[1],), jnp.int32)
            out = histogram_leafbatch(bins, grad, hess, cid, mask, 1,
                                      num_bins_max, chunk=chunk,
                                      compute_dtype=compute_dtype,
                                      packing=packing,
                                      feat_gather=feat_gather)
            return out[0]
        return histogram_matmul(bins, grad, hess, mask, num_bins_max,
                                chunk=chunk, compute_dtype=compute_dtype,
                                packing=packing, feat_gather=feat_gather)
    if backend == "segsum":
        return histogram_segsum(bins, grad, hess, mask, num_bins_max,
                                packing=packing, feat_gather=feat_gather)
    raise ValueError(f"unknown histogram backend {backend!r}")
