"""Gradient/hessian histogram construction — the hottest kernel.

The reference's hottest loop is a CPU scatter-add over rows
(/root/reference/src/io/dense_bin.hpp:46-112, 4-way unrolled).  TPUs have no
fast scatter; the TPU-native formulation is a ONE-HOT × VALUES matmul on the
MXU:

    H[f*B + b, k] = Σ_rows  onehot(f*B + bin[f, row])[...]  ·  vals[row, k]

with ``vals = [grad, hess, 1] * mask``.  The one-hot is generated on the fly
per row-chunk (lax.scan) so it never lives in HBM at full size, and the
contraction runs over rows with fp32 accumulation (reference accumulates in
double, bin.h:15-17; fp32 + matmul tree-reduction is the deliberate TPU
precision choice).

A ``segment_sum`` backend exists for comparison/testing; matmul is default.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

# transient one-hot working-set budget (bytes) for the chunked matmul
CHUNK_BYTE_BUDGET = 256 << 20


def histogram_matmul(bins: jax.Array, grad: jax.Array, hess: jax.Array,
                     mask: jax.Array, num_bins_max: int,
                     chunk: int = 16384,
                     compute_dtype=jnp.float32) -> jax.Array:
    """Build per-feature histograms for the masked row subset.

    Parameters
    ----------
    bins : [F, N] integer bin matrix
    grad, hess : [N] float32
    mask : [N] bool/float — row inclusion (leaf membership × bagging)
    num_bins_max : static B (histogram width per feature)

    Returns
    -------
    hist : [F, B, 3] float32 — (sum_grad, sum_hess, count) per bin, matching
    HistogramBinEntry (bin.h:20-42).
    """
    F, N = bins.shape
    B = num_bins_max
    # bound the transient one-hot working set ([F, chunk, B] floats) by a
    # byte budget so wide datasets don't OOM; the chunk arg is a ceiling
    budget_rows = max(CHUNK_BYTE_BUDGET // (F * B * 4), 256)
    chunk = min(chunk, -(-budget_rows // 256) * 256)
    maskf = mask.astype(compute_dtype)
    vals = jnp.stack([grad.astype(compute_dtype) * maskf,
                      hess.astype(compute_dtype) * maskf,
                      maskf], axis=1)  # [N, 3]

    if N <= chunk:
        hist = _onehot_chunk(bins.astype(jnp.int32), vals, B, compute_dtype)
        return hist.astype(jnp.float32)

    pad = (-N) % chunk
    if pad:
        bins = jnp.pad(bins, ((0, 0), (0, pad)))
        vals = jnp.pad(vals, ((0, pad), (0, 0)))
    n_chunks = (N + pad) // chunk
    bins_c = bins.reshape(F, n_chunks, chunk).transpose(1, 0, 2)  # [n, F, C]
    vals_c = vals.reshape(n_chunks, chunk, 3)

    def body(carry, xs):
        b_chunk, v_chunk = xs
        carry = carry + _onehot_chunk(b_chunk.astype(jnp.int32), v_chunk, B,
                                      compute_dtype)
        return carry, None

    init = jnp.zeros((F, B, 3), dtype=compute_dtype)
    hist, _ = jax.lax.scan(body, init, (bins_c, vals_c))
    return hist.astype(jnp.float32)


def _onehot_chunk(bins_chunk: jax.Array, vals_chunk: jax.Array, B: int,
                  compute_dtype) -> jax.Array:
    """One chunk: [F, C] bins + [C, 3] vals -> [F, B, 3] partial histogram.

    The einsum contracts over rows; output layout [F*B, 3] keeps the large
    dimension on the MXU lane axis.
    """
    F, C = bins_chunk.shape
    iota = jax.lax.broadcasted_iota(jnp.int32, (F, C, B), 2)
    onehot = (bins_chunk[:, :, None] == iota).astype(compute_dtype)  # [F, C, B]
    # [3, C] @ [C, F*B] -> [3, F*B]
    flat = onehot.transpose(1, 0, 2).reshape(C, F * B)
    out = jnp.dot(vals_chunk.T, flat,
                  preferred_element_type=jnp.float32)  # [3, F*B]
    return out.reshape(3, F, B).transpose(1, 2, 0).astype(compute_dtype)


def histogram_segsum(bins: jax.Array, grad: jax.Array, hess: jax.Array,
                     mask: jax.Array, num_bins_max: int) -> jax.Array:
    """Scatter-add backend (CPU-friendly, used by tests as an oracle)."""
    F, N = bins.shape
    B = num_bins_max
    maskf = mask.astype(jnp.float32)
    ids = bins.astype(jnp.int32) + (jnp.arange(F, dtype=jnp.int32) * B)[:, None]
    ids = ids.reshape(-1)  # [F*N]
    vals = jnp.stack([grad * maskf, hess * maskf, maskf], axis=1)  # [N, 3]
    vals = jnp.broadcast_to(vals[None], (F, N, 3)).reshape(-1, 3)
    hist = jax.ops.segment_sum(vals, ids, num_segments=F * B)
    return hist.reshape(F, B, 3)


def build_histogram(bins, grad, hess, mask, num_bins_max, *,
                    backend: str = "matmul", chunk: int = 16384,
                    compute_dtype=jnp.float32) -> jax.Array:
    if backend == "matmul":
        return histogram_matmul(bins, grad, hess, mask, num_bins_max,
                                chunk=chunk, compute_dtype=compute_dtype)
    if backend == "segsum":
        return histogram_segsum(bins, grad, hess, mask, num_bins_max)
    raise ValueError(f"unknown histogram backend {backend!r}")
