"""Best-split search over histograms.

Vectorized re-design of FeatureHistogram::FindBestThreshold
(/root/reference/src/treelearner/feature_histogram.hpp:106-165): the
right-to-left scan becomes a cumulative sum over the bin axis plus a masked
argmax — embarrassingly parallel over features × thresholds on the VPU.

Parity-critical semantics preserved:
- threshold t means "bin <= t goes left"; candidate thresholds are
  0 .. num_bin-2 (the reference scans t = num_bins-1 .. 1 and stores t-1).
- kEpsilon hessian padding: the leaf total gets +2ε, each side +ε
  (feature_histogram.hpp:53, 113, 128).
- constraints: both sides need >= min_data_in_leaf rows and
  >= min_sum_hessian_in_leaf hessian mass (lines 123-131).
- a candidate must reach gain >= gain_shift (line 137); reported gain is
  ``best_gain - gain_shift`` (line 164).
- tie-breaks: within a feature the LARGER threshold wins (right-to-left scan
  updates only on strictly-greater, line 143); across features the SMALLER
  feature index wins (split_info.hpp:98-103).
- split gain g²/h, leaf output −g/h (lines 219-231; no L1/L2 terms in this
  reference snapshot).
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from .. import telemetry

K_EPSILON = 1e-15  # meta.h kEpsilon
NEG_INF = -jnp.inf


class SplitResult(NamedTuple):
    """Best split across features for one leaf (SplitInfo,
    split_info.hpp:17-54)."""
    gain: jax.Array          # f32 scalar; -inf when unsplittable
    feature: jax.Array       # i32 inner feature index
    threshold: jax.Array     # i32 bin threshold
    left_output: jax.Array   # f32
    right_output: jax.Array
    left_count: jax.Array    # i32
    right_count: jax.Array
    left_sum_grad: jax.Array
    left_sum_hess: jax.Array  # raw (no epsilon)
    right_sum_grad: jax.Array
    right_sum_hess: jax.Array


def find_best_split(hist: jax.Array, sum_grad: jax.Array, sum_hess: jax.Array,
                    num_data: jax.Array, num_bins: jax.Array,
                    feature_mask: jax.Array, min_data_in_leaf: float,
                    min_sum_hessian_in_leaf: float) -> SplitResult:
    """Find the best split over all features of one leaf.

    Parameters
    ----------
    hist : [F, B, 3] float32 (sum_grad, sum_hess, count)
    sum_grad, sum_hess, num_data : leaf totals (raw, no epsilon)
    num_bins : [F] int32 — real bin count per feature (B is padded)
    feature_mask : [F] bool — feature_fraction sampling / ownership masks

    Mixed-bin invariant (ISSUE 6): under feature packing the histogram
    routes hand back CANONICAL feature order with narrow-class features
    zero-padded from their class width up to B — exactly the zeros the
    uniform pass puts there (no row carries a bin >= the feature's own
    num_bin), and the ``thresholds <= num_bins - 2`` validity mask below
    never admits the padding as a candidate.  This function therefore
    needs no packing awareness, and the across-feature argmax tie-break
    (smaller CANONICAL index wins) is identical packed or not.
    """
    with telemetry.span("split_find") as sp:
        return sp.fence(_find_best_split_impl(
            hist, sum_grad, sum_hess, num_data, num_bins, feature_mask,
            min_data_in_leaf, min_sum_hessian_in_leaf))


def _find_best_split_impl(hist, sum_grad, sum_hess, num_data, num_bins,
                          feature_mask, min_data_in_leaf,
                          min_sum_hessian_in_leaf) -> SplitResult:
    # unconditional named_scope: profile_dir= traces label these ops
    # "split_find" — the same key as the telemetry span/JSONL records —
    # with or without telemetry armed (ISSUE 2 profiler alignment)
    with jax.named_scope("split_find"):
        return _find_best_split_scoped(
            hist, sum_grad, sum_hess, num_data, num_bins, feature_mask,
            min_data_in_leaf, min_sum_hessian_in_leaf)


def _threshold_scan(hist, sum_grad, sum_hess, num_data, num_bins,
                    feature_mask, min_data_in_leaf,
                    min_sum_hessian_in_leaf):
    """Shared [F, B] threshold scan: cumulative left sums, the validity
    mask and the per-candidate gain score — the common core of the full
    best-split search and the voting learner's per-feature local gains.
    Returns (cg, ch, cc, score, gain_shift)."""
    F, B, _ = hist.shape
    eps = jnp.float32(K_EPSILON)

    cg = jnp.cumsum(hist[:, :, 0], axis=1)   # [F, B] left sums at threshold t
    ch = jnp.cumsum(hist[:, :, 1], axis=1)
    cc = jnp.cumsum(hist[:, :, 2], axis=1)

    total_g = sum_grad.astype(jnp.float32)
    total_h = sum_hess.astype(jnp.float32)
    total_c = num_data.astype(jnp.float32)

    # per threshold t (bin <= t left):
    left_g = cg
    left_h = ch + eps                        # raw_left + ε
    left_c = cc
    right_g = total_g - cg
    right_h = (total_h - ch) + eps           # raw_right + ε
    right_c = total_c - cc

    thresholds = jnp.arange(B, dtype=jnp.int32)
    valid = (
        (right_c >= min_data_in_leaf)
        & (left_c >= min_data_in_leaf)
        & (right_h >= min_sum_hessian_in_leaf)
        & (left_h >= min_sum_hessian_in_leaf)
        & (thresholds[None, :] <= (num_bins[:, None] - 2))
        & feature_mask[:, None]
    )

    gain_shift = _leaf_split_gain(total_g, total_h + 2 * eps)
    current_gain = (_leaf_split_gain(left_g, left_h)
                    + _leaf_split_gain(right_g, right_h))
    valid = valid & (current_gain >= gain_shift)
    score = jnp.where(valid, current_gain, NEG_INF)
    return cg, ch, cc, score, gain_shift


def per_feature_best_scores(hist, sum_grad, sum_hess, num_data, num_bins,
                            feature_mask, min_data_in_leaf,
                            min_sum_hessian_in_leaf) -> jax.Array:
    """[F] best (unshifted) split score per feature, -inf when a feature
    has no valid candidate — the voting learner's LOCAL gain vector
    (ISSUE 9; PV-tree / the reference's absent voting_parallel design):
    each shard proposes its top-k features by this score, and only the
    globally-voted features' histograms are exchanged."""
    _, _, _, score, _ = _threshold_scan(
        hist, sum_grad, sum_hess, num_data, num_bins, feature_mask,
        min_data_in_leaf, min_sum_hessian_in_leaf)
    return jnp.max(score, axis=1)


def _find_best_split_scoped(hist, sum_grad, sum_hess, num_data, num_bins,
                            feature_mask, min_data_in_leaf,
                            min_sum_hessian_in_leaf) -> SplitResult:
    F, B, _ = hist.shape
    eps = jnp.float32(K_EPSILON)
    total_g = sum_grad.astype(jnp.float32)
    total_h = sum_hess.astype(jnp.float32)
    total_c = num_data.astype(jnp.float32)

    cg, ch, cc, score, gain_shift = _threshold_scan(
        hist, sum_grad, sum_hess, num_data, num_bins, feature_mask,
        min_data_in_leaf, min_sum_hessian_in_leaf)

    # within-feature argmax, larger threshold wins ties → argmax on the
    # reversed threshold axis
    rev = score[:, ::-1]
    best_t_rev = jnp.argmax(rev, axis=1)
    best_t = (B - 1) - best_t_rev                    # [F]
    best_score = jnp.take_along_axis(score, best_t[:, None], axis=1)[:, 0]

    # across features: smaller feature index wins ties (jnp.argmax returns
    # the first maximum)
    best_f = jnp.argmax(best_score).astype(jnp.int32)
    gain_raw = best_score[best_f]
    t = best_t[best_f].astype(jnp.int32)

    lg = cg[best_f, t]
    lh_raw = ch[best_f, t]
    lc = cc[best_f, t]
    rg = total_g - lg
    rh_raw = total_h - lh_raw
    rc = total_c - lc

    return SplitResult(
        gain=jnp.where(jnp.isfinite(gain_raw), gain_raw - gain_shift, NEG_INF),
        feature=best_f,
        threshold=t,
        left_output=_leaf_output(lg, lh_raw + eps),
        right_output=_leaf_output(rg, rh_raw + eps),
        left_count=lc.astype(jnp.int32),
        right_count=rc.astype(jnp.int32),
        left_sum_grad=lg,
        left_sum_hess=lh_raw,
        right_sum_grad=rg,
        right_sum_hess=rh_raw,
    )


def _leaf_split_gain(g, h):
    """g²/h (feature_histogram.hpp:219-221)."""
    return (g * g) / h


def _leaf_output(g, h):
    """−g/h (feature_histogram.hpp:229-231)."""
    return -g / h
