"""Pallas TPU histogram kernel — the hot loop, hand-scheduled.

The XLA one-hot-einsum formulation (ops/histogram.py) runs at ~70% of MXU
peak and cannot use the int8 MXU path.  This kernel owns the schedule:

- grid over row-chunks; the [F, B, K] accumulator lives in VMEM across the
  whole grid (written back to HBM once), so HBM traffic is the int8 bin
  matrix + a packed int8 side-band — nothing else.  All row-aligned inputs
  are LANE-major or lane-packed: a [N, small] f32 buffer would be
  tile-padded to 128 lanes in HBM (128 bytes/row of traffic), so grad,
  hess, mask and column id travel as ONE packed [N, 4] int8 array;
- per feature, the bin one-hot [chunk, B] is generated in VMEM by an iota
  compare (never touches HBM) and contracted on the MXU
  (sublane-contracting dot_general) against the column-expanded value
  block [chunk, K];
- ``dtype="int8"`` is the quantized-gradient variant: stochastically /
  nearest-rounded int8 grad/hess, int8xint8->int32 MXU at 2x the bf16
  rate, exact int32 counts — modern LightGBM's quantized-training idea
  recast for a systolic array (the reference's double accumulators,
  bin.h:15-17, sit at the other end of this precision spectrum).

Layout contract: bins_t [N, F] int8 (row-major TRANSPOSE of the dataset's
[F, N] bin matrix), packed values [N, 4] int8 (gq, hq, ok, cid), output
hist [C, F, B, 3] f32 after dequantization.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANES = 128  # default value-operand width: 42 leaf columns x 3 stats + 2


def _hist_kernel(bins_ref, packed_ref, out_ref, *, F, B, chunk, lanes,
                 compute_dtype, acc_dtype, stats=3):
    # grid = (feature_blocks, row_chunks), rows minor: each feature
    # block's accumulator lives in VMEM across its whole row sweep and is
    # written back to HBM once
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    # pure arithmetic (no jnp.where): Mosaic cannot relayout replicated
    # boolean vectors.  VPU math runs wide (8-bit vector arithmetic is
    # unsupported) and casts to compute_dtype only for the MXU operands.
    # Everything is LANE-major ([*, chunk]); the value block vL is built
    # TRANSPOSED [lanes, chunk] so the contraction is an NT-form matmul.
    # ``stats`` values interleave per leaf column (3 = grad/hess/count;
    # 5 = the f32 single-pass hi/lo packing g_hi,g_lo,h_hi,h_lo,count).
    wide = jnp.int32 if compute_dtype == jnp.int8 else jnp.float32
    jrow = jax.lax.broadcasted_iota(jnp.int32, (lanes, chunk), 0)
    leaf_j = jrow // stats
    k_j = jrow - stats * leaf_j
    # packed may be int8 (quantized levels) or bf16 (float values); both
    # convert exactly to ``wide`` (int levels <= 127, cid <= 191 — small
    # integers are exact in f32, so the cid equality compare is safe)
    packed = packed_ref[...].astype(wide)           # [stats + 1, chunk]
    terms = None
    for k in range(stats):
        vk = ((k_j == k).astype(wide)
              * jnp.broadcast_to(packed[k:k + 1, :], (lanes, chunk)))
        terms = vk if terms is None else terms + vk
    cidb = jnp.broadcast_to(packed[stats:stats + 1, :], (lanes, chunk))
    lmask = (cidb == leaf_j.astype(wide)).astype(wide)
    vLt = (terms * lmask).astype(compute_dtype)     # [lanes, chunk]

    iota_b = jax.lax.broadcasted_iota(jnp.int32, (B, chunk), 0)
    dn = (((1,), (1,)), ((), ()))                           # contract chunk
    for f in range(F):
        # bins ride as int8 bit-patterns; values >= 128 (uint8 source,
        # max_bin up to 256) wrap negative on the cast, so mask back
        # (int8-domain compares don't compile in Mosaic)
        brow = bins_ref[f:f + 1, :].astype(jnp.int32) & 255  # [1, chunk]
        oh = (iota_b == brow).astype(compute_dtype)         # [B, chunk]
        out_ref[f] += jax.lax.dot_general(
            oh, vLt, dimension_numbers=dn,
            preferred_element_type=acc_dtype)               # [B, LANES]


def _hist_pallas_raw_fn(bins, packed, *, B: int, chunk: int = 2048,
                        dtype: str = "int8", lanes: int = LANES,
                        stats: int = 3):
    """[F, B, lanes] accumulator from [F, N] bins and packed values.

    Rows must be pre-padded to a multiple of ``chunk`` (pad cid with -1).
    packed is [stats + 1, N]: ``stats`` values per leaf column followed by
    the cid row (stats=3: grad, hess, ok; stats=5: the f32 hi/lo packing
    g_hi, g_lo, h_hi, h_lo, ok).  Three dtype modes:
      "int8"  — packed int8 quantized levels, int8xint8->int32 MXU;
      "bf16"  — the SAME int8 levels riding bf16 operands (integers <= 127
                are bf16-exact), bit-identical histograms to "int8";
      "bf16v" — packed is BFLOAT16 carrying FLOAT grad/hess values
                (not quantized levels), f32 MXU accumulation.  This is the
                float-gradient variant: per-value bf16 precision instead of
                a shared int8 scale, and — being hand-scheduled — immune to
                XLA einsum-lowering regressions (BASELINE.md round 3).
    ``bins`` may carry uint8 bit-patterns (the kernel masks the
    sign-extension back off).  ``lanes`` widens the value operand past one
    MXU tile (192 fits 64 leaf columns in 1.5 tiles instead of two full
    128-lane passes).

    Wide datasets ride a FEATURE-BLOCK grid axis: the [Fb, B, lanes]
    accumulator of one block fits VMEM (~12 MB) and each block sweeps the
    rows in turn, so F is unbounded (the row side-band is re-read per
    block — F/Fb x a few MB of HBM, noise next to the matmuls).
    """
    from .. import telemetry
    telemetry.count("hist/pallas_kernel_" + dtype)
    F, N = bins.shape
    assert N % chunk == 0 and packed.shape == (stats + 1, N)
    compute_dtype = jnp.int8 if dtype == "int8" else jnp.bfloat16
    acc_dtype = jnp.int32 if dtype == "int8" else jnp.float32
    if dtype == "bf16v":
        assert packed.dtype == jnp.bfloat16, packed.dtype
    if F <= feature_block(B, lanes):
        # single block: the output window is constant across the grid, so
        # Mosaic keeps ONE VMEM copy — the full ~12 MB budget applies
        # (the round-2 kernel ran exactly this shape)
        fb, n_fblocks = F, 1
    else:
        # multi-block: the output window rotates with grid axis i, which
        # Mosaic DOUBLE-BUFFERS — budget half the VMEM per block.  Blocks
        # are balanced: with fb_max=48 (B=256, lanes=128), 100 features
        # run as 3 x 40 (20 pad) instead of 48+48+48 (44 pad) — padded
        # features cost full matmul passes
        fb_max = feature_block(B, lanes, budget=6 << 20)
        n_fblocks = -(-F // fb_max)
        fb = -(-F // n_fblocks)
        fb += (-fb) % 8                       # sublane-tile multiple
        pad_f = n_fblocks * fb - F
        if pad_f:
            bins = jnp.pad(bins, ((0, pad_f), (0, 0)))
    kernel = functools.partial(
        _hist_kernel, F=fb, B=B, chunk=chunk, lanes=lanes,
        compute_dtype=compute_dtype, acc_dtype=acc_dtype, stats=stats)
    out = pl.pallas_call(
        kernel,
        grid=(n_fblocks, N // chunk),
        in_specs=[
            pl.BlockSpec((fb, chunk), lambda i, j: (i, j)),
            pl.BlockSpec((stats + 1, chunk), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((fb, B, lanes), lambda i, j: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n_fblocks * fb, B, lanes),
                                       acc_dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
    )(bins, packed)
    out = out[:F]
    if dtype in ("int8", "bf16v"):
        return out                       # int32 / f32 accumulator as-is
    return out.astype(jnp.int32)


# jitted + wrapped in the cost registry: a STANDALONE (eager) call of the
# Pallas kernel — micro-benchmarks, tests — self-reports its compile cost
# and memory analysis; inside a traced grower program the wrapper passes
# straight through and the kernel inlines as before (cost analysis cannot
# see into the custom call either way — the analytic MAC counts ride
# costmodel.note_traced_pass from the histogram routing layer instead)
from .. import costmodel as _costmodel  # noqa: E402

hist_pallas_raw = _costmodel.instrument(
    "hist/pallas_raw",
    jax.jit(_hist_pallas_raw_fn,
            static_argnames=("B", "chunk", "dtype", "lanes", "stats")),
    phase="histogram")


def feature_block(B: int, lanes: int, budget: int = 12 << 20) -> int:
    """Features per VMEM-resident accumulator block: the largest multiple
    of 8 (sublane tile) whose [Fb, B, lanes] int32/f32 block fits the
    given budget (~12 MB of v5e VMEM with operand headroom for the
    single-buffered case; callers halve it when the block rotates across
    the grid and Mosaic double-buffers it)."""
    fb = budget // (B * lanes * 4)
    return max(8, fb - fb % 8)


def _mix32(x):
    """murmur3-style integer finalizer (public-domain mixing constants):
    a stateless uint32 hash good enough to decorrelate rounding noise."""
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    return x ^ (x >> 16)


def stochastic_bits(x, other, salt):
    """Deterministic per-element uniform bits for stochastic rounding,
    keyed on the (grad, hess) VALUE PAIR of the row and a per-use
    ``salt``.  Value-keyed means no row-position plumbing: the same
    physical row carries the same gradient bits in serial, sharded and
    multi-process programs alike — regardless of row position in the
    padded layouts — so the serial == distributed bit-identity of the
    int8 histograms survives, and the key varies per boosting iteration
    automatically because the gradients do.  Rows sharing the exact
    (grad, hess) pair round identically (iteration 0's uniform hessians
    are the worst case — but there grad/hess quantize near-exactly by
    construction of the per-pass max scale); from iteration 1 on the
    score fan-out makes the pairs effectively unique per row."""
    ix = jax.lax.bitcast_convert_type(x.astype(jnp.float32), jnp.uint32)
    io = jax.lax.bitcast_convert_type(other.astype(jnp.float32),
                                      jnp.uint32)
    return _mix32(ix ^ _mix32(io)
                  ^ _mix32(jnp.uint32(salt) + jnp.uint32(0x9E3779B9)))


def quantize_values(grad, hess, col_ok, rng_bits=None, axis_name=None,
                    stochastic=False, salt=0):
    """int8 quantization of grad/hess with a per-pass global scale.

    Round-to-nearest by default; unbiased stochastic rounding
    (floor(y+u), u uniform in [0,1)) with ``stochastic=True`` — the
    uniform bits come from a deterministic value-keyed hash
    (``stochastic_bits``), or from explicit ``rng_bits`` [2, N] uint32.
    Returns (vals [3, N] int8 lane-major, scale [3] f32) — the count row
    is exact by construction.

    ``axis_name``: under shard_map, pmax the scale over the data axis so
    every shard quantizes identically — int32 accumulation is then
    order-free, making data-parallel histograms BIT-identical to serial
    (the quantized analog of the reference's every-worker-identical-split
    invariant, data_parallel_tree_learner.cpp:237-243).
    """
    okf = col_ok.astype(jnp.float32)
    # the scale must come from PARTICIPATING rows only: multi-process
    # phantom padding rows can carry arbitrary score-residual gradients
    # (their scores still accumulate leaf values) and would inflate the
    # scale, collapsing quantization resolution and breaking the
    # serial == distributed bit-identity
    ag = jnp.max(jnp.abs(grad) * okf)
    ah = jnp.max(jnp.abs(hess) * okf)
    if axis_name is not None:
        from .. import telemetry
        telemetry.record_collective("hist/quant_scale_pmax", "pmax",
                                    axis_name,
                                    telemetry._tree_nbytes((ag, ah)))
        ag = jax.lax.pmax(ag, axis_name)
        ah = jax.lax.pmax(ah, axis_name)
    gs = jnp.maximum(ag, 1e-30) / 127.0
    hs = jnp.maximum(ah, 1e-30) / 127.0

    def quant(x, s, bits):
        y = x / s
        if bits is None:
            q = jnp.round(y)
        else:
            u = (bits >> 8).astype(jnp.float32) * (1.0 / (1 << 24))
            q = jnp.floor(y + u)
        return jnp.clip(q, -127, 127)

    gbits = hbits = None
    if rng_bits is not None:
        gbits, hbits = rng_bits[0], rng_bits[1]
    elif stochastic:
        gbits = stochastic_bits(grad, hess, salt)
        hbits = stochastic_bits(hess, grad, salt + 0x51ED)
    gq = quant(grad, gs, gbits)
    hq = quant(hess, hs, hbits)
    vals = jnp.stack([gq * okf, hq * okf, okf], axis=0).astype(jnp.int8)
    return vals, jnp.stack([gs, hs, jnp.float32(1.0)])


def quant_saturation_count(grad, hess, axis_name=None):
    """Health gauge: how many grad/hess entries quantize to the ±127
    ceiling under quantize_values' per-pass max scale (|x| > 126.5·s with
    s = max|x|/127).  The scale construction pins the max row at 127 by
    design, so a handful of saturated rows is normal; a LARGE count means
    the magnitude distribution has collapsed onto the ceiling — iteration
    0's uniform hessians are the canonical case, and the precondition for
    the int32 accumulator wraparound models/gbdt.check_int8_row_capacity
    bounds.  Kept next to quantize_values so the two can never drift.

    Uses the finite global max per channel (the health monitor evaluates
    once per iteration over ALL rows).  Histogram passes quantize with
    per-pass MASKED scales ≤ this global max, so a pass whose local max
    sits below the global one saturates MORE of its entries than the
    gauge counts — read the gauge as a floor, not a ceiling: nonzero
    means at-least-this-much concentration at the representable limit.
    ``axis_name``: pmax the scale across shards before counting, psum the
    count — every shard reports the identical global gauge."""
    f32 = jnp.float32
    total = jnp.zeros((), f32)
    if axis_name is not None:
        from .. import telemetry
        telemetry.record_collective("health/quant_sat_reduce", "psum",
                                    axis_name, 2 * 4)
    for x in (grad, hess):
        ax = jnp.where(jnp.isfinite(x), jnp.abs(x), 0.0)
        m = jnp.max(ax)
        if axis_name is not None:
            m = jax.lax.pmax(m, axis_name)
        sat = jnp.sum((ax * 127.0 > m * 126.5).astype(f32))
        total = total + (jax.lax.psum(sat, axis_name)
                         if axis_name is not None else sat)
    return total


def _grouped(fn, bins, grad, hess, col_id, col_ok, num_cols, B, *,
             group_width=42, **kw):
    """Split levels wider than ``group_width`` columns into balanced
    groups (the same rule as ops/histogram.histogram_leafbatch: ceil-split
    so the last group is never a nearly-empty full pass).  42 = one
    128-lane MXU tile (XLA paths); the Pallas kernels take 64 (a 192-lane
    operand is cheaper than two passes)."""
    if num_cols <= group_width:
        return fn(bins, grad, hess, col_id, col_ok, num_cols, B, **kw)
    n_groups = -(-num_cols // group_width)
    width = -(-num_cols // n_groups)
    parts = []
    for base in range(0, num_cols, width):
        k = min(width, num_cols - base)
        ok = col_ok & (col_id >= base) & (col_id < base + k)
        parts.append(fn(bins, grad, hess, col_id - base, ok, k, B, **kw))
    return jnp.concatenate(parts, axis=0)


def _class_acc_assemble(parts, packing, B: int):
    """Per-class accumulators (packed feature order, feature axis 0, bin
    axis 1) -> ONE canonical-order accumulator padded to B bins.  Stays in
    the accumulator's own domain (int32 for the quantized kernels), so the
    ownership psum_scatter / cross-shard psum that follows operates on
    canonical contiguous feature blocks exactly as in the uniform path —
    the per-class passes ride the EXISTING reduction schedule unchanged.
    ONE implementation (ops/histogram._assemble_classes): the reassembly
    is the bit-identity-critical step, so every kernel route must share
    it."""
    from .histogram import _assemble_classes
    return _assemble_classes(parts, packing, B, feat_axis=0, bin_axis=1)


def _packing_on(packing) -> bool:
    from .histogram import _packing_active
    return _packing_active(packing)


def hist_pallas_leafbatch(bins, grad, hess, col_id, col_ok, num_cols: int,
                          num_bins_max: int, *, chunk: int = 2048,
                          dtype: str = "int8", rng_bits=None,
                          axis_name=None, int_reduce=None,
                          stochastic=False, salt=0, packing=None,
                          feat_gather=None):
    """Drop-in histogram_leafbatch equivalent on the Pallas kernel.

    ``bins`` is the usual [F, N] matrix (int8 or uint8).  The int32
    accumulator dequantizes to the usual [C, F, B, 3] f32.  Levels up to
    64 columns run as ONE pass (<=42 columns fill one 128-lane MXU tile;
    43-64 use a 192-lane operand = 1.5 tiles, cheaper than two full
    passes over the data); wider levels split into 64-column groups.

    ``packing`` (mixed-bin layout): one kernel launch per bin-width class
    — the narrow class's [Fc, 64, lanes] accumulator costs a quarter of
    the 255-wide pass in MXU/one-hot work — assembled back into ONE
    canonical int accumulator BEFORE the cross-shard reduction, so the
    int-domain bit-exactness chain and the DP ownership schedule are
    untouched."""
    from .. import telemetry
    # named_scope unconditionally (the span is a no-op with telemetry
    # off): profile_dir= traces label the kernel "histogram" either way
    with jax.named_scope("histogram"), telemetry.span("histogram") as sp:
        return sp.fence(_grouped(
            _hist_pallas_one, bins, grad, hess, col_id, col_ok,
            num_cols, num_bins_max, group_width=64, chunk=chunk,
            dtype=dtype, rng_bits=rng_bits, axis_name=axis_name,
            int_reduce=int_reduce, stochastic=stochastic, salt=salt,
            packing=packing, feat_gather=feat_gather))


def _hist_pallas_one(bins, grad, hess, col_id, col_ok, num_cols, B, *,
                     chunk, dtype, rng_bits, axis_name=None,
                     int_reduce=None, stochastic=False, salt=0,
                     packing=None, feat_gather=None):
    F, N = bins.shape
    lanes = LANES if num_cols <= 42 else 192
    # ONE quantization for every class pass: the scale comes from the same
    # grad/hess/col_ok whatever the feature layout, so packed and uniform
    # passes quantize identically (bit-identity precondition)
    vals, scale = quantize_values(grad, hess, col_ok, rng_bits,
                                  axis_name=axis_name,
                                  stochastic=stochastic, salt=salt)
    cid8 = jnp.where(col_ok, col_id, -1).astype(jnp.int8)
    packed = jnp.concatenate([vals, cid8[None, :]], axis=0)  # [4, N] int8

    pad = (-N) % chunk
    if pad:
        bins = jnp.pad(bins, ((0, 0), (0, pad)))
        packed = jnp.pad(packed, ((0, 0), (0, pad)), constant_values=-1)
    if _packing_on(packing):
        from .. import telemetry
        telemetry.count("hist/mixedbin_pallas_int")
        parts = [hist_pallas_raw(
            jax.lax.slice_in_dim(bins, start, start + cnt,
                                 axis=0).astype(jnp.int8),
            packed, B=width, chunk=chunk, dtype=dtype, lanes=lanes)
            for start, cnt, width in packing.ranges]
        acc = _class_acc_assemble(parts, packing, B)         # [F, B, lanes]
    else:
        acc = hist_pallas_raw(bins.astype(jnp.int8), packed, B=B,
                              chunk=chunk, dtype=dtype,
                              lanes=lanes)                   # [F, B, lanes]
    if feat_gather is not None:
        # block-local packing's storage->canonical reorder, IN the int
        # domain and BEFORE the cross-shard reduction: the gather
        # commutes with the elementwise int psum, and the dequantized
        # f32 graph downstream is shape-identical to the uniform
        # layout's (XLA contraction choices cannot diverge — ISSUE 12)
        assert int_reduce is None, \
            "feat_gather does not compose with the ownership int scatter"
        acc = jnp.take(acc, feat_gather, axis=0)
    if int_reduce is not None:
        # ownership schedule: psum_scatter the INT accumulators by feature
        # block (feature axis 0) — still int-domain, still bit-exact
        acc = int_reduce(acc)
        F = acc.shape[0]
    elif axis_name is not None:
        # reduce the INT accumulators across shards: dequantize-then-psum
        # would round (sum of 8 f32 products != int-sum x scale) and break
        # the bit-identical serial == data-parallel invariant
        from .. import telemetry
        telemetry.record_collective("hist/int8_pallas_psum", "psum",
                                    axis_name, telemetry._tree_nbytes(acc))
        acc = jax.lax.psum(acc, axis_name)
    hist = acc[:, :, :num_cols * 3].astype(jnp.float32)
    hist = hist.reshape(F, B, num_cols, 3).transpose(2, 0, 1, 3)
    return hist * scale


def hist_pallas_float_leafbatch(bins, grad, hess, col_id, col_ok,
                                num_cols: int, num_bins_max: int, *,
                                chunk: int = 2048,
                                precision: str = "bf16", packing=None):
    """Float-gradient Pallas histogram — [C, F, B, 3] f32, same contract as
    histogram_leafbatch's einsum formulation but hand-scheduled (and so
    immune to the environment's XLA einsum-lowering regression, BASELINE.md
    round-3 addendum).

    precision="bf16"  (hist_dtype=bfloat16): grad/hess ride as single bf16
      operands — per-value exponents, ~8-bit mantissa, f32 accumulation.
      One pass over the data, the same MXU cost as the int-level kernel's
      bf16 mode.
    precision="f32" (hist_dtype=float32 on TPU): hi/lo bf16 split,
      g = bf16(g) + bf16(g - bf16(g)) — recovers ~16 mantissa bits of the
      f32 operand (vs 24 native; sums accumulate f32 either way, and the
      reference's doubles, bin.h:15-17, sit above both).  Levels up to 38
      columns run as ONE pass with FIVE stats per column (g_hi, g_lo,
      h_hi, h_lo, count — "f32x1"; 25 columns fill a 128-lane tile, 38
      fill 192): measured 2x faster than two 3-stat passes at 8 columns,
      1.4x at 25.  Wider levels run the SAME hi/lo split as TWO 3-stat
      passes over 64-column groups ("f32x2" — equal MXU units there, and
      fewer per-pass overheads than grouped 5-stat).  Both orderings
      accumulate identical per-lane f32 partial sums, so the choice is
      bit-invisible; "f32x1"/"f32x2" force one variant (A/B tests).

    Counts are exact in every mode: ok rides as 1.0 (bf16-exact) and the
    lo lanes carry zeros.
    """
    if precision == "f32":
        precision = "f32x1" if num_cols <= 38 else "f32x2"
    with jax.named_scope("histogram"):
        if precision == "f32x1":
            return _grouped(_hist_float_one, bins, grad, hess, col_id,
                            col_ok, num_cols, num_bins_max, group_width=38,
                            chunk=chunk, precision=precision,
                            packing=packing)
        return _grouped(_hist_float_one, bins, grad, hess, col_id, col_ok,
                        num_cols, num_bins_max, group_width=64, chunk=chunk,
                        precision=precision, packing=packing)


def _hist_float_one(bins, grad, hess, col_id, col_ok, num_cols, B, *,
                    chunk, precision, packing=None):
    if _packing_on(packing):
        # one kernel launch per bin-width class over the class's feature
        # rows; f32 accumulation is per row-chunk in fixed grid order, so
        # every canonical cell sums in exactly the uniform pass's order
        from .. import telemetry
        telemetry.count("hist/mixedbin_pallas_float")
        parts = []
        for start, cnt, width in packing.ranges:
            h = _hist_float_one(
                jax.lax.slice_in_dim(bins, start, start + cnt, axis=0),
                grad, hess, col_id, col_ok, num_cols, width,
                chunk=chunk, precision=precision)        # [C, Fc, w, 3]
            if width < B:
                h = jnp.pad(h, ((0, 0), (0, 0), (0, B - width), (0, 0)))
            parts.append(h)
        packed_h = jnp.concatenate(parts, axis=1)
        return jnp.take(packed_h, jnp.asarray(packing.c2p, jnp.int32),
                        axis=1)
    F, N = bins.shape
    okf = col_ok.astype(jnp.float32)
    g = grad.astype(jnp.float32) * okf
    h = hess.astype(jnp.float32) * okf
    # cid rides the bf16 side-band: small integers (<= 64 after grouping)
    # are bf16-exact, and -1 never matches a lane's leaf id
    cidb = jnp.where(col_ok, col_id, -1).astype(jnp.bfloat16)
    bins8 = bins.astype(jnp.int8)
    pad = (-N) % chunk
    if pad:
        bins8 = jnp.pad(bins8, ((0, 0), (0, pad)))

    def run(vals, lanes):
        packed = jnp.stack([v.astype(jnp.bfloat16) for v in vals]
                           + [cidb], axis=0)
        if pad:
            packed = jnp.pad(packed, ((0, 0), (0, pad)),
                             constant_values=-1)
        return hist_pallas_raw(bins8, packed, B=B, chunk=chunk,
                               dtype="bf16v", lanes=lanes,
                               stats=len(vals))

    lanes3 = LANES if num_cols <= 42 else 192
    if precision == "bf16":
        acc = run([g, h, okf], lanes3)
    elif precision == "f32x1":
        g_hi = g.astype(jnp.bfloat16).astype(jnp.float32)
        h_hi = h.astype(jnp.bfloat16).astype(jnp.float32)
        lanes5 = LANES if num_cols <= 25 else 192
        acc5 = run([g_hi, g - g_hi, h_hi, h - h_hi, okf], lanes5)
        w = acc5[:, :, :num_cols * 5].reshape(F, B, num_cols, 5)
        hist = jnp.stack([w[..., 0] + w[..., 1], w[..., 2] + w[..., 3],
                          w[..., 4]], axis=-1)
        return hist.transpose(2, 0, 1, 3)
    elif precision == "f32x2":
        g_hi = g.astype(jnp.bfloat16).astype(jnp.float32)
        h_hi = h.astype(jnp.bfloat16).astype(jnp.float32)
        acc = (run([g_hi, h_hi, okf], lanes3)
               + run([g - g_hi, h - h_hi, jnp.zeros_like(okf)], lanes3))
    else:
        raise ValueError(f"unknown float-hist precision {precision!r}")
    hist = acc[:, :, :num_cols * 3]
    return hist.reshape(F, B, num_cols, 3).transpose(2, 0, 1, 3)


def hist_quant_xla(bins, grad, hess, col_id, col_ok, num_cols: int,
                   num_bins_max: int, *, chunk: int = 65536, rng_bits=None,
                   axis_name=None, int_reduce=None,
                   stochastic=False, salt=0, packing=None,
                   feat_gather=None):
    """XLA reference of the SAME quantized-gradient math as the Pallas int8
    kernel (bit-identical output) — the CPU-testable oracle and the
    fallback on non-TPU backends.  ``packing``: per-class int accumulators
    assembled canonically before the cross-shard reduction, exactly like
    the Pallas route (int32 sums are order-free, so packed == uniform is
    bit-exact here by construction)."""
    from .. import telemetry
    telemetry.count("hist/xla_int_kernel")
    with jax.named_scope("histogram"), telemetry.span("histogram") as sp:
        return sp.fence(_grouped(
            _hist_quant_xla_one, bins, grad, hess, col_id, col_ok,
            num_cols, num_bins_max, chunk=chunk, rng_bits=rng_bits,
            axis_name=axis_name, int_reduce=int_reduce,
            feat_gather=feat_gather,
            stochastic=stochastic, salt=salt, packing=packing))


def _quant_xla_acc(bins, vals, cid, B: int, C: int, chunk: int):
    """One class's raw [F, B, C*3] int32 accumulator (rows pre-padded)."""
    F = bins.shape[0]
    N = bins.shape[1]
    n_chunks = N // chunk
    bins_c = bins.astype(jnp.int32).reshape(F, n_chunks,
                                            chunk).transpose(1, 0, 2)
    vals_c = vals.astype(jnp.int32).T.reshape(n_chunks, chunk, 3)
    cid_c = cid.reshape(n_chunks, chunk)
    ib = jnp.arange(B, dtype=jnp.int32)
    ic = jnp.arange(C, dtype=jnp.int32)

    def body(carry, xs):
        bc, vc, cc = xs
        oh = (bc[:, :, None] == ib).astype(jnp.int32)
        lsel = (cc[:, None] == ic).astype(jnp.int32)
        vL = (lsel[:, :, None] * vc[:, None, :]).reshape(chunk, C * 3)
        out = jnp.einsum("fcb,ck->fbk", oh, vL,
                         preferred_element_type=jnp.int32)
        return carry + out, None

    init = jnp.zeros((F, B, C * 3), jnp.int32)
    hist, _ = jax.lax.scan(body, init, (bins_c, vals_c, cid_c))
    return hist


def _hist_quant_xla_one(bins, grad, hess, col_id, col_ok, num_cols, B, *,
                        chunk, rng_bits, axis_name=None, int_reduce=None,
                        stochastic=False, salt=0, packing=None,
                        feat_gather=None):
    F, N = bins.shape
    C = num_cols
    # don't pad a small input up to a full default chunk
    chunk = min(chunk, max(256, -(-N // 256) * 256))
    vals, scale = quantize_values(grad, hess, col_ok, rng_bits,
                                  axis_name=axis_name,
                                  stochastic=stochastic, salt=salt)
    cid = jnp.where(col_ok, col_id, -1).astype(jnp.int32)
    pad = (-N) % chunk
    if pad:
        bins = jnp.pad(bins, ((0, 0), (0, pad)))
        vals = jnp.pad(vals, ((0, 0), (0, pad)))
        cid = jnp.pad(cid, (0, pad), constant_values=-1)
    if _packing_on(packing):
        from .. import telemetry
        telemetry.count("hist/mixedbin_xla_int")
        parts = [_quant_xla_acc(
            jax.lax.slice_in_dim(bins, start, start + cnt, axis=0),
            vals, cid, width, C, chunk)
            for start, cnt, width in packing.ranges]
        hist = _class_acc_assemble(parts, packing, B)    # [F, B, C*3] i32
    else:
        hist = _quant_xla_acc(bins, vals, cid, B, C, chunk)
    if feat_gather is not None:
        # storage->canonical reorder IN the int domain, before the
        # cross-shard psum (commutes elementwise) — see _hist_pallas_one
        assert int_reduce is None, \
            "feat_gather does not compose with the ownership int scatter"
        hist = jnp.take(hist, feat_gather, axis=0)
    if int_reduce is not None:
        hist = int_reduce(hist)                # int-domain feature scatter
        F = hist.shape[0]
    elif axis_name is not None:
        from .. import telemetry
        telemetry.record_collective("hist/int8_xla_psum", "psum",
                                    axis_name, telemetry._tree_nbytes(hist))
        hist = jax.lax.psum(hist, axis_name)   # int-domain cross-shard sum
    hist = hist.reshape(F, B, C, 3).transpose(2, 0, 1, 3).astype(jnp.float32)
    return hist * scale
