"""Vectorized tree application (score updates / prediction on binned data).

Replaces the reference's per-row pointer walks (tree.h:163-175,
tree.cpp:85-109) with a split-sequence REPLAY: node k split leaf
``split_leaf[k]`` into (itself, leaf k+1), so applying the recorded splits in
creation order reassigns every row's leaf id using [num_leaves-1] masked
vector steps — each step is one dynamic-sliced bin row gather + compare,
which is bandwidth-bound and TPU-friendly.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .lookup import batched_int8_table_lookup, exact_table_lookup


@functools.partial(jax.jit, static_argnames=("max_nodes",))
def leaf_ids_by_replay(bins: jax.Array, split_feature: jax.Array,
                       threshold_bin: jax.Array, split_leaf: jax.Array,
                       num_nodes: jax.Array, *, max_nodes: int) -> jax.Array:
    """Assign each row (column of ``bins``) to a leaf.

    Parameters
    ----------
    bins : [F, N] bin matrix
    split_feature, threshold_bin, split_leaf : [max_nodes] per-node records
    num_nodes : actual node count (num_leaves - 1)
    """
    N = bins.shape[1]
    leaf = jnp.zeros((N,), jnp.int32)

    def body(k, leaf):
        active = k < num_nodes
        fbin = jax.lax.dynamic_index_in_dim(
            bins, split_feature[k], axis=0, keepdims=False).astype(jnp.int32)
        go_right = fbin > threshold_bin[k]
        new_leaf = jnp.where((leaf == split_leaf[k]) & go_right, k + 1, leaf)
        return jnp.where(active, new_leaf, leaf)

    return jax.lax.fori_loop(0, max_nodes, body, leaf)


def split_leaf_sequence(left_child: jax.Array, right_child: jax.Array,
                        num_leaves_max: int, num_nodes=None):
    """Compute, per node in creation order, the leaf id it split.

    Node k's right child is always the new leaf ``~(k+1)`` (tree.cpp:70-71);
    walking parent edges top-down: the root split leaf 0; a node reached via
    its parent's LEFT edge split the same leaf id as its parent, via the
    RIGHT edge it split leaf ``parent+1``.  Pure jnp so it can run under jit.
    """
    L1 = num_leaves_max - 1
    parent = jnp.full((L1,), -1, jnp.int32)
    is_left = jnp.zeros((L1,), bool)

    def record(k, carry):
        parent, is_left = carry
        active = True if num_nodes is None else (k < num_nodes)
        # padded node slots carry zeros; mask them so they cannot touch
        # real entries
        lc = jnp.where(active, left_child[k], -1)
        rc = jnp.where(active, right_child[k], -1)
        parent = jnp.where(lc >= 0, parent.at[jnp.maximum(lc, 0)].set(k), parent)
        is_left = jnp.where(lc >= 0, is_left.at[jnp.maximum(lc, 0)].set(True),
                            is_left)
        parent = jnp.where(rc >= 0, parent.at[jnp.maximum(rc, 0)].set(k), parent)
        is_left = jnp.where(rc >= 0, is_left.at[jnp.maximum(rc, 0)].set(False),
                            is_left)
        return parent, is_left

    parent, is_left = jax.lax.fori_loop(0, L1, record, (parent, is_left))

    split_leaf = jnp.zeros((L1,), jnp.int32)

    def fill(k, split_leaf):
        p = parent[k]
        val = jnp.where(k == 0, 0,
                        jnp.where(is_left[k], split_leaf[jnp.maximum(p, 0)],
                                  p + 1))
        return split_leaf.at[k].set(val)

    return jax.lax.fori_loop(0, L1, fill, split_leaf)


@functools.partial(jax.jit, static_argnames=("max_nodes", "num_class"))
def ensemble_scores(codes: jax.Array, split_feature: jax.Array,
                    threshold_rank: jax.Array, left_child: jax.Array,
                    right_child: jax.Array, leaf_value: jax.Array,
                    num_leaves: jax.Array, tree_class: jax.Array,
                    *, max_nodes: int, num_class: int) -> jax.Array:
    """Batch ensemble prediction: Σ over trees of tree(codes rows), summed
    per class (GBDT::PredictRaw / Predictor batch loop,
    gbdt.cpp:470-519 + predictor.hpp:109-197, as ONE device scan).

    ``codes`` is the integer rank encoding of raw feature values against
    the union of the ensemble's own thresholds (built on host in f64), so
    routing is EXACT — no f32 threshold-comparison rounding.  Per-tree
    arrays are stacked [T, ...]; returns [num_class, N] raw score sums.
    """
    N = codes.shape[1]

    def body(score, xs):
        sf, tr, lc, rc, lv, nl, tc = xs
        split_leaf = split_leaf_sequence(lc, rc, max_nodes + 1,
                                         num_nodes=nl - 1)
        leaf = leaf_ids_by_replay(codes, sf, tr, split_leaf, nl - 1,
                                  max_nodes=max_nodes)
        return score.at[tc].add(exact_table_lookup(lv, leaf)), None

    init = jnp.zeros((num_class, N), jnp.float32)
    score, _ = jax.lax.scan(
        body, init, (split_feature, threshold_rank, left_child, right_child,
                     leaf_value, num_leaves, tree_class))
    return score


@functools.partial(jax.jit, static_argnames=("max_nodes",))
def ensemble_leaf_indices(codes: jax.Array, split_feature: jax.Array,
                          threshold_rank: jax.Array, left_child: jax.Array,
                          right_child: jax.Array, num_leaves: jax.Array,
                          *, max_nodes: int) -> jax.Array:
    """[T, N] leaf index per tree (PredictLeafIndex, gbdt.cpp:510-519)."""

    def body(_, xs):
        sf, tr, lc, rc, nl = xs
        split_leaf = split_leaf_sequence(lc, rc, max_nodes + 1,
                                         num_nodes=nl - 1)
        leaf = leaf_ids_by_replay(codes, sf, tr, split_leaf, nl - 1,
                                  max_nodes=max_nodes)
        return None, leaf

    _, leaves = jax.lax.scan(
        body, None, (split_feature, threshold_rank, left_child, right_child,
                     num_leaves))
    return leaves


# ---------------------------------------------------------------- serving BFS
#
# The per-tree replay above is the TRAINING-side scorer: one lax.scan step
# per tree, each replaying num_leaves-1 sequential masked splits — O(T·L)
# dependent device steps.  The serving engine (lightgbm_tpu/serving.py)
# instead walks ALL trees breadth-first in lockstep: the walk state is the
# [T, N] frontier of current node ids, and one gather-based level step
# advances every (tree, row) pair one depth at once — O(max_depth) fused
# steps total, independent of the tree count.  Node ids reuse the tree.h
# child encoding (>= 0 internal node, < 0 a bitwise-complemented leaf
# ``~leaf``), so "row finished" is simply ``state < 0`` and the masked
# step is branch-free.


def _bfs_leaf_state(codes, split_feature, threshold_rank, left_child,
                    right_child, root_state, max_depth: int):
    """[T, N] leaf ids via the lockstep breadth-first walk.

    ``codes`` [F, N] is the host-built integer rank encoding (same tables
    as the replay path, so routing is EXACT); node tables are [T,
    max_nodes]; ``root_state`` [T] is 0 for trees with nodes and ~0 for
    single-leaf stumps.  Returns nonneg leaf indices [T, N]."""
    T = split_feature.shape[0]
    N = codes.shape[1]
    state = jnp.broadcast_to(root_state[:, None], (T, N)).astype(jnp.int32)

    def step(_, state):
        node = jnp.maximum(state, 0)
        sf = jnp.take_along_axis(split_feature, node, axis=1)
        tr = jnp.take_along_axis(threshold_rank, node, axis=1)
        lc = jnp.take_along_axis(left_child, node, axis=1)
        rc = jnp.take_along_axis(right_child, node, axis=1)
        code = jnp.take_along_axis(codes, sf, axis=0)
        nxt = jnp.where(code > tr, rc, lc)
        return jnp.where(state >= 0, nxt, state)

    state = jax.lax.fori_loop(0, max_depth, step, state)
    return -state - 1  # ~state: every row has reached a leaf by max_depth


def _accumulate_tree_scores(vals, tree_class, num_class: int):
    """Σ over trees of per-tree leaf values ``vals`` [T, N] f32, summed
    per class IN TREE ORDER — the exact f32 accumulation sequence of
    ``ensemble_scores``' scan (score.at[tc].add per tree), so the BFS
    engine is bit-equal to the training-side scorer by construction."""
    T, N = vals.shape
    init = jnp.zeros((num_class, N), jnp.float32)

    def add(t, score):
        return score.at[tree_class[t]].add(vals[t])

    return jax.lax.fori_loop(0, T, add, init)


def bfs_scores_impl(codes, split_feature, threshold_rank, left_child,
                    right_child, leaf_value, root_state, tree_class,
                    *, max_depth: int, num_class: int):
    """[num_class, N] raw ensemble sums, breadth-first (f32 ensemble).

    The leaf read is a per-tree aligned gather (take_along_axis): the f32
    leaf table is [T, max_leaves] and every (tree, row) reads its own
    tree's row, so the read is exact by definition — the byte-split
    one-hot trick is reserved for the int8 variant where a single bf16
    pass suffices."""
    leaf = _bfs_leaf_state(codes, split_feature, threshold_rank,
                           left_child, right_child, root_state, max_depth)
    vals = jnp.take_along_axis(leaf_value, leaf, axis=1)   # [T, N] f32
    return _accumulate_tree_scores(vals, tree_class, num_class)


def bfs_scores_int8_impl(codes, split_feature, threshold_rank, left_child,
                         right_child, leaf_q, leaf_scale, root_state,
                         tree_class, *, max_depth: int, num_class: int):
    """int8-ensemble variant: leaf values ride as int8 [T, max_leaves]
    plus a per-tree f32 dequantization scale.  The table read is the
    single-pass bf16 one-hot matmul (batched_int8_table_lookup — int8
    magnitudes are bf16-exact, so the read is exact; only the
    quantization itself loses precision).  Accumulation order matches the
    f32 path, so the scores are bit-equal to a host replay of the SAME
    quantized model."""
    leaf = _bfs_leaf_state(codes, split_feature, threshold_rank,
                           left_child, right_child, root_state, max_depth)
    qvals = batched_int8_table_lookup(leaf_q, leaf)        # [T, N] f32
    vals = qvals * leaf_scale[:, None]
    return _accumulate_tree_scores(vals, tree_class, num_class)


def bfs_leaf_indices_impl(codes, split_feature, threshold_rank, left_child,
                          right_child, root_state, *, max_depth: int):
    """[T, N] leaf index per tree, breadth-first (PredictLeafIndex)."""
    return _bfs_leaf_state(codes, split_feature, threshold_rank,
                           left_child, right_child, root_state, max_depth)


# ------------------------------------------------------- tree-axis sharding
#
# ISSUE 13: the lockstep BFS walk is embarrassingly parallel in T — each
# shard of a 1-D ("tree",) mesh walks its CONTIGUOUS block of trees
# ([Tb, N] frontier over its own [Tb, max_nodes] node tables, the only
# tables resident in its HBM — the 10k+-tree / multi-GB-ensemble regime a
# single device cannot hold).  The only cross-shard work is the final
# score accumulation, and bit-equality with the single-device engine
# pins its design: the single-device accumulate is a sequential LEFT
# FOLD over trees in canonical order (``_accumulate_tree_scores``), and
# f32 addition is not associative, so a psum of per-shard partials would
# regroup the sum and drift by ulps.  Instead the partial [C, N] score
# is CARRIED shard-to-shard along the tree axis (ppermute chain, shard s
# folds its block onto the running total from shards 0..s-1 — exactly
# the single-device add sequence, including NaN/Inf propagation), and
# ONE masked psum at the end broadcasts the final shard's total (every
# other contribution is +0.0; the running score can never be -0.0 — it
# starts at +0.0 and IEEE round-to-nearest never produces -0.0 from
# x + y with x != -0.0 or y != -0.0 — so adding the zeros is exact).


def _sharded_tree_accumulate(vals, tree_class, *, num_class: int,
                             num_trees: int, shards: int, axis_name: str):
    """[C, N] ensemble sums from per-shard tree values ``vals`` [Tb, N],
    bit-equal to ``_accumulate_tree_scores`` over the canonically-ordered
    full [T, N] (see block comment).  ``tree_class`` is this shard's
    [Tb] slice of the global class map; ``num_trees`` masks the pad
    trees a non-dividing T leaves on the last shard (skipped entirely —
    never added, not even as zeros)."""
    from .. import telemetry

    Tb, N = vals.shape
    idx = jax.lax.axis_index(axis_name)
    base = idx * Tb

    def fold(carry):
        def add(t, score):
            new = score.at[tree_class[t]].add(vals[t])
            return jnp.where(base + t < num_trees, new, score)
        return jax.lax.fori_loop(0, Tb, add, carry)

    carry = jnp.zeros((num_class, N), jnp.float32)
    if shards <= 1:
        return fold(carry)
    # the carry chain: shard s's fold result travels to shard s+1, which
    # folds its own block on top — S-1 hops of one [C, N] payload
    send = telemetry.collective_span(
        "serve/tree_carry",
        lambda x: jax.lax.ppermute(
            x, axis_name, [(i, i + 1) for i in range(shards - 1)]),
        kind="ppermute", axis=axis_name, phase="predict")
    for _ in range(shards - 1):
        carry = send(fold(carry))
    chain = fold(carry)       # complete on the LAST shard only
    tree_psum = telemetry.collective_span(
        "serve/tree_psum", lambda x: jax.lax.psum(x, axis_name),
        kind="psum", axis=axis_name, phase="predict")
    return tree_psum(jnp.where(idx == shards - 1, chain,
                               jnp.zeros_like(chain)))


def bfs_scores_sharded_impl(codes, split_feature, threshold_rank,
                            left_child, right_child, leaf_value, root_state,
                            tree_class, *, max_depth: int, num_class: int,
                            num_trees: int, shards: int, axis_name: str):
    """Tree-sharded f32 variant of ``bfs_scores_impl`` (one shard of the
    1-D tree mesh: per-shard [Tb, ...] node tables, replicated codes;
    see the sharding block comment).  Returns the REPLICATED [C, N]
    sums, bit-equal to the single-device walk."""
    leaf = _bfs_leaf_state(codes, split_feature, threshold_rank,
                           left_child, right_child, root_state, max_depth)
    vals = jnp.take_along_axis(leaf_value, leaf, axis=1)   # [Tb, N] f32
    return _sharded_tree_accumulate(vals, tree_class, num_class=num_class,
                                    num_trees=num_trees, shards=shards,
                                    axis_name=axis_name)


def bfs_scores_sharded_int8_impl(codes, split_feature, threshold_rank,
                                 left_child, right_child, leaf_q, leaf_scale,
                                 root_state, tree_class, *, max_depth: int,
                                 num_class: int, num_trees: int, shards: int,
                                 axis_name: str):
    """Tree-sharded int8 variant: per-shard int8 leaf block + per-tree
    scales, the same exact one-hot read and accumulation order as the
    single-device ``bfs_scores_int8_impl``."""
    leaf = _bfs_leaf_state(codes, split_feature, threshold_rank,
                           left_child, right_child, root_state, max_depth)
    qvals = batched_int8_table_lookup(leaf_q, leaf)        # [Tb, N] f32
    vals = qvals * leaf_scale[:, None]
    return _sharded_tree_accumulate(vals, tree_class, num_class=num_class,
                                    num_trees=num_trees, shards=shards,
                                    axis_name=axis_name)


# Module-level jitted conveniences (tests, ad-hoc callers).  The serving
# engine builds its OWN jits from the impls above so it can donate the
# codes buffer and instrument each program through costmodel.
ensemble_scores_bfs = jax.jit(
    bfs_scores_impl, static_argnames=("max_depth", "num_class"))
ensemble_scores_bfs_int8 = jax.jit(
    bfs_scores_int8_impl, static_argnames=("max_depth", "num_class"))
ensemble_leaf_indices_bfs = jax.jit(
    bfs_leaf_indices_impl, static_argnames=("max_depth",))


@functools.partial(jax.jit, static_argnames=("max_nodes",))
def add_tree_score(bins: jax.Array, score: jax.Array,
                   split_feature: jax.Array, threshold_bin: jax.Array,
                   left_child: jax.Array, right_child: jax.Array,
                   leaf_value: jax.Array, num_leaves: jax.Array,
                   *, max_nodes: int) -> jax.Array:
    """score += tree(bins rows) — Tree::AddPredictionToScore equivalent."""
    split_leaf = split_leaf_sequence(left_child, right_child, max_nodes + 1,
                                     num_nodes=num_leaves - 1)
    leaf = leaf_ids_by_replay(bins, split_feature, threshold_bin, split_leaf,
                              num_leaves - 1, max_nodes=max_nodes)
    return score + exact_table_lookup(
        leaf_value.astype(jnp.float32), leaf).astype(score.dtype)
