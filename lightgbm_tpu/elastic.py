"""Elastic-training support (ISSUE 14): the straggler/skew logic shared
by ``scripts/timeline_report.py`` and the live trainer policy, plus the
small collective programs the elastic path runs on the mesh.

The persistent-straggler rule was born in the timeline report (PR 5):
one host STRICTLY slowest ``k`` consecutive iteration numbers — ties
never count, and a gap in the compared iterations resets the run rather
than bridging it (a truncated shard can't manufacture consecutiveness).
The trainer's live mesh-shrink policy must flag exactly the same hosts
the post-mortem report would, so the logic lives HERE once and both
consumers import it:

- ``skew_from_rows`` — the full per-phase skew/barrier-wait/straggler
  report over ``{iteration: {host: {phase: seconds}}}`` rows (the
  script's shape);
- ``StragglerTracker`` — the bare run-length state machine;
- ``StragglerMonitor`` — the trainer-side consumer: feed per-iteration
  per-host totals (from the cross-host time exchange, or injected by
  the fault-injection harness), read the flagged host at iteration
  boundaries.

Collectives (wire sites ``elastic/times_allgather`` and
``elastic/survivor_pmin``, censused by graftlint J2 via
``analysis/programs.elastic_programs``):

- ``exchange_times`` — every host's per-iteration seconds all_gathered
  over a 1-D ``(data,)`` mesh, so each host holds the identical vector
  and the deterministic straggler rule reaches the same verdict
  everywhere (no leader election needed);
- ``agree_survivors`` — elementwise ``pmin`` over per-host vote vectors:
  the drop decision every survivor commits to before the drain (a host
  that disagrees can only make the plan MORE conservative, never less).
"""
from __future__ import annotations

import functools
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from . import telemetry, tracing
from .utils import log

CANONICAL_PHASES = ("histogram", "split_find", "partition", "eval")


def median(vals: List[float]) -> float:
    s = sorted(vals)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def slowest_unique(totals: Dict[str, float]) -> Optional[str]:
    """The STRICTLY slowest host of one iteration, or None on a tie /
    all-zero totals (a tie is not a straggler)."""
    if not totals:
        return None
    t_max = max(totals.values())
    if t_max <= 0:
        return None
    if sum(1 for v in totals.values() if v == t_max) != 1:
        return None
    return max(totals, key=lambda h: totals[h])


class StragglerTracker:
    """Run-length state machine for the persistent-straggler rule: same
    host strictly slowest >= k CONSECUTIVE iteration numbers.  Gaps in
    the fed iteration numbers reset the run; ``None`` (tie / no signal)
    resets it too."""

    def __init__(self, k: int = 3):
        self.k = max(int(k), 1)
        self.run_host: Optional[str] = None
        self.run_len = 0
        self.prev_it: Optional[int] = None
        self.flagged: Optional[str] = None

    def update(self, iteration: int, slowest: Optional[str]) -> Optional[str]:
        """Feed one iteration's strictly-slowest host (or None); returns
        the flagged host once the run reaches k, else None."""
        if (slowest is not None and slowest == self.run_host
                and self.prev_it is not None
                and iteration == self.prev_it + 1):
            self.run_len += 1
        else:
            self.run_host, self.run_len = slowest, 1
        self.prev_it = iteration
        if self.run_host is not None and self.run_len >= self.k:
            self.flagged = self.run_host
            return self.run_host
        return None

    def reset(self) -> None:
        self.run_host, self.run_len, self.prev_it = None, 0, None
        self.flagged = None


def skew_from_rows(rows: Dict[int, Dict[str, Dict[str, float]]],
                   straggler_k: int = 3) -> dict:
    """Per-phase cross-host skew + barrier-wait decomposition + the
    persistent-straggler flag over ``{iteration: {host: {phase: s}}}``
    rows — the ONE implementation behind scripts/timeline_report.py's
    report and the trainer's live policy.  Needs >= 2 hosts with
    overlapping iteration records; degrades to an empty report."""
    multi = {it: hosts for it, hosts in rows.items() if len(hosts) >= 2}
    phases: Dict[str, dict] = {}
    barrier_wait: Dict[str, float] = {}
    tracker = StragglerTracker(straggler_k)
    for it in sorted(multi):
        hosts = multi[it]
        it_phases = sorted({p for pt in hosts.values() for p in pt})
        totals = {h: sum(pt.values()) for h, pt in hosts.items()}
        t_max = max(totals.values())
        tracker.update(it, slowest_unique(totals))
        for h, tot in totals.items():
            # time this host spends idle at the collectives waiting for
            # the slowest peer of the iteration
            barrier_wait[h] = barrier_wait.get(h, 0.0) + (t_max - tot)
        for p in it_phases:
            vals = [pt.get(p, 0.0) for pt in hosts.values()]
            med = median(vals)
            if med <= 0:
                continue
            ratio = max(vals) / med
            blk = phases.setdefault(p, {"max_skew": 0.0, "ratios": []})
            blk["max_skew"] = max(blk["max_skew"], ratio)
            blk["ratios"].append(ratio)
    for p, blk in phases.items():
        blk["mean_skew"] = round(sum(blk["ratios"]) / len(blk["ratios"]), 4)
        blk["iterations"] = len(blk.pop("ratios"))
        blk["max_skew"] = round(blk["max_skew"], 4)
    return {
        "iterations_compared": len(multi),
        "hosts": sorted({h for hosts in multi.values() for h in hosts}),
        "phases": phases,
        "max_phase_skew": round(max(
            [b["max_skew"] for b in phases.values()] or [0.0]), 4),
        "barrier_wait_s": {h: round(v, 6)
                           for h, v in sorted(barrier_wait.items())},
        "straggler_k": tracker.k,
        "persistent_straggler": tracker.flagged,
    }


class StragglerMonitor:
    """Trainer-side live policy: feed per-iteration per-host wall-time
    totals (label -> seconds), take the flagged host at an iteration
    boundary.  Observations come from ``exchange_times`` in real
    multi-host runs, or are injected by tests/the fault harness —
    training never blocks on missing observations (no signal = no
    straggler)."""

    def __init__(self, k: int = 3):
        self._tracker = StragglerTracker(k)
        self._flagged: Optional[str] = None
        self._obs_n = 0

    @property
    def k(self) -> int:
        return self._tracker.k

    def observe(self, iteration: int,
                host_totals: Dict[str, float]) -> Optional[str]:
        # the tracker's consecutiveness is over the fed sequence numbers;
        # live observations arrive once per iteration BOUNDARY — which is
        # once per CHUNK on the fused path, where raw iteration numbers
        # jump by chunk_size and would reset the run on every
        # observation.  Consecutive OBSERVATIONS are the live rule, so
        # the monitor feeds its own monotone counter (``iteration`` is
        # kept in the signature for log/context parity with the
        # post-mortem rows, whose per-iteration-number gap-reset
        # semantics stay in skew_from_rows).
        self._obs_n += 1
        flagged = self._tracker.update(self._obs_n,
                                       slowest_unique(host_totals))
        if flagged is not None:
            self._flagged = flagged
        return flagged

    def feed(self, iteration: int, host_totals: Dict[str, float]) -> None:
        """Alias of observe() for harness/injection callers."""
        self.observe(iteration, host_totals)

    def take_flagged(self) -> Optional[str]:
        """The flagged host, consumed: the caller is acting on it (mesh
        shrink), so the run-length state resets for the NEW topology."""
        flagged, self._flagged = self._flagged, None
        if flagged is not None:
            self._tracker.reset()
        return flagged

    def reset(self) -> None:
        self._tracker.reset()
        self._flagged = None
        self._obs_n = 0


# ----------------------------------------------------- mesh collectives

# jitted exchange programs per 1-D mesh (the mesh object hashes its device
# assignment, so a rebuilt/shrunk mesh never reuses a stale program)
_TIMES_PROGRAMS: dict = {}
_VOTE_PROGRAMS: dict = {}


def _flat_mesh(mesh):
    """Any training mesh -> a 1-D ``(data,)`` mesh over the same devices
    (the elastic exchanges are per-HOST scalars; the 2-D hybrid factoring
    is irrelevant to them)."""
    from jax.sharding import Mesh
    from .parallel.mesh import DATA_AXIS
    devs = np.asarray(mesh.devices).reshape(-1)
    if tuple(mesh.axis_names) == (DATA_AXIS,):
        return mesh
    return Mesh(devs, (DATA_AXIS,))


def mapped_times_fn(mesh):
    """The all_gather exchange shard_mapped over ``mesh`` — exported
    unjitted so analysis/programs.py can census the EXACT program the
    trainer runs."""
    import jax
    from jax.sharding import PartitionSpec as P
    from .parallel.learners import shard_map
    from .parallel.mesh import DATA_AXIS

    gather = telemetry.collective_span(
        "elastic/times_allgather",
        lambda v: jax.lax.all_gather(v, DATA_AXIS),
        kind="all_gather", axis=DATA_AXIS, phase="elastic")

    def fn(t):
        # t: this shard's [1] seconds -> the replicated [n] vector
        return gather(t).reshape(-1)

    return shard_map(fn, mesh=mesh, in_specs=(P(DATA_AXIS),),
                     out_specs=P())


def mapped_vote_fn(mesh):
    """The survivor-agreement exchange: elementwise ``pmin`` over each
    host's replicated vote vector — every survivor commits to the SAME
    (most conservative) plan before the drain."""
    import jax
    from jax.sharding import PartitionSpec as P
    from .parallel.learners import shard_map
    from .parallel.mesh import DATA_AXIS

    agree = telemetry.collective_span(
        "elastic/survivor_pmin",
        lambda v: jax.lax.pmin(v, DATA_AXIS),
        kind="pmin", axis=DATA_AXIS, phase="elastic")

    def fn(votes):
        return agree(votes)

    return shard_map(fn, mesh=mesh, in_specs=(P(),), out_specs=P())


def exchange_times(mesh, seconds: float,
                   iteration: Optional[int] = None) -> np.ndarray:
    """All hosts' per-iteration seconds, gathered device-slot-wise over
    the (flattened) mesh: returns the identical [n_devices] float32
    vector on every host.  Single-process meshes yield a constant vector
    (one host's clock) — the monitor's strictly-slowest rule then never
    fires, by design.

    When ``iteration`` is given, the EXECUTED blocked window (both
    wall-clock edges of the host-side sync on the gathered result) files
    a ``collective_sync`` flight-recorder event — podtrace's clock-
    alignment sync point when the gather truly spans processes."""
    import jax
    import jax.numpy as jnp
    mesh1d = _flat_mesh(mesh)
    key = mesh1d
    prog = _TIMES_PROGRAMS.get(key)
    if prog is None:
        prog = _TIMES_PROGRAMS[key] = jax.jit(mapped_times_fn(mesh1d))
    n = int(np.asarray(mesh1d.devices).size)
    pod = jax.process_count() > 1
    if pod:
        from jax.sharding import NamedSharding, PartitionSpec
        from .parallel.mesh import DATA_AXIS
        local = np.full(jax.local_device_count(), np.float32(seconds))
        arr = jax.make_array_from_process_local_data(
            NamedSharding(mesh1d, PartitionSpec(DATA_AXIS)), local, (n,))
    else:
        arr = jnp.full((n,), np.float32(seconds))
    with telemetry.span("elastic"):
        t0 = time.time()
        out = np.asarray(prog(arr))
        if iteration is not None:
            tracing.record_collective_sync("elastic/times_allgather",
                                           iteration, t0, time.time(),
                                           pod=pod)
    return out


def agree_survivors(mesh, votes: np.ndarray,
                    iteration: Optional[int] = None) -> np.ndarray:
    """Elementwise minimum of every host's int32 vote vector (replicated
    shapes); the agreed plan all survivors act on.  ``iteration`` files
    the executed blocked window as a ``collective_sync`` event, like
    :func:`exchange_times`."""
    import jax
    import jax.numpy as jnp
    mesh1d = _flat_mesh(mesh)
    key = mesh1d
    prog = _VOTE_PROGRAMS.get(key)
    if prog is None:
        prog = _VOTE_PROGRAMS[key] = jax.jit(mapped_vote_fn(mesh1d))
    with telemetry.span("elastic"):
        t0 = time.time()
        out = np.asarray(prog(jnp.asarray(np.asarray(votes, np.int32))))
        if iteration is not None:
            tracing.record_collective_sync("elastic/survivor_pmin",
                                           iteration, t0, time.time(),
                                           pod=jax.process_count() > 1)
    return out


def host_times_from_gather(gathered: np.ndarray,
                           slots_per_host: int = 1) -> Dict[str, float]:
    """The gathered per-device-slot vector -> per-host totals labeled
    ``p<i>`` (timeline_report's shard labels), one host per
    ``slots_per_host`` consecutive slots."""
    gathered = np.asarray(gathered, np.float64).reshape(-1)
    sph = max(int(slots_per_host), 1)
    out: Dict[str, float] = {}
    for i in range(0, gathered.size, sph):
        out["p%d" % (i // sph)] = float(gathered[i])
    return out
