"""Compiled-program cost registry: roofline attribution + compile observability.

PROFILE.md's roofline rows (histogram attained bandwidth, per-split fixed
costs, "93% of int8 peak") were hand-assembled each round from one-off
probes.  This module makes attained-fraction-of-peak a first-class,
machine-written metric (Williams et al., "Roofline: an insightful visual
performance model", CACM 2009, applied to the histogram-bound cost
structure of LightGBM, Ke et al., NeurIPS 2017):

1. **Program capture.**  ``instrument(name, jax.jit(fn), phase=...)``
   wraps a jitted program.  While the registry is armed, the first call
   of each (shape, dtype, static) signature compiles through the AOT
   path (``fn.lower(...).compile()``) and records the backend's own
   static analysis — ``compiled.cost_analysis()`` (flops, bytes
   accessed), ``compiled.memory_analysis()`` (argument/output/temp
   bytes) — plus the wall-clock compile seconds; subsequent calls run
   the SAME compiled executable directly (identical HLO and compile
   options, so numerics are bit-identical to the plain jit path —
   tests/test_costmodel.py locks this in).  Disabled, the wrapper is a
   flag check and a straight call into the inner jit — zero overhead,
   nothing recorded.

   Contract for instrumented call sites (repo-wide convention already):
   dynamic inputs are POSITIONAL, jit statics are KEYWORD.  A call made
   while JAX is tracing (inner jits inlined into an outer program) or
   under ``jax.disable_jit()`` passes straight through.  Any AOT
   surprise (resharded input, backend quirk) falls back to the inner
   jit and counts ``costmodel/aot_call_fallback`` — capture must never
   break training.

2. **Peak table.**  Per-``device_kind`` hardware ceilings (dense
   flops/sec, int8 ops/sec, HBM bytes/sec) for the TPU generations this
   repo targets.  Unknown kinds (CPU fallback included) degrade to
   ``peaks: "unavailable"`` — attained rates are still reported, the
   fraction-of-peak fields are simply absent.  Never an error.

3. **Roofline join.**  ``roofline(phase_times)`` joins the static
   program costs (flops x calls, bytes x calls per phase label) to the
   telemetry layer's MEASURED phase spans: attained FLOP/s, attained
   HBM GB/s, arithmetic intensity, fraction of peak.  The telemetry
   summary/snapshot and bench.py carry the block; perf_gate.py tracks
   the fractions across BENCH rounds.

   Caveat, stated in the block itself: XLA's cost analysis sees custom
   calls (the Pallas histogram/partition kernels) as opaque — their
   MACs are NOT in ``flops``.  The histogram/partition routing sites
   therefore file ANALYTIC per-pass costs (``note_traced_pass``: the
   dense N*F*B*lanes MAC count PROFILE.md derives by hand) under
   ``traced_passes``, so the Pallas-routed phases keep a machine-written
   cost model too.

4. **Compile observability.**  ``compile_block()``: program count,
   total (cold) compile seconds, warm-program count, plus the telemetry
   counters for true backend compiles, persistent-cache hits and
   mid-run recompiles (telemetry.emit_iteration flags compiles that
   happen after the first iteration record).

Armed/disarmed with the telemetry registry (telemetry.enable/disable/
reset call into here), so every ``metrics_out=`` run gets roofline +
compile blocks with no extra flag.  A program captured in one run stays
usable after ``disable()`` (the wrapper keeps serving the cached
executable — re-compiling it would be strictly worse); ``reset()``
starts a new GENERATION: records re-register lazily on next call,
marked ``warm`` (their compile was paid by a previous run).
"""
from __future__ import annotations

import os
import subprocess
import time
from typing import Any, Dict, List, Optional, Tuple

_enabled = False
_generation = 0
_records: List[dict] = []            # this generation's programs, in order
_pass_notes: Dict[tuple, dict] = {}  # (phase, static key) -> analytic cost


# ------------------------------------------------------------------ life cycle

def enabled() -> bool:
    return _enabled


def active() -> bool:
    """True when there is anything to report (armed, or a previous run's
    records are still registered)."""
    return _enabled or bool(_records) or bool(_pass_notes)


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    """Stop capturing.  Existing records (and cached executables) are
    kept — snapshot()/reports after disable still serve the run's data."""
    global _enabled
    _enabled = False


def reset() -> None:
    """Start a new generation: clear the report tables.  Wrappers keep
    their compiled executables and lazily re-register (as ``warm``) on
    their next call, so a second run in one process reports fresh call
    counts without paying a second compile."""
    global _generation
    _generation += 1
    del _records[:]
    _pass_notes.clear()


# ------------------------------------------------------------------ peak table

# Per-chip ceilings, flop convention matching XLA cost analysis (one FMA =
# 2 flops; the marketing "TFLOPS" numbers already count it that way).
# ici_bytes_per_sec is the per-chip aggregate inter-chip-interconnect
# egress (one direction, all links), from the public per-chip interchip
# bandwidth specs — the seam-roofline denominator podtrace divides
# measured collective GB/s by.  A logical-payload seam can't exceed it,
# so attained/peak is a conservative (under-)estimate of link saturation.
_PEAK_TABLE: Tuple[Tuple[Tuple[str, ...], Dict[str, float]], ...] = (
    (("v6e", "v6 lite", "trillium"),
     {"flops_per_sec": 918e12, "int8_ops_per_sec": 1836e12,
      "hbm_bytes_per_sec": 1640e9, "ici_bytes_per_sec": 448e9}),
    (("v5p",),
     {"flops_per_sec": 459e12, "int8_ops_per_sec": 918e12,
      "hbm_bytes_per_sec": 2765e9, "ici_bytes_per_sec": 600e9}),
    (("v5e", "v5 lite", "v5lite"),
     {"flops_per_sec": 197e12, "int8_ops_per_sec": 394e12,
      "hbm_bytes_per_sec": 819e9, "ici_bytes_per_sec": 200e9}),
    (("v4",),
     {"flops_per_sec": 275e12, "int8_ops_per_sec": 275e12,
      "hbm_bytes_per_sec": 1228e9, "ici_bytes_per_sec": 300e9}),
    (("v3",),
     {"flops_per_sec": 123e12, "int8_ops_per_sec": 123e12,
      "hbm_bytes_per_sec": 900e9, "ici_bytes_per_sec": 280e9}),
)


def device_kind() -> str:
    """The first local device's kind string (e.g. "TPU v5 lite", "cpu").
    Looked up per call — __graft_entry__ steers backends mid-process."""
    try:
        import jax
        return str(jax.local_devices()[0].device_kind)
    except Exception:
        return "unknown"


def resolve_peaks(kind: str) -> Optional[Dict[str, float]]:
    """Peak table lookup by device-kind substring.  None (not an error)
    for unknown kinds — CPU, simulators, future chips."""
    k = (kind or "").lower()
    for subs, peaks in _PEAK_TABLE:
        if any(s in k for s in subs):
            return dict(peaks)
    return None


def host_fingerprint() -> dict:
    """Self-describing host/run metadata (bench.py's ``host`` block):
    device kind, backend, jax/jaxlib versions, git SHA, process count —
    what perf_gate needs to refuse cross-hardware comparisons."""
    out: Dict[str, Any] = {"device_kind": device_kind()}
    try:
        import jax
        out["backend"] = jax.default_backend()
        out["jax_version"] = jax.__version__
        out["process_count"] = jax.process_count()
        out["local_device_count"] = jax.local_device_count()
    except Exception:
        pass
    try:
        import jaxlib
        out["jaxlib_version"] = jaxlib.__version__
    except Exception:
        pass
    try:
        sha = subprocess.run(
            ["git", "-C", os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=5)
        if sha.returncode == 0 and sha.stdout.strip():
            out["git_sha"] = sha.stdout.strip()
    except Exception:
        pass
    return out


# -------------------------------------------------------------- program capture

def _tracing() -> bool:
    # single-homed in telemetry (the span layer's trace/execution split
    # depends on the same check — two copies would drift apart across jax
    # API churn)
    from . import telemetry
    return telemetry._tracing()


def _jit_disabled() -> bool:
    # under jax.disable_jit() the POINT is eager per-op execution
    # (profile_phases --mode=telemetry); serving a compiled program would
    # defeat it
    try:
        import jax
        return bool(jax.config.jax_disable_jit)
    except Exception:
        return False


def _sig(args, kwargs):
    """Hashable call signature: array leaves by (shape, dtype), everything
    else (jit statics) by value."""
    import jax
    leaves, treedef = jax.tree.flatten(
        (args, tuple(sorted(kwargs.items()))))
    key = []
    for leaf in leaves:
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            key.append(("a", tuple(leaf.shape), str(leaf.dtype)))
        else:
            key.append(("v", leaf))
    return (treedef, tuple(key))


def _analyze(compiled) -> dict:
    """Normalize compiled.cost_analysis()/memory_analysis() across
    backends: missing/partial analyses yield None fields, never errors
    (the CPU backend's graceful-degradation contract)."""
    out: Dict[str, Any] = {"flops": None, "bytes_accessed": None,
                           "transcendentals": None, "memory": None}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        if isinstance(ca, dict):
            for field, key in (("flops", "flops"),
                               ("bytes_accessed", "bytes accessed"),
                               ("transcendentals", "transcendentals")):
                if key in ca:
                    try:
                        out[field] = float(ca[key])
                    except (TypeError, ValueError):
                        pass
    except Exception:
        pass
    try:
        ma = compiled.memory_analysis()
        if ma is not None:
            out["memory"] = {
                "argument_bytes": int(getattr(ma, "argument_size_in_bytes",
                                              0)),
                "output_bytes": int(getattr(ma, "output_size_in_bytes", 0)),
                "temp_bytes": int(getattr(ma, "temp_size_in_bytes", 0)),
                "alias_bytes": int(getattr(ma, "alias_size_in_bytes", 0)),
            }
    except Exception:
        pass
    return out


class Instrumented:
    """Cost-capturing wrapper around one jitted program (see module
    docstring for the call-site contract).  One signature-keyed cache of
    (record, compiled executable) per wrapper — wrappers are cached in
    the same program tables (_CHUNK_PROGRAMS etc.) the inner jits were."""
    __slots__ = ("_fn", "name", "phase", "_cache")

    def __init__(self, name: str, fn, phase: Optional[str] = None):
        self._fn = fn
        self.name = name
        self.phase = phase or name
        self._cache: Dict[Any, tuple] = {}

    def lower(self, *args, **kwargs):
        return self._fn.lower(*args, **kwargs)

    def _register(self, rec: dict) -> None:
        # a record whose generation is current is already in _records
        # (appended at capture or at a previous re-register); a stale one
        # re-files with fresh call counts, marked warm — its compile was
        # paid by a previous run
        if rec["gen"] != _generation:
            rec["gen"] = _generation
            rec["calls"] = 0
            rec["warm"] = True
            # no capture happened this generation: nothing to subtract
            # from this run's measured spans
            rec["capture_seconds"] = 0.0
            _records.append(rec)

    def _capture(self, sig, args, kwargs):
        from . import telemetry
        # the inner jit holding a compiled entry means a previous
        # (disarmed) call already paid this program's compile: the AOT
        # re-compile below is NOT this run's cold cost (on TPU the
        # persistent cache makes it a disk hit) — mark the record warm so
        # total_compile_seconds stays honest
        try:
            warm_hint = bool(self._fn._cache_size())
        except Exception:
            warm_hint = False
        t0 = time.perf_counter()
        try:
            compiled = self._fn.lower(*args, **kwargs).compile()
        except Exception as e:
            telemetry.count("costmodel/capture_failed")
            rec = {"name": self.name, "phase": self.phase,
                   "compile_seconds": 0.0, "flops": None,
                   "bytes_accessed": None, "transcendentals": None,
                   "memory": None, "calls": 0, "warm": False,
                   "gen": _generation, "error": type(e).__name__}
            _records.append(rec)
            entry = (rec, None)
            self._cache[sig] = entry
            return entry
        dt = round(time.perf_counter() - t0, 3)
        # the capture ran inside the caller's phase span (the program call
        # site is span-wrapped), so roofline() subtracts this wall time
        # from the measured phase seconds — attained rates must price
        # execution, not compilation, or cold-vs-warm-cache rounds would
        # read as kernel regressions (perf_gate false positives)
        rec = {"name": self.name, "phase": self.phase,
               "compile_seconds": dt, "capture_seconds": dt,
               "calls": 0, "warm": warm_hint, "gen": _generation}
        rec.update(_analyze(compiled))
        _records.append(rec)
        try:
            from . import tracing
            if tracing.active():
                # compile captures on the flight-recorder timeline
                # (ISSUE 16): a mid-run capture next to a latency spike
                # is usually the whole explanation
                tracing.event("compile_capture", name=self.name,
                              phase=self.phase, seconds=dt,
                              warm=warm_hint)
        except Exception:
            pass
        entry = (rec, compiled)
        self._cache[sig] = entry
        return entry

    def __call__(self, *args, **kwargs):
        if ((not _enabled and not self._cache)
                or _tracing() or _jit_disabled()):
            return self._fn(*args, **kwargs)
        try:
            sig = _sig(args, kwargs)
            entry = self._cache.get(sig)
        except Exception:
            return self._fn(*args, **kwargs)
        if entry is None:
            if not _enabled:
                # disarmed: no NEW captures, but cached executables above
                # keep serving (re-compiling a program we hold would be
                # strictly worse)
                return self._fn(*args, **kwargs)
            entry = self._capture(sig, args, kwargs)
        rec, compiled = entry
        if _enabled or active():
            self._register(rec)
            rec["calls"] += 1
        if compiled is not None:
            try:
                return compiled(*args)
            except Exception:
                from . import telemetry
                telemetry.count("costmodel/aot_call_fallback")
                # poison the executable for this signature (keep the
                # record: the static analysis is still right)
                self._cache[sig] = (rec, None)
        return self._fn(*args, **kwargs)


def instrument(name: str, fn, phase: Optional[str] = None) -> Instrumented:
    """Wrap a jitted program for cost capture.  ``phase`` is the
    telemetry span name whose measured seconds this program's static
    costs join against in ``roofline()``."""
    return Instrumented(name, fn, phase=phase)


# -------------------------------------------------------- analytic pass notes

def note_traced_pass(phase: str, key: tuple, **cost) -> None:
    """File an ANALYTIC per-pass cost at trace time (the hand-derived
    numbers PROFILE.md's roofline used: dense MACs per histogram pass,
    bytes moved per partition call).  XLA cost analysis cannot see into
    Pallas custom calls, so these notes are the cost model for the
    Pallas-routed phases.  Deduped by static ``key``; ``traces`` counts
    how many program traces baked this pass in.

    Mixed-bin packing (ISSUE 6): a histogram level pass over a packed
    dataset is one pass PER bin-width class, and the routing layer files
    one note per class with a trailing ``binclass<width>`` key element
    (ops/histogram._note_hist_pass) — so the roofline block attributes
    narrow-class and wide-class cost separately instead of pricing every
    feature at the uniform worst case, and the modeled MAC total shrinks
    in step with the measured seconds."""
    if not _enabled:
        return
    k = (phase, key)
    note = _pass_notes.get(k)
    if note is None:
        note = {"phase": phase, "key": list(key), "traces": 0}
        note.update({f: float(v) for f, v in cost.items()})
        _pass_notes[k] = note
    note["traces"] += 1


# ------------------------------------------------------------------- reporting

def roofline(phase_times: Dict[str, float],
             kind: Optional[str] = None,
             fenced: Optional[bool] = None) -> dict:
    """Join static program costs to measured phase seconds.

    ``phase_times``: the telemetry layer's cumulative execution spans.
    Per phase: total flops/bytes (cost x calls), attained FLOP/s and HBM
    GB/s over the measured seconds, arithmetic intensity, and — when the
    device kind is in the peak table — fraction-of-peak fields.  Unknown
    kinds report ``peaks: "unavailable"`` and skip only the fractions.

    ``fenced``: whether the spans ran in telemetry fence mode.  On an
    async-dispatch backend (TPU) UNFENCED spans time the dispatch, not
    the execution — the block carries ``fenced_spans`` so consumers
    (perf_gate, PROFILE rounds) know whether the attained rates are
    meaningful; bench.py fences its depthwise runs for exactly this
    reason."""
    kind = kind if kind is not None else device_kind()
    peaks = resolve_peaks(kind)
    agg: Dict[str, dict] = {}
    for rec in _records:
        p = rec.get("phase") or "other"
        a = agg.setdefault(p, {"flops": 0.0, "bytes_accessed": 0.0,
                               "programs": 0, "calls": 0, "capture": 0.0,
                               "flops_unknown": False})
        a["programs"] += 1
        a["calls"] += int(rec.get("calls", 0))
        a["capture"] += float(rec.get("capture_seconds", 0.0))
        for field in ("flops", "bytes_accessed"):
            v = rec.get(field)
            if v is None:
                a["flops_unknown"] = True
            else:
                a[field] += v * int(rec.get("calls", 0))
    phases: Dict[str, dict] = {}
    for p, a in sorted(agg.items()):
        secs = float(phase_times.get(p, 0.0))
        # the first armed call's AOT capture (lower + compile) ran inside
        # this phase's span: attained rates price EXECUTION seconds only,
        # so a cold compile cache cannot read as a kernel regression
        exec_secs = secs - a["capture"] if secs > 0.0 else secs
        blk: Dict[str, Any] = {
            "flops": round(a["flops"], 1),
            "bytes_accessed": round(a["bytes_accessed"], 1),
            "programs": a["programs"], "calls": a["calls"],
            "seconds": round(secs, 6),
        }
        if a["capture"] > 0.0 and secs > 0.0:
            blk["compile_seconds_excluded"] = round(a["capture"], 6)
        if a["flops_unknown"]:
            blk["cost_analysis"] = "partial"
        if exec_secs > 0.0:
            blk["attained_flops_per_sec"] = round(a["flops"] / exec_secs, 1)
            blk["attained_hbm_gbps"] = round(
                a["bytes_accessed"] / exec_secs / 1e9, 4)
            if a["bytes_accessed"] > 0.0:
                blk["arithmetic_intensity"] = round(
                    a["flops"] / a["bytes_accessed"], 4)
            if peaks:
                blk["frac_of_peak_flops"] = round(
                    a["flops"] / exec_secs / peaks["flops_per_sec"], 6)
                blk["frac_of_peak_bw"] = round(
                    a["bytes_accessed"] / exec_secs
                    / peaks["hbm_bytes_per_sec"], 6)
        phases[p] = blk
    out: Dict[str, Any] = {
        "device_kind": kind,
        "peaks": peaks if peaks else "unavailable",
        "phases": phases,
        # honesty marker: Pallas custom calls are opaque to XLA cost
        # analysis — their MACs live in traced_passes, not in flops
        "method": "xla_cost_analysis+measured_spans; custom-call (Pallas) "
                  "flops are analytic (traced_passes), not in phase flops",
    }
    if fenced is not None:
        out["fenced_spans"] = bool(fenced)
        if not fenced:
            out["method"] += ("; spans UNFENCED — on async backends "
                              "attained rates time dispatch, not "
                              "execution (metrics_fence=true to fix)")
    if _pass_notes:
        out["traced_passes"] = [dict(n) for _, n in
                                sorted(_pass_notes.items(),
                                       key=lambda kv: kv[0])]
    return out


def phase_program_records(phase: str) -> List[dict]:
    """This generation's captured-program records filed under one phase
    label (copies).  The serving no-recompile assertion reads this: a
    steady-state bucketed engine must keep a CLOSED program inventory —
    repeated calls at a bucket shape bump ``calls`` on existing records
    and never add a new one (tests/test_serving.py, bench.py
    bench_predict lane)."""
    return [dict(r) for r in _records if r.get("phase") == phase]


def compile_block() -> dict:
    """Run-level compile observability: captured-program inventory,
    total cold-compile seconds, and the telemetry compile counters
    (true backend compiles, persistent-cache hits, mid-run recompiles)."""
    from . import telemetry
    programs = []
    for rec in _records:
        p = {"name": rec["name"], "phase": rec["phase"],
             "compile_seconds": rec["compile_seconds"],
             "calls": rec["calls"]}
        for field in ("flops", "bytes_accessed", "memory", "error"):
            if rec.get(field) is not None:
                p[field] = rec[field]
        if rec.get("warm"):
            p["warm"] = True
        programs.append(p)
    counters = telemetry.counters()
    return {
        "program_count": len(_records),
        "total_compile_seconds": round(
            sum(r["compile_seconds"] for r in _records
                if not r.get("warm")), 3),
        "warm_programs": sum(1 for r in _records if r.get("warm")),
        "backend_compiles": counters.get("jit/backend_compile", 0),
        "persistent_cache_hits": counters.get("jit/persistent_cache_hit",
                                              0),
        "midrun_recompiles": counters.get("jit/midrun_recompile", 0),
        "programs": programs,
    }
