"""Host-side data layer: parsers, binning, Dataset, Metadata."""
from .dataset import Dataset
from .binning import BinMapper
from .metadata import Metadata
