"""Host-side data layer: parsers, binning, Dataset, Metadata."""
import os as _os

if _os.environ.get("LIGHTGBM_TPU_INGEST_WORKER") != "1":
    # exec'd parallel-parse workers (parallel_ingest.py) skip the
    # Dataset import — it pulls the whole JAX model stack
    from .dataset import Dataset
    from .binning import BinMapper
    from .metadata import Metadata
