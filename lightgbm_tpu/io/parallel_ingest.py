"""Process-parallel byte-range ingest (ISSUE 18).

The streaming loader (io/streaming.load_train_streaming) tokenizes the
whole text file twice on one core — and PR 17's per-chunk attribution
proved that tokenizer IS the declining ingest_rows_per_sec wall
(ingest_sync ≈ ingest: the device pipeline hides nothing).  This module
is the reference's pipeline_reader.h generalized to worker PROCESSES
over disjoint byte ranges (parser.split_byte_ranges snaps split points
to row starts, so no two workers ever read the same bytes), with two
structural savings on top of core-parallelism:

- **Pass 0 is folded into the split scan**: one raw byte scan yields the
  snapped ranges AND the per-range/total row counts, so the file is read
  twice per load, not three times.
- **Pass 1 is selective**: only the label / in-file weight / in-file
  group columns are extracted for every row (a positional token split +
  the exact ``_atof`` semantics both full-parse tiers reduce to), and
  the full tokenizer runs ONLY over the ≤SAMPLE_CNT pinned sample rows.
  The serial loader full-parses every row twice; this path full-parses
  every row once — the dominant term of the measured speedup on hosts
  where cores don't help (bench lane: PROFILE.md's ingest cost model).

Distributed loads (num_machines > 1) add the pod-scale cut: pass 2
parses ONLY the rows of this host's shard (the mask is drawn up front —
it depends only on the seed, the row count and the SIDE-file query
boundaries, all known before pass 1), where the serial path tokenizes
the full file on every host and masks after parse.  Pass 1 stays
full-file on purpose: labels/weights/groups enter metadata full-length
before ``partition`` (the serial order of operations), and the binning
sample is global.

Bit-identity with the serial loader is the correctness bar and is
test-pinned end to end (tests/test_parallel_ingest.py): same mappers,
same bin matrix bytes, same streamed cache bytes, same metadata, same
trained model text — at any worker count, including the sharded
multi-process path.  Everything order-sensitive is assembled in the
parent in range order; the pinned-sample reservoir is filled per GLOBAL
row id, so each range writes only its slice of the draw.

Workers are exec'd processes (``python -m lightgbm_tpu.io.parallel_ingest``),
NOT forks: forking the training process deadlocks once the XLA
backend's threads are live (the forked child inherits locked mutexes no
surviving thread will ever release — reproduced mid-suite in tier-1),
and every ``multiprocessing`` start method either forks the parent or
re-imports ``__main__`` in the child (the spawn/forkserver preparation
step — wrong and slow for a ``bench.py``/stdin parent).  So the pool
execs clean interpreters that import ONLY the numpy parse stack (the
package ``__init__`` skips its JAX surface under
``LIGHTGBM_TPU_INGEST_WORKER=1``; startup is milliseconds) and speaks
length-free pickle frames over stdin/stdout.  Workers PERSIST across
passes and loads (module-global pool, atexit-reaped) so repeat loads
pay zero spawn cost; per-pass job state (parser, ranges, mappers) is
re-broadcast into each worker's ``_JOB`` before its tasks.  Workers
return measured parse/bin times; the parent files the ``ingest/*``
counters and
``record_ingest_chunk`` events (with the worker id, so per-worker parse
spans land in the flight-recorder ring and pod_report attribution keeps
working), plus ``ingest/worker_wait_us`` — the parent's time actually
blocked on worker results, the residual tokenizer wall that shrinks as
workers scale.
"""
from __future__ import annotations

import collections
import os
import sys
import time
from typing import List, Optional

import numpy as np

from .. import telemetry, tracing
from ..utils import log
from . import parser as parser_mod
from .parser import ZERO_THRESHOLD, _atof, _DelimitedParser

# in-flight task window per pool: enough to keep every worker busy while
# the parent drains results in range order, small enough that buffered
# results (one range's sample/bin payload each) stay bounded
_WINDOW_EXTRA = 2

_JOB = None      # per-pass worker state; broadcast before each pass
_WORKERS: List["_Worker"] = []  # persistent exec'd pool, atexit-reaped
_REAPER_ARMED = False

WORKER_ENV = "LIGHTGBM_TPU_INGEST_WORKER"


def available() -> bool:
    """Parallel parse execs fresh interpreters (never forks the
    JAX-threaded trainer), so it only needs a launchable
    ``sys.executable``."""
    try:
        return bool(sys.executable) and os.path.exists(sys.executable)
    except Exception:
        return False


class _Job:
    """Per-pass worker state, broadcast to each worker as one pickle."""

    def __init__(self, **kw):
        self.__dict__.update(kw)


class _InlineResult:
    def __init__(self, fn, args):
        self._fn, self._args = fn, args

    def get(self):
        return self._fn(*self._args)


class _InlinePool:
    """``workers == 1`` — the pod-sharded parse with no parallelism
    requested: run the range jobs in-process through the same code
    path, skipping the worker spawn cost every multi-process load would
    otherwise pay per pass."""

    def apply_async(self, fn, args):
        return _InlineResult(fn, args)

    def terminate(self):
        pass

    def join(self):
        pass


class _Worker:
    """One exec'd worker: pickle frames over stdin/stdout (pickle is
    self-delimiting, so no length prefix); stderr passes through."""

    def __init__(self):
        import pickle
        import subprocess
        env = dict(os.environ)
        env[WORKER_ENV] = "1"
        # the worker resolves this package by import, wherever the
        # parent loaded it from
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = (pkg_root + os.pathsep
                             + env.get("PYTHONPATH", "")).rstrip(os.pathsep)
        self._pickle = pickle
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "lightgbm_tpu.io.parallel_ingest"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, env=env)
        # a 64KB default pipe stalls the worker mid-result while the
        # parent is committing an earlier range; at the kernel cap a
        # whole binned-range payload fits, so workers parse ahead
        # instead of blocking (the overlap the fork-Pool's reader
        # thread used to provide)
        try:
            import fcntl
            fcntl.fcntl(self.proc.stdout.fileno(),
                        getattr(fcntl, "F_SETPIPE_SZ", 1031), 1 << 20)
        except Exception:
            pass

    def send(self, msg) -> None:
        self._pickle.dump(msg, self.proc.stdin,
                          protocol=self._pickle.HIGHEST_PROTOCOL)
        self.proc.stdin.flush()

    def recv(self):
        try:
            kind, payload = self._pickle.load(self.proc.stdout)
        except EOFError:
            raise RuntimeError(
                "parallel ingest worker (pid %s) exited mid-task"
                % self.proc.pid)
        if kind == "err":
            raise RuntimeError(
                "parallel ingest worker task failed:\n%s" % payload)
        return payload

    def close(self) -> None:
        try:
            self.send(("exit",))
            self.proc.stdin.close()
        except Exception:
            pass
        try:
            self.proc.wait(timeout=5)
        except Exception:
            self.proc.kill()
            self.proc.wait()


def shutdown_workers() -> None:
    """Reap the persistent pool (atexit; also the desync escape: a load
    that died mid-pass may leave queued tasks, so the broken workers are
    dropped and the next load respawns clean ones)."""
    global _WORKERS
    workers, _WORKERS = _WORKERS, []
    for w in workers:
        w.close()


class _SubprocPool:
    """apply_async/terminate/join shim over the persistent workers.

    Tasks are dealt round-robin; each worker answers its own stdin
    queue in FIFO order, so reading results in submission order per
    worker keeps the parent's range-ordered drain exact."""

    def __init__(self, workers: int, job):
        global _REAPER_ARMED
        _WORKERS[:] = [w for w in _WORKERS if w.proc.poll() is None]
        while len(_WORKERS) < workers:
            _WORKERS.append(_Worker())
        if not _REAPER_ARMED:
            import atexit
            atexit.register(shutdown_workers)
            _REAPER_ARMED = True
        self.ws = _WORKERS[:workers]
        self.rr = 0
        self.outstanding = 0
        for w in self.ws:
            w.send(("job", job))

    def apply_async(self, fn, args):
        w = self.ws[self.rr % len(self.ws)]
        self.rr += 1
        w.send(("task", fn.__name__, args[0]))
        self.outstanding += 1
        return _PoolResult(self, w)

    def terminate(self):
        if self.outstanding:
            shutdown_workers()

    def join(self):
        pass


class _PoolResult:
    def __init__(self, pool: _SubprocPool, worker: _Worker):
        self._pool, self._worker = pool, worker

    def get(self):
        res = self._worker.recv()
        self._pool.outstanding -= 1
        return res


def _pool(workers: int, job):
    global _JOB
    _JOB = job
    if int(workers) <= 1:
        return _InlinePool()
    return _SubprocPool(int(workers), job)


def _bounded_imap(pool, fn, n_tasks: int, window: int):
    """Ordered results with at most ``window`` tasks in flight — the
    backpressure Pool.imap lacks (its result cache would otherwise
    buffer every completed range while the parent is mid-commit)."""
    pending: "collections.deque" = collections.deque()
    nxt = 0
    while nxt < min(window, n_tasks):
        pending.append(pool.apply_async(fn, (nxt,)))
        nxt += 1
    while pending:
        t0 = time.perf_counter()
        res = pending.popleft().get()
        telemetry.count("ingest/worker_wait_us",
                        int((time.perf_counter() - t0) * 1e6))
        if nxt < n_tasks:
            pending.append(pool.apply_async(fn, (nxt,)))
            nxt += 1
        yield res


def plan_ranges(filename: str, skip_header: bool, workers: int,
                chunk_rows: int):
    """Choose and snap the byte ranges (the fused pass-0 scan).

    Ranges are byte-balanced at ~4 tasks per worker (clamped to
    [1MB, 32MB] targets), then re-split until no range exceeds
    ``ingest_chunk_rows`` rows — the streaming tier's host-residency
    bound applies per worker payload exactly as it does per serial
    chunk."""
    size = os.path.getsize(filename)
    d0 = parser_mod.data_byte_start(filename, skip_header)
    data_bytes = max(size - d0, 1)
    target = min(max(data_bytes // max(workers * 4, 1), 1 << 20), 32 << 20)
    k = max(workers, -(-data_bytes // target))
    ranges, counts, total = parser_mod.split_byte_ranges(
        filename, k, skip_header=skip_header)
    for _ in range(8):
        if not any(c > chunk_rows for c in counts):
            break
        cands = []
        for (s, e), c in zip(ranges, counts):
            cands.append(s)
            if c > chunk_rows:
                parts = -(-c // chunk_rows)
                cands.extend(s + ((e - s) * i) // parts
                             for i in range(1, parts))
        ranges, counts, total = parser_mod.split_byte_ranges_at(
            filename, cands[1:], skip_header=skip_header)
    return ranges, counts, total


# ------------------------------------------------------------ pass 1


def _extract_column(lines, delim: str, raw_idx: int) -> np.ndarray:
    """One raw column as float64 via the exact-tier token semantics
    (``_atof``): bit-identical to slicing the full-parse matrix —
    round_trip IS float(), and both tiers map na/garbage tokens to 0."""
    if raw_idx == 0:
        toks = [ln.split(delim, 1)[0] for ln in lines]
    else:
        n = raw_idx + 1
        toks = [ln.split(delim, n)[raw_idx] for ln in lines]
    return np.array([_atof(t) for t in toks], dtype=np.float64)


def _pass1_range(ridx: int):
    job = _JOB
    t0 = time.perf_counter()
    s, e = job.ranges[ridx]
    lines = parser_mod.read_range_lines(job.filename, s, e)
    n = len(lines)
    g0 = job.offsets[ridx]
    out = {"ridx": ridx, "n": n, "pid": os.getpid()}
    local = None
    if job.sample_idx is not None:
        lo = np.searchsorted(job.sample_idx, g0)
        hi = np.searchsorted(job.sample_idx, g0 + n)
        local = job.sample_idx[lo:hi] - g0
    delim = job.delimiter
    selective = delim is not None and local is not None and n > 0
    if selective:
        n_delim = lines[0].count(delim)
        if any(ln.count(delim) != n_delim for ln in lines):
            # ragged range: the full parser reproduces the exact tier's
            # format-error fatal (or its values, for short first lines)
            selective = False
    if selective:
        ncols_raw = n_delim + 1
        li = job.label_raw
        has_label = 0 <= li < ncols_raw
        out["num_cols"] = ncols_raw - 1 if has_label else ncols_raw
        if has_label:
            out["labels"] = _extract_column(lines, delim, li).astype(
                np.float32)
        else:
            out["labels"] = np.zeros(n, dtype=np.float32)
        for key, fidx in (("weight", job.weight_idx),
                          ("group", job.group_idx)):
            if fidx >= 0:
                raw = fidx + (1 if has_label and fidx >= li else 0)
                col = _extract_column(lines, delim, raw)
                # parse() zero-drops features AFTER label removal; the
                # weight/group slices the serial pass 1 takes are
                # post-threshold values
                col[np.abs(col) <= ZERO_THRESHOLD] = 0.0
                out[key] = (col.astype(np.float32) if key == "weight"
                            else col)
        if local.size:
            out["sample"] = job.parser.parse(
                [lines[i] for i in local]).features
    else:
        parsed = job.parser.parse(lines)
        feats = parsed.features
        out["num_cols"] = feats.shape[1]
        out["labels"] = parsed.labels
        if job.weight_idx >= 0:
            out["weight"] = feats[:, job.weight_idx].astype(np.float32)
        if job.group_idx >= 0:
            out["group"] = feats[:, job.group_idx].copy()
        if local is None:
            out["sample"] = feats
        elif local.size:
            out["sample"] = feats[local]
    out["parse_us"] = (time.perf_counter() - t0) * 1e6
    return out


# ------------------------------------------------------------ pass 2


def _pass2_range(ridx: int):
    job = _JOB
    t0 = time.perf_counter()
    s, e = job.ranges[ridx]
    lines = parser_mod.read_range_lines(job.filename, s, e)
    c0 = len(lines)
    sel = job.sel_local[ridx] if job.sel_local is not None else None
    if sel is not None:
        lines = [lines[i] for i in sel]
    if lines:
        feats = job.parser.parse(lines).features
    else:
        feats = np.zeros((0, job.num_cols), dtype=np.float64)
    t1 = time.perf_counter()
    n = feats.shape[0]
    binned = np.empty((len(job.mappers), n), dtype=job.dtype)
    for j_raw, j_inner in job.used_feature_map.items():
        binned[j_inner] = job.mappers[j_inner].value_to_bin(
            feats[:, j_raw]).astype(job.dtype)
    t2 = time.perf_counter()
    return (ridx, c0, n, binned, feats if job.need_feats else None,
            (t1 - t0) * 1e6, (t2 - t1) * 1e6, os.getpid())


# ------------------------------------------------------------ the load


def load_train_streaming_parallel(
        ds, io_config, parser, rank: int, num_machines: int, predict_fun,
        bin_finder, weight_idx: int, group_idx: int, ignore_set,
        header_names, shard_rows: bool = False,
        shard_devices: Optional[int] = None, device_type: str = "",
        foreign_bin: bool = False, workers: int = 2) -> None:
    """The parallel twin of ``streaming.load_train_streaming`` — same
    passes, same metadata order of operations, same counters/events/
    guards, with parse (and bin) fanned out over byte-range workers."""
    from . import dataset as dataset_mod
    from . import streaming

    filename = io_config.data_filename
    chunk_rows = getattr(io_config, "ingest_chunk_rows", 200_000)
    device_resident = num_machines <= 1 and streaming.single_process()
    workers = max(int(workers), 1)
    window = workers + _WINDOW_EXTRA
    ds.ingest_workers_effective = workers

    with telemetry.span("ingest"):
        # ---- pass 0, folded into the byte-range split: ONE raw scan
        t_pass = time.perf_counter()
        with telemetry.span("ingest_count"):
            ranges, counts, total_rows = plan_ranges(
                filename, io_config.has_header, workers, chunk_rows)
        tracing.record_ingest_pass(0, time.perf_counter() - t_pass,
                                   total_rows)
        ds.global_num_data = total_rows
        sample_idx = streaming.pinned_sample_indices(
            total_rows, io_config.data_random_seed, dataset_mod.SAMPLE_CNT)
        offsets = np.concatenate(
            ([0], np.cumsum(counts))).astype(np.int64)
        k = len(ranges)

        # shard mask up front (serial draws it after pass 1): the draw
        # reads only the seed, the row count and the SIDE-file query
        # boundaries — none of which pass 1 touches — so the mask is
        # bit-identical, and pass 2 can parse owned rows only
        ds.used_data_indices = ds._draw_shard_mask(io_config, rank,
                                                   num_machines,
                                                   total_rows)

        # ---- pass 1 (pooled): selective label/side-column scan; the
        # full tokenizer runs only over the pinned sample rows
        delim = (parser.delimiter
                 if isinstance(parser, _DelimitedParser) else None)
        job = _Job(filename=filename, ranges=ranges,
                   offsets=offsets[:-1], parser=parser, delimiter=delim,
                   label_raw=parser.label_idx, sample_idx=sample_idx,
                   weight_idx=weight_idx, group_idx=group_idx)
        labels_parts: List[np.ndarray] = []
        weight_parts: List[np.ndarray] = []
        group_parts: List[np.ndarray] = []
        sample_parts: List[np.ndarray] = []
        reservoir = None
        num_cols = None
        start = 0
        t_pass = time.perf_counter()
        with telemetry.span("ingest_pass1"):
            pool = _pool(workers, job)
            try:
                for out in _bounded_imap(pool, _pass1_range, k, window):
                    n = out["n"]
                    g0 = int(offsets[out["ridx"]])
                    num_cols = out["num_cols"]
                    labels_parts.append(out["labels"])
                    if weight_idx >= 0:
                        weight_parts.append(out["weight"])
                    if group_idx >= 0:
                        group_parts.append(out["group"])
                    if sample_idx is None:
                        if "sample" in out:
                            sample_parts.append(out["sample"])
                    elif "sample" in out:
                        if reservoir is None:
                            reservoir = np.empty(
                                (sample_idx.size, num_cols), np.float64)
                        lo = np.searchsorted(sample_idx, g0)
                        hi = np.searchsorted(sample_idx, g0 + n)
                        reservoir[lo:hi] = out["sample"]
                    telemetry.count("ingest/parse_us",
                                    int(out["parse_us"]))
                    tracing.record_ingest_chunk(
                        1, out["ridx"], n, out["parse_us"], 0.0, 0.0,
                        worker=out["pid"])
                    start += n
            finally:
                pool.terminate()
                pool.join()
        tracing.record_ingest_pass(1, time.perf_counter() - t_pass, start)
        log.check(start == total_rows,
                  "Input file changed between the streaming passes "
                  f"(pass 0: {total_rows} rows, pass 1: {start})")
        if sample_idx is None:
            sample = (np.concatenate(sample_parts) if sample_parts
                      else np.zeros((0, 0), np.float64))
        else:
            sample = reservoir
        del sample_parts, reservoir

        ds.num_total_features = num_cols or 0
        ds.feature_names = dataset_mod._make_feature_names(
            header_names, ds.label_idx, ds.num_total_features)

        ds._build_bin_mappers(sample, io_config.max_bin, bin_finder,
                              ignore_set)
        del sample

        if weight_idx >= 0:
            log.info("using weight in data file, and ignore additional "
                     "weight file")
            ds.metadata.weights = np.concatenate(weight_parts)
        if group_idx >= 0:
            log.info("using query id in data file, and ignore additional "
                     "query file")
            ds.metadata.query_boundaries = None
            ds.metadata.set_queries_from_column(np.concatenate(group_parts))

        all_labels = (np.concatenate(labels_parts) if labels_parts
                      else np.zeros((0,), np.float32))
        ds.metadata.set_label(all_labels)
        if ds.used_data_indices is not None:
            if ds.metadata.queries is not None:
                ds.metadata.queries = \
                    ds.metadata.queries[ds.used_data_indices]
            ds.metadata.partition(ds.used_data_indices, total_rows)
            ds.num_data = len(ds.used_data_indices)
        else:
            ds.num_data = total_rows
        ds.metadata.finalize(ds.num_data)

        # ---- pass 2 (pooled): workers parse+quantize their ranges —
        # owned rows only under a shard mask (the pod-scale cut: the
        # serial path tokenizes the full file on every host) — and the
        # parent commits ranges in order: cache write, device append,
        # init scores, counters
        F_used = len(ds.bin_mappers)
        dtype = dataset_mod._bin_dtype(
            int(ds.num_bins.max()) if F_used else 256)
        writer = (streaming.DeviceRowWriter(
                      F_used, ds.num_data, dtype,
                      sharding=streaming._placement(
                          ds.num_data, shard_rows, shard_devices,
                          device_type))
                  if device_resident
                  else streaming.HostRowWriter(F_used, ds.num_data, dtype))
        cache = streaming._open_cache(ds, io_config, dtype,
                                      (F_used, ds.num_data), foreign_bin)
        sel_local = None
        if ds.used_data_indices is not None:
            owned = ds.used_data_indices
            sel_local = []
            for ridx in range(k):
                g0, g1 = int(offsets[ridx]), int(offsets[ridx + 1])
                lo = np.searchsorted(owned, g0)
                hi = np.searchsorted(owned, g1)
                sel_local.append((owned[lo:hi] - g0).astype(np.int64))
        job2 = _Job(filename=filename, ranges=ranges, parser=parser,
                    mappers=ds.bin_mappers,
                    used_feature_map=ds.used_feature_map, dtype=dtype,
                    sel_local=sel_local, num_cols=num_cols or 0,
                    need_feats=predict_fun is not None)
        init_scores = [] if predict_fun is not None else None
        cursor = 0
        start = 0
        t_pass = time.perf_counter()
        try:
            pool = _pool(workers, job2)
            try:
                for (ridx, c0, n, binned, feats, parse_us, bin_us,
                     pid) in _bounded_imap(pool, _pass2_range, k, window):
                    with telemetry.span("ingest_bin"):
                        t2 = time.perf_counter()
                        if n:
                            if init_scores is not None:
                                init_scores.append(np.asarray(
                                    predict_fun(feats),
                                    np.float32).reshape(-1))
                            if cache is not None:
                                cache.write(binned, cursor)
                            writer.append(binned, cursor)
                        t_h2d = time.perf_counter()
                    h2d_us = (t_h2d - t2) * 1e6
                    telemetry.count("ingest/chunks")
                    telemetry.count("ingest/rows", n)
                    telemetry.count("ingest/parse_us", int(parse_us))
                    telemetry.count("ingest/bin_us", int(bin_us))
                    telemetry.count("ingest/h2d_us", int(h2d_us))
                    tracing.record_ingest_chunk(2, ridx, n, parse_us,
                                                bin_us, h2d_us,
                                                worker=pid)
                    cursor += n
                    start += c0
            finally:
                pool.terminate()
                pool.join()
            log.check(start == total_rows and cursor == ds.num_data,
                      "Input file changed between the streaming passes "
                      f"(pass 1: {total_rows} rows, pass 2: {start})")
            tracing.record_ingest_pass(2, time.perf_counter() - t_pass,
                                       cursor)
            t_fin = time.perf_counter()
            out = writer.finish()
            telemetry.count("ingest/h2d_us",
                            int((time.perf_counter() - t_fin) * 1e6))
            if device_resident:
                ds.device_bins = out
                ds.bins = None
            else:
                ds.bins = out
            if init_scores is not None:
                ds.metadata.init_score = np.concatenate(init_scores)
            if cache is not None:
                cache.finish()
        except BaseException:
            if cache is not None:
                cache.abort()
            raise


# ------------------------------------------------------- worker entry


def _worker_main() -> int:
    """The exec'd worker loop: ``("job", job)`` lands per-pass state,
    ``("task", fn_name, ridx)`` runs one range and answers
    ``("ok", result)`` or ``("err", traceback)``, ``("exit",)``/EOF
    stops.  The protocol owns the real stdout; accidental prints from
    library code are re-routed to stderr."""
    import pickle
    import traceback
    global _JOB
    inp = sys.stdin.buffer
    out = sys.stdout.buffer
    sys.stdout = sys.stderr
    while True:
        try:
            msg = pickle.load(inp)
        except EOFError:
            return 0
        if msg[0] == "exit":
            return 0
        if msg[0] == "job":
            _JOB = msg[1]
            continue
        try:
            res = ("ok", globals()[msg[1]](msg[2]))
        except BaseException:
            res = ("err", traceback.format_exc())
        pickle.dump(res, out, protocol=pickle.HIGHEST_PROTOCOL)
        out.flush()


if __name__ == "__main__":
    sys.exit(_worker_main())
