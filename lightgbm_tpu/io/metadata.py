"""Metadata: labels, weights, query boundaries, init scores.

Re-design of /root/reference/src/io/metadata.cpp:10-369 and
include/LightGBM/dataset.h:34-207 as a NumPy container.  Side-file
conventions preserved: ``<data>.weight`` (one weight per line),
``<data>.query`` (one per-query document count per line), plus an optional
explicit init-score file.  Query-id columns in the data file are converted to
boundaries exactly like Metadata::CheckOrPartition (metadata.cpp:79-106).
"""
from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

from ..utils import log


class Metadata:
    def __init__(self):
        self.num_data: int = 0
        self.label: Optional[np.ndarray] = None            # float32 [N]
        self.weights: Optional[np.ndarray] = None          # float32 [N]
        self.query_boundaries: Optional[np.ndarray] = None  # int32 [num_queries+1]
        self.query_weights: Optional[np.ndarray] = None    # float32 [num_queries]
        self.init_score: Optional[np.ndarray] = None       # float32 [N]
        self.queries: Optional[np.ndarray] = None          # raw per-row query ids

    # --- loading (metadata.cpp:228-299) ---

    def init_from_files(self, data_filename: str, init_score_filename: str = "") -> None:
        self._load_query_boundaries(data_filename + ".query")
        self._load_weights(data_filename + ".weight")
        self._load_query_weights()
        if init_score_filename:
            self._load_init_score(init_score_filename)

    def _load_weights(self, path: str) -> None:
        if not os.path.exists(path):
            return
        log.info("Start loading weights")
        self.weights = np.loadtxt(path, dtype=np.float64, ndmin=1).astype(np.float32)

    def _load_query_boundaries(self, path: str) -> None:
        if not os.path.exists(path):
            return
        log.info("Start loading query boundries")
        counts = np.loadtxt(path, dtype=np.int64, ndmin=1)
        boundaries = np.zeros(counts.size + 1, dtype=np.int32)
        boundaries[1:] = np.cumsum(counts)
        self.query_boundaries = boundaries

    def _load_init_score(self, path: str) -> None:
        log.info("Start loading initial scores")
        self.init_score = np.loadtxt(path, dtype=np.float64, ndmin=1).astype(np.float32)

    def _load_query_weights(self) -> None:
        """Per-query mean of record weights (metadata.cpp:285-299)."""
        if self.weights is None or self.query_boundaries is None:
            return
        log.info("Start loading query weights")
        nq = self.query_boundaries.size - 1
        qw = np.zeros(nq, dtype=np.float32)
        for i in range(nq):
            lo, hi = self.query_boundaries[i], self.query_boundaries[i + 1]
            qw[i] = self.weights[lo:hi].mean() if hi > lo else 0.0
        self.query_weights = qw

    def global_view(self, gather_rows) -> "Metadata":
        """Rebuild the GLOBAL metadata from this process's row shard.

        ``gather_rows(local_rows) -> global_rows`` concatenates every
        process's row-aligned array in process order
        (mesh.gather_ragged_rows).  Row sharding is query-atomic
        (dataset.cpp:189-206), so
        local query boundaries concatenate into valid global boundaries with
        per-process row offsets.  Metrics evaluated against this view over
        the identically-ordered gathered score reproduce the serial
        values exactly — stronger than the reference's per-machine training
        metrics (gbdt.cpp:225-259 evaluates each machine's local rows)."""
        g = Metadata()
        if self.label is not None:
            g.set_label(gather_rows(self.label))
        if self.weights is not None:
            g.weights = gather_rows(self.weights)
        # init_score is deliberately NOT gathered: metrics read only
        # label/weights/query layout, and scores already carry it
        if self.query_boundaries is not None:
            # counts survive concatenation; boundaries are their cumsum
            counts = np.diff(self.query_boundaries).astype(np.int64)
            gcounts = gather_rows(counts)
            boundaries = np.zeros(gcounts.size + 1, dtype=np.int32)
            boundaries[1:] = np.cumsum(gcounts)
            g.query_boundaries = boundaries
            g._load_query_weights()
        return g

    # --- finalization (metadata.cpp:79-160 CheckOrPartition, no-partition path) ---

    def set_label(self, label: np.ndarray) -> None:
        self.label = np.asarray(label, dtype=np.float32)
        self.num_data = self.label.size

    def set_queries_from_column(self, queries: np.ndarray) -> None:
        """Query-id column → boundaries (metadata.cpp:81-106): a new query
        starts whenever the id changes."""
        self.queries = np.asarray(queries)

    def finalize(self, num_data: int) -> None:
        self.num_data = num_data
        if self.queries is not None:
            q = self.queries
            change = np.nonzero(q[1:] != q[:-1])[0] + 1
            starts = np.concatenate(([0], change, [q.size]))
            self.query_boundaries = starts.astype(np.int32)
            self._load_query_weights()
            self.queries = None
        if self.weights is not None and self.weights.size != num_data:
            log.fatal("Initial weight size doesn't equal to data")
        if (self.query_boundaries is not None
                and self.query_boundaries[-1] != num_data):
            log.fatal("Initial query size doesn't equal to data")
        if self.init_score is not None and self.init_score.size != num_data:
            log.fatal("Initial score size doesn't equal to data")

    def partition(self, used_indices: np.ndarray, num_all_data: int) -> None:
        """Distributed load: slice side data down to this worker's rows
        (metadata.cpp:130-212)."""
        used_indices = np.asarray(used_indices)
        if self.weights is not None:
            if self.weights.size != num_all_data:
                log.fatal("Initial weights size doesn't equal to data")
            self.weights = self.weights[used_indices]
        if self.query_boundaries is not None:
            if self.query_boundaries[-1] != num_all_data:
                log.fatal("Initial query size doesn't equal to data")
            # keep only queries fully owned by this worker; sharding is
            # query-atomic (dataset.cpp:195-215) so membership is per-query
            row_query = np.searchsorted(self.query_boundaries, used_indices,
                                        side="right") - 1
            kept_queries, counts = np.unique(row_query, return_counts=True)
            boundaries = np.zeros(kept_queries.size + 1, dtype=np.int32)
            boundaries[1:] = np.cumsum(counts)
            self.query_boundaries = boundaries
            self._load_query_weights()
        if self.init_score is not None:
            if self.init_score.size != num_all_data:
                log.fatal("Initial score size doesn't equal to data")
            self.init_score = self.init_score[used_indices]
        if self.label is not None:
            self.label = self.label[used_indices]
        self.num_data = used_indices.size

    @property
    def num_queries(self) -> int:
        if self.query_boundaries is None:
            return 0
        return self.query_boundaries.size - 1
