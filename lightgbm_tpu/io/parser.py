"""Text parsers: CSV / TSV / LibSVM with format sniffing.

Re-design of /root/reference/src/io/parser.cpp:9-145 and parser.hpp:15-109.
Behavioral parity:

- format sniffed from the first two lines by comma/tab/colon counts
  (parser.cpp:94-124),
- label-column presence heuristics for predict-time files
  (parser.cpp:24-62),
- values with ``|v| <= 1e-10`` are treated as zero (parser.hpp:32,62),
- ``na``/``nan``/unparseable tokens parse as 0 (utils/common.h:177-178).

The TPU-first difference: instead of emitting per-line ``(col, val)`` pairs,
parsers return whole dense ``float64 [num_rows, num_cols]`` NumPy matrices —
the downstream dense bin matrix is the device format, so there is no reason
to keep a sparse intermediate.  A native C++ fast path (lightgbm_tpu/native)
accelerates tokenization when built.
"""
from __future__ import annotations

import itertools
import math
import os
from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np

from ..utils import log

_WARNED_NO_PANDAS = False

ZERO_THRESHOLD = 1e-10  # parser.hpp:32


# every casing of na/nan — the NA vocabulary of the reference's data files
# (generated, not hand-enumerated: a missing casing would silently dump
# whole files onto the slow per-token tier).  Both tiers map these to 0
# either way; the list only controls which tier handles them.
_NA_SPELLINGS = sorted(
    {"".join(cs) for w in ("na", "nan")
     for cs in itertools.product(*((c.lower(), c.upper()) for c in w))})


def _atof(token: str) -> float:
    """Locale-free float parse; na/nan/inf and garbage parse as 0
    (common.h Atof treats unparseable as 0)."""
    token = token.strip()
    if not token:
        return 0.0
    try:
        value = float(token)
    except ValueError:
        return 0.0
    if math.isnan(value):
        return 0.0
    return value


def _count_stats(line: str) -> Tuple[int, int, int]:
    """comma/tab/colon counts (parser.cpp:9-22)."""
    return line.count(","), line.count("\t"), line.count(":")


@dataclass
class ParsedData:
    """Dense parse result: the whole file as matrices."""
    # [num_rows, num_raw_features] raw feature values (label column removed,
    # later columns shifted left by one as in parser.hpp's ``bias``)
    features: np.ndarray
    # [num_rows] labels (0.0 when the file has no label column)
    labels: np.ndarray


class Parser:
    """Base parser.  ``label_idx < 0`` means the file has no label column."""

    format_name = "unknown"

    def __init__(self, label_idx: int):
        self.label_idx = label_idx

    def parse(self, lines: List[str]) -> ParsedData:
        raise NotImplementedError

    def parse_one_line(self, line: str) -> Tuple[List[Tuple[int, float]], float]:
        """Single-line parse emitting sparse pairs; used by the predictor
        (mirrors Parser::ParseOneLine)."""
        raise NotImplementedError


class _DelimitedParser(Parser):
    delimiter = ","

    def parse_one_line(self, line: str):
        pairs: List[Tuple[int, float]] = []
        label = 0.0
        bias = 0
        for idx, token in enumerate(line.rstrip("\r\n").split(self.delimiter)):
            value = _atof(token)
            if idx == self.label_idx:
                label = value
                bias = -1
            elif abs(value) > ZERO_THRESHOLD:
                pairs.append((idx + bias, value))
        return pairs, label

    def parse(self, lines: List[str]) -> ParsedData:
        num_rows = len(lines)
        if num_rows == 0:
            return ParsedData(np.zeros((0, 0)), np.zeros((0,), dtype=np.float32))
        # Fast path: uniform column count via np.loadtxt-like parsing.
        matrix = _parse_delimited_fast(lines, self.delimiter)
        labels = np.zeros((num_rows,), dtype=np.float32)
        if 0 <= self.label_idx < matrix.shape[1]:
            labels = matrix[:, self.label_idx].astype(np.float32)
            matrix = np.delete(matrix, self.label_idx, axis=1)
        # zero-dropping parity: tiny values are zeros (parser.hpp:32)
        matrix[np.abs(matrix) <= ZERO_THRESHOLD] = 0.0
        return ParsedData(matrix, labels)


def _parse_delimited_fast(lines: List[str], delimiter: str) -> np.ndarray:
    """Tokenize uniform delimited lines to float64; na/nan → 0.

    Three tiers: the native OpenMP parser (built at first use), a
    vectorized pandas C-engine pass, then the exact-semantics per-token
    loop (which also produces the format-error fatal for ragged input)."""
    native = _try_native()
    if native is not None:
        out = native.parse_delimited(lines, delimiter)
        if out is not None:
            return out
    out = _parse_delimited_pandas(lines, delimiter)
    if out is not None:
        return out
    first_cols = len(lines[0].rstrip("\r\n").split(delimiter))
    out = np.empty((len(lines), first_cols), dtype=np.float64)
    for i, line in enumerate(lines):
        tokens = line.rstrip("\r\n").split(delimiter)
        if len(tokens) != first_cols:
            log.fatal("input format error, should be %s" %
                      ("CSV" if delimiter == "," else "TSV"))
        for j, token in enumerate(tokens):
            out[i, j] = _atof(token)
    return out


class CSVParser(_DelimitedParser):
    format_name = "csv"
    delimiter = ","


class TSVParser(_DelimitedParser):
    format_name = "tsv"
    delimiter = "\t"


class LibSVMParser(Parser):
    format_name = "libsvm"

    def __init__(self, label_idx: int):
        if label_idx > 0:
            log.fatal("label should be the first column in Libsvm file")
        super().__init__(label_idx)

    def parse_one_line(self, line: str):
        tokens = line.split()
        pairs: List[Tuple[int, float]] = []
        label = 0.0
        start = 0
        if self.label_idx == 0 and tokens and ":" not in tokens[0]:
            label = _atof(tokens[0])
            start = 1
        for token in tokens[start:]:
            if ":" not in token:
                log.fatal("input format error, should be LibSVM")
            col, value = token.split(":", 1)
            pairs.append((int(col), _atof(value)))
        return pairs, label

    def parse(self, lines: List[str]) -> ParsedData:
        rows = []
        labels = np.zeros((len(lines),), dtype=np.float32)
        max_col = -1
        for i, line in enumerate(lines):
            pairs, label = self.parse_one_line(line)
            labels[i] = label
            rows.append(pairs)
            for col, _ in pairs:
                max_col = max(max_col, col)
        matrix = np.zeros((len(lines), max_col + 1), dtype=np.float64)
        for i, pairs in enumerate(rows):
            for col, value in pairs:
                if abs(value) > ZERO_THRESHOLD:
                    matrix[i, col] = value
        return ParsedData(matrix, labels)


_native_mod = None
_native_checked = False


def _try_native():
    """Lazy import of the native C++ text parsing extension."""
    global _native_mod, _native_checked
    if not _native_checked:
        _native_checked = True
        try:
            from ..native import lib as native_lib
            _native_mod = native_lib if native_lib.available() else None
        except Exception:
            _native_mod = None
    return _native_mod


def create_parser(filename: str, has_header: bool, num_features: int,
                  label_idx: int) -> Parser:
    """Format sniffing + label presence heuristics (parser.cpp:71-143).

    ``num_features > 0`` activates the predict-time heuristic: if a line has
    exactly ``num_features`` columns the file carries no label column
    (parser.cpp:24-62).
    """
    try:
        f = open(filename, "r")
    except OSError:
        log.fatal("Data file: %s doesn't exist" % filename)
    with f:
        if has_header:
            f.readline()
        line1 = f.readline().rstrip("\r\n")
        if not line1:
            log.fatal("Data file: %s at least should have one line" % filename)
        line2 = f.readline().rstrip("\r\n")
        if not line2:
            log.warning("Data file: %s only have one line" % filename)

    comma1, tab1, colon1 = _count_stats(line1)
    comma2, tab2, colon2 = _count_stats(line2)
    data_type = None
    if len(line2) == 0:
        if colon1 > 0:
            data_type = "libsvm"
        elif tab1 > 0:
            data_type = "tsv"
        elif comma1 > 0:
            data_type = "csv"
    else:
        if colon1 > 0 or colon2 > 0:
            data_type = "libsvm"
        elif tab1 == tab2 and tab1 > 0:
            data_type = "tsv"
        elif comma1 == comma2 and comma1 > 0:
            data_type = "csv"
    if data_type is None:
        log.fatal("Unknown format of training data")

    if data_type == "libsvm":
        label_idx = _label_idx_for_libsvm(line1, num_features, label_idx)
        parser: Parser = LibSVMParser(label_idx)
    elif data_type == "tsv":
        label_idx = _label_idx_for_delimited(line1, "\t", num_features, label_idx)
        parser = TSVParser(label_idx)
    else:
        label_idx = _label_idx_for_delimited(line1, ",", num_features, label_idx)
        parser = CSVParser(label_idx)
    if label_idx < 0:
        log.info("Data file: %s doesn't contain label column" % filename)
    return parser


def _label_idx_for_libsvm(line: str, num_features: int, label_idx: int) -> int:
    """parser.cpp:24-36: no label if the first token already has a colon."""
    if num_features <= 0:
        return label_idx
    line = line.strip()
    pos_space = -1
    for i, ch in enumerate(line):
        if ch.isspace():
            pos_space = i
            break
    pos_colon = line.find(":")
    if pos_space < 0 or (pos_colon >= 0 and pos_space < pos_colon):
        return label_idx
    return -1


def _label_idx_for_delimited(line: str, delimiter: str, num_features: int,
                             label_idx: int) -> int:
    """parser.cpp:38-62: token count == num_features ⇒ no label column."""
    if num_features <= 0:
        return label_idx
    if len(line.strip().split(delimiter)) == num_features:
        return -1
    return label_idx


def _parse_delimited_pandas(lines: List[str], delimiter: str):
    """Vectorized fallback via the pandas C engine (na/nan -> 0 like
    _atof); returns None on any irregularity so the caller's per-token
    loop keeps the exact reference error semantics.

    pandas silently NaN-pads SHORT rows, so field counts are validated
    up front (C-level str.count — cheap next to the parse), and quoting
    is disabled so quoted tokens fall back to the _atof path rather than
    being helpfully unquoted."""
    try:
        import csv
        import io as _io
        import pandas as pd
    except ImportError:
        # reached only when the native tier already bowed out: the load is
        # about to drop to the exact per-token loop (orders of magnitude
        # slower on big text files) — say so once
        global _WARNED_NO_PANDAS
        if not _WARNED_NO_PANDAS:
            _WARNED_NO_PANDAS = True
            log.warning(
                "pandas unavailable: text parsing falls back to the exact "
                "per-token tier (slow); pip install 'lightgbm-tpu[fast-parse]'")
        return None
    n_delim = lines[0].count(delimiter)
    if any(ln.count(delimiter) != n_delim for ln in lines):
        return None   # ragged input -> exact loop -> reference fatal
    try:
        # round_trip: the C engine's default xstrtod is ~1 ulp off
        # Python float() on ~1% of tokens, which would make bin boundaries
        # (and therefore trees) depend on which parser tier is active
        # keep_default_na=False: pandas' default NA vocabulary (NULL, N/A,
        # null, #N/A, ...) is wider than _atof's (na/nan spellings only).
        # Both tiers ultimately produce 0.0 for such tokens (_atof maps
        # all garbage to 0 like the reference's Atof, common.h:177-178),
        # but restricting the fast path's vocabulary keeps the TIERS'
        # routing aligned: tokens _atof considers garbage now fail the C
        # engine's float conversion and take the exact per-token tier,
        # instead of silently short-circuiting through pandas' broader NA
        # rules
        df = pd.read_csv(_io.StringIO("\n".join(lines)), header=None,
                         sep=delimiter, engine="c", dtype=np.float64,
                         quoting=csv.QUOTE_NONE,
                         float_precision="round_trip",
                         keep_default_na=False,
                         na_values=_NA_SPELLINGS)
    except Exception:
        return None
    out = df.to_numpy()
    if out.shape != (len(lines), n_delim + 1):
        return None
    out[np.isnan(out)] = 0.0
    return out


def prefetch_chunks(iterable, depth: int = 2):
    """Overlap file reading with downstream parsing/quantization — the
    reference's PipelineReader (utils/pipeline_reader.h:17-71: a reader
    thread fills 16MB blocks while the parser drains them) as a bounded
    background-thread prefetcher over any chunk iterator."""
    import queue
    import threading

    from .. import lifecycle

    q: "queue.Queue" = queue.Queue(maxsize=depth)
    sentinel = object()
    err: List[BaseException] = []
    stop = threading.Event()

    def put_blocking(item) -> bool:
        """Stop-aware blocking put; False when the consumer went away."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            for item in iterable:
                if not put_blocking(item):
                    return
        except BaseException as e:  # surfaced on the consumer side
            err.append(e)
        finally:
            # the sentinel must use the same stop-aware loop: dropping it
            # on a momentarily-full queue would strand the consumer in
            # q.get() forever (and swallow any stored producer exception)
            put_blocking(sentinel)
            # self-deregistration: if _close's bounded join timed out (a
            # slow chunk parse outliving the 1s grace), the entry must
            # still clear when the thread actually exits — only a thread
            # that never reaches here stays registered for the guard
            lifecycle.untrack(thread)

    thread = threading.Thread(target=worker, name="lgbm-tpu-prefetch",
                              daemon=True)

    def _close() -> None:
        """Stop-and-join closer: shared with the generator's own finally
        and the lifecycle leak guard (a leaked prefetch thread holds the
        underlying file handle open past the test that spawned it)."""
        stop.set()
        thread.join(1.0)
        if not thread.is_alive():
            lifecycle.untrack(thread)

    lifecycle.track("prefetch", thread, _close)
    thread.start()
    try:
        while True:
            item = q.get()
            if item is sentinel:
                if err:
                    raise err[0]
                return
            yield item
    finally:
        # consumer stopped early OR drained fully: unblock the worker so
        # it exits (releasing the file handle) and deregister it from
        # the live inventory once it is provably gone
        _close()


def read_lines(filename: str, skip_header: bool = False) -> List[str]:
    """Read all data lines (TextReader::ReadAllLines equivalent,
    utils/text_reader.h:20-308 — pipelined IO replaced by buffered reads).

    Implemented ON TOP of ``read_line_chunks`` so the resident and
    streaming loaders provably parse the SAME row set: the two readers
    used to split and skip headers independently (``str.splitlines``
    additionally breaks rows on \\f/\\v/\\u2028-class boundaries that
    file iteration does not, and it dropped the first SPLIT line as the
    header where the chunk reader consumes the first PHYSICAL line), so
    a file could stream to a different dataset than it loaded resident.
    One implementation, one semantics (tests/test_streaming.py pins
    blank-line/header/exotic-separator cases)."""
    out: List[str] = []
    for chunk in read_line_chunks(filename, skip_header=skip_header):
        out.extend(chunk)
    return out


def count_data_rows(filename: str, skip_header: bool = False) -> int:
    """Count the data rows ``read_line_chunks`` would yield, without
    parsing (streaming pass 0: the pinned-index binning sample needs the
    total row count before any chunk is parsed).  Delegates to the chunk
    reader itself — host memory stays bounded by one chunk of line
    strings, and any future change to its header/blank-line filter keeps
    pass 0 and pass 1/2 counting the same rows."""
    return sum(len(chunk) for chunk in
               read_line_chunks(filename, skip_header=skip_header))


def read_line_chunks(filename: str, skip_header: bool = False,
                     chunk_lines: int = 200_000):
    """Stream data lines in bounded chunks (TextReader's 16MB-block
    pipelined reads, utils/text_reader.h:248-281) — the two-round loading
    path's memory bound."""
    with open(filename, "r") as f:
        if skip_header:
            f.readline()
        buf: List[str] = []
        for line in f:
            line = line.rstrip("\n")
            if line:
                buf.append(line)
                if len(buf) >= chunk_lines:
                    yield buf
                    buf = []
        if buf:
            yield buf


# --------------------------------------------------------- byte ranges
#
# Process-parallel ingest (io/parallel_ingest.py) hands each worker a
# BYTE range of the file instead of a line range, so no two workers ever
# read the same bytes.  Correctness rests on three facts about
# ``read_line_chunks``'s semantics:
#
# - text mode is universal-newline: ``\r\n`` and lone ``\r`` translate
#   to ``\n`` before iteration, so the row boundaries are exactly the
#   bytes {0x0A, 0x0D} — and UTF-8 never embeds either inside a
#   multibyte sequence, so byte-level snapping is encoding-safe;
# - a data row is a maximal run of non-terminator bytes: blank physical
#   lines (any mix of \r/\n) are dropped by the truthiness filter, and a
#   missing final newline still yields the last line;
# - \f/\v/ -class separators are NOT terminators (file iteration
#   does not split on them; tests pin this), and they are non-terminator
#   BYTES here, so they stay inside their run.
#
# Snapping a split point to the next run START therefore never lands
# inside row content, and every terminator byte of a row sits before the
# next run start — ranges partition the data bytes with zero overlap.

_SCAN_BLOCK = 8 * 1024 * 1024


def data_byte_start(filename: str, skip_header: bool = False) -> int:
    """Byte offset of the first data byte — the byte-domain twin of the
    ``f.readline()`` header consume in ``read_line_chunks`` (the header
    is the first PHYSICAL line: up to and including the first ``\\n``,
    ``\\r`` or ``\\r\\n``; a file with no terminator is all header)."""
    if not skip_header:
        return 0
    with open(filename, "rb") as f:
        pos = 0
        pending_cr = False
        while True:
            block = f.read(_SCAN_BLOCK)
            if not block:
                return pos  # no terminator at all -> whole file is header
            if pending_cr:
                # header ended on a \r at the previous block's edge; a
                # \n here belongs to the same \r\n terminator
                return pos + (1 if block[0:1] == b"\n" else 0)
            arr = np.frombuffer(block, dtype=np.uint8)
            hits = np.nonzero((arr == 10) | (arr == 13))[0]
            if hits.size == 0:
                pos += len(block)
                continue
            i = int(hits[0])
            if block[i:i + 1] == b"\n":
                return pos + i + 1
            if i + 1 < len(block):
                return pos + i + 1 + (1 if block[i + 1:i + 2] == b"\n"
                                      else 0)
            pos += len(block)
            pending_cr = True


def split_byte_ranges_at(filename: str, candidates,
                         skip_header: bool = False):
    """Snap candidate byte offsets to data-row starts with ONE raw scan.

    Returns ``(ranges, counts, total_rows)``: byte ranges
    ``[(start, end), ...]`` covering the data region exactly once, the
    data-row count of each range, and their sum — the same count
    ``count_data_rows`` produces, so the split scan doubles as pass 0
    (the file is read twice per load, not three times).  Each candidate
    snaps FORWARD to the next row start (or EOF), so any candidate set —
    mid-line, between the bytes of a ``\\r\\n``, inside the skipped
    header, past EOF — yields ranges whose concatenated rows reproduce
    the serial ``read_line_chunks`` sequence exactly."""
    size = os.path.getsize(filename)
    d0 = data_byte_start(filename, skip_header)
    pending = sorted(min(max(int(c), d0), size) for c in candidates)
    snapped: List[Tuple[int, int]] = []  # (byte offset, rows before it)
    total = 0
    in_run = False
    pos = d0
    with open(filename, "rb") as f:
        f.seek(d0)
        while True:
            block = f.read(_SCAN_BLOCK)
            if not block:
                break
            arr = np.frombuffer(block, dtype=np.uint8)
            m = (arr != 10) & (arr != 13)
            prev = np.empty_like(m)
            prev[0] = in_run
            prev[1:] = m[:-1]
            starts = np.nonzero(m & ~prev)[0]
            while pending and pending[0] < pos + len(block):
                j = int(np.searchsorted(starts, pending[0] - pos))
                if j >= starts.size:
                    break  # snaps in a later block (or to EOF)
                snapped.append((pos + int(starts[j]), total + j))
                pending.pop(0)
            total += int(starts.size)
            in_run = bool(m[-1])
            pos += len(block)
    for _ in pending:
        snapped.append((size, total))
    bounds = [d0] + [b for b, _ in snapped] + [size]
    cum = [0] + [c for _, c in snapped] + [total]
    ranges = list(zip(bounds[:-1], bounds[1:]))
    counts = [cum[i + 1] - cum[i] for i in range(len(ranges))]
    return ranges, counts, total


def split_byte_ranges(filename: str, num_ranges: int,
                      skip_header: bool = False):
    """Split the data region into ``num_ranges`` byte-balanced,
    row-start-snapped ranges (see ``split_byte_ranges_at``)."""
    size = os.path.getsize(filename)
    d0 = data_byte_start(filename, skip_header)
    num_ranges = max(int(num_ranges), 1)
    span = max(size - d0, 0)
    cands = [d0 + (span * i) // num_ranges for i in range(1, num_ranges)]
    return split_byte_ranges_at(filename, cands, skip_header=skip_header)


def read_range_lines(filename: str, start: int, end: int) -> List[str]:
    """The data lines of one snapped byte range — bit-identical to the
    slice of ``read_lines`` the range covers.  The replace chain IS
    universal-newline translation; dropping empty segments IS the
    truthiness filter (a \\r\\n "blank" line becomes one empty segment
    on whichever side of a split it falls — dropped either way)."""
    if end <= start:
        return []
    with open(filename, "rb") as f:
        f.seek(start)
        data = f.read(end - start)
    text = data.decode()
    if "\r" in text:
        text = text.replace("\r\n", "\n").replace("\r", "\n")
    return [ln for ln in text.split("\n") if ln]
