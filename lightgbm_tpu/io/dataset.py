"""Dataset: host-side loading, binning, and the device bin matrix.

Re-design of /root/reference/src/io/dataset.cpp:18-909 for TPU.  The load
pipeline is preserved (column-role resolution by index or ``name:`` prefix,
reservoir sampling ≤50k rows for binning, BinMapper construction, trivial
feature removal, row sharding for distributed training, binary cache), but
the storage layout inverts the reference's per-feature Bin objects: the whole
dataset becomes ONE dense ``[num_features, num_rows]`` integer matrix of bin
indices (uint8 when max_bin ≤ 256), which is exactly the array a TPU histogram
kernel wants in HBM.  Sparse/ordered-bin machinery (sparse_bin.hpp,
ordered_sparse_bin.hpp) is a CPU cache optimization and is deliberately not
reproduced.
"""
from __future__ import annotations

import os
import pickle
from struct import error as struct_error
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..utils import log
from . import parser as parser_mod
from .binning import BinMapper
from .metadata import Metadata

SAMPLE_CNT = 50000  # dataset.cpp:219 — max rows sampled for bin finding
BINARY_MAGIC = b"LGBM_TPU_BIN_V1"


def _bin_dtype(max_num_bin: int):
    """uint8/16/32 selection mirrors Bin::CreateDenseBin (bin.cpp:202-210)."""
    if max_num_bin <= 256:
        return np.uint8
    if max_num_bin <= 65536:
        return np.uint16
    return np.uint32


class Dataset:
    """Binned dataset.

    Attributes
    ----------
    bins : np.ndarray [num_features, num_data]
        Bin index per (used feature, row).
    bin_mappers : list[BinMapper]
        Per used feature.
    num_bins : np.ndarray [num_features]
        Bins per used feature.
    real_feature_idx : np.ndarray [num_features]
        Used-feature → original column index (after label removal), i.e. the
        reference's ``split_feature_real`` space (dataset.cpp used_feature_map).
    """

    def __init__(self):
        self.data_filename: str = ""
        self.bins: Optional[np.ndarray] = None
        # streaming ingestion (io/streaming.py): single-process streamed
        # loads land the bin matrix directly in device memory (a
        # jax.Array with explicit NamedSharding placement); ``bins``
        # stays None then — the host never holds the full matrix
        self.device_bins = None
        self.bin_mappers: List[BinMapper] = []
        self.num_bins: np.ndarray = np.zeros(0, dtype=np.int32)
        self.real_feature_idx: np.ndarray = np.zeros(0, dtype=np.int32)
        self.used_feature_map: Dict[int, int] = {}
        self.num_total_features: int = 0
        self.feature_names: List[str] = []
        self.metadata: Metadata = Metadata()
        self.label_idx: int = 0
        self.num_data: int = 0
        self.global_num_data: int = 0
        self.used_data_indices: Optional[np.ndarray] = None
        self.max_bin: int = 256

    # ------------------------------------------------------------------ load

    @classmethod
    def load_train(cls, io_config, rank: int = 0, num_machines: int = 1,
                   predict_fun: Optional[Callable] = None,
                   bin_finder: Optional[Callable] = None,
                   shard_rows: bool = False,
                   shard_devices: Optional[int] = None,
                   device_type: str = "") -> "Dataset":
        """LoadTrainData (dataset.cpp:420-465).

        ``bin_finder(sample_matrix, max_bin) -> List[BinMapper]`` lets the
        distributed path plug in feature-sliced bin finding + allgather
        (dataset.cpp:353-415); default is local bin finding.

        ``shard_rows``: a single-process data-parallel learner will
        consume the dataset — a streamed load then places the device
        matrix row-sharded over the ``(data,)`` mesh axis
        (parallel.mesh.dataset_row_sharding) instead of replicated.
        ``shard_devices`` (with ``device_type``): set for ANY
        single-process parallel consumer to the learner's mesh size —
        the streamed matrix is then committed on the learner's exact
        device mesh (row-sharded under ``shard_rows`` when rows divide
        it, replicated on that mesh otherwise), never on the serial
        one-device placement a multi-device shard_map would reject.
        """
        from . import streaming
        self = cls()
        self.data_filename = io_config.data_filename
        self.max_bin = io_config.max_bin

        # direct columnar-binary input (ISSUE 18b): ``data=`` itself IS a
        # native cache — header-sniffed via BINARY_MAGIC, so repeat jobs
        # skip text entirely (no text sibling required).  A text file
        # classifies "foreign" here and falls through to the normal
        # loaders untouched.
        if os.path.exists(io_config.data_filename):
            kind = self._classify_binary_cache(io_config.data_filename)
            if kind == "ours":
                direct = io_config.data_filename
                if (num_machines <= 1 and streaming.single_process()
                        and streaming.resolve_streaming(io_config,
                                                        direct)):
                    log.info("Loading data set from binary file "
                             "(streamed, direct)")
                    streaming.load_binary_streaming(
                        self, direct, io_config, shard_rows=shard_rows,
                        shard_devices=shard_devices,
                        device_type=device_type)
                else:
                    log.info("Loading data set from binary file (direct)")
                    self._load_binary(direct, rank, num_machines,
                                      io_config.is_pre_partition,
                                      io_config.data_random_seed)
                self._attach_init_score(io_config.input_init_score,
                                        predict_fun)
                return self
            if kind == "corrupt":
                log.fatal("Binary file %s is a corrupt/truncated "
                          "lightgbm_tpu cache — delete it to regenerate"
                          % io_config.data_filename)

        bin_path = io_config.data_filename + ".bin"
        foreign_bin = False
        if os.path.exists(bin_path):
            kind = self._classify_binary_cache(bin_path)
            if kind == "ours":
                if (num_machines <= 1 and streaming.single_process()
                        and streaming.resolve_streaming(io_config,
                                                        bin_path)):
                    log.info("Loading data set from binary file "
                             "(streamed)")
                    streaming.load_binary_streaming(
                        self, bin_path, io_config, shard_rows=shard_rows,
                        shard_devices=shard_devices,
                        device_type=device_type)
                else:
                    log.info("Loading data set from binary file")
                    self._load_binary(bin_path, rank, num_machines,
                                      io_config.is_pre_partition,
                                      io_config.data_random_seed)
                self._attach_init_score(io_config.input_init_score,
                                        predict_fun)
                return self
            if kind == "corrupt":
                log.fatal("Binary file %s is a corrupt/truncated "
                          "lightgbm_tpu cache — delete it to regenerate"
                          % bin_path)
            # a reference-LightGBM cache (dataset.cpp:653-898 layout, no
            # magic) sitting next to the data file: load it natively —
            # same bins, mappers and metadata the reference would see —
            # and never clobber the user's still-valid reference cache
            foreign_bin = True
            try:
                log.info("Loading data set from reference-format binary "
                         "file")
                self._load_reference_binary(bin_path, rank, num_machines,
                                            io_config.is_pre_partition,
                                            io_config.data_random_seed)
            except (ValueError, struct_error) as e:
                self.__dict__.update(cls().__dict__)
                self.data_filename = io_config.data_filename
                self.max_bin = io_config.max_bin
                if not os.path.exists(io_config.data_filename):
                    log.fatal("Binary file %s is neither a lightgbm_tpu "
                              "cache nor a readable reference-LightGBM "
                              "cache (%s), and the text data file %s does "
                              "not exist"
                              % (bin_path, e, io_config.data_filename))
                log.warning("Binary file %s could not be parsed as a "
                            "reference-LightGBM cache (%s) — re-binning "
                            "from the text file (the file is left "
                            "untouched)" % (bin_path, e))
            else:
                # the reference cache stores label DATA, not the label
                # column index — recover a configured label_column (the
                # name: form needs the text header, when still present)
                self.label_idx = _label_idx_without_text_load(io_config)
                self._attach_init_score(io_config.input_init_score,
                                        predict_fun)
                return self
            if io_config.is_save_binary_file:
                log.warning("is_save_binary_file requested but %s is a "
                            "foreign file — NOT overwriting it; delete "
                            "or move it to let lightgbm_tpu write its own"
                            % bin_path)

        label_idx, weight_idx, group_idx, ignore_set, header_names = \
            _resolve_columns(io_config)
        self.label_idx = label_idx

        self.metadata.init_from_files(io_config.data_filename,
                                      io_config.input_init_score)

        parser = parser_mod.create_parser(io_config.data_filename,
                                          io_config.has_header, 0, label_idx)
        if streaming.resolve_streaming(io_config, io_config.data_filename):
            # streaming ingestion (ISSUE 8, io/streaming.py): chunked
            # parse→sample→bin with double-buffered device feeds —
            # bit-identical to the resident load below, and strictly
            # more memory-bound than two-round loading (which it
            # supersedes when both are requested)
            if io_config.use_two_round_loading:
                log.info("streaming supersedes use_two_round_loading")
            streaming.load_train_streaming(
                self, io_config, parser, rank, num_machines, predict_fun,
                bin_finder, weight_idx, group_idx, ignore_set,
                header_names, shard_rows=shard_rows,
                shard_devices=shard_devices, device_type=device_type,
                foreign_bin=foreign_bin)
            self.metadata.finalize(self.num_data)
            return self
        if io_config.use_two_round_loading:
            # streaming two-pass load (dataset.cpp two-round path): never
            # materializes the [N, F] float64 matrix — pass 1 samples rows
            # for binning and collects labels/side columns, pass 2
            # quantizes chunks straight into the bin matrix
            self._load_train_two_round(
                io_config, parser, rank, num_machines, predict_fun,
                bin_finder, weight_idx, group_idx, ignore_set, header_names)
            self.metadata.finalize(self.num_data)
            if io_config.is_save_binary_file and not foreign_bin:
                self._save_binary_as(io_config, bin_path)
            return self
        lines = parser_mod.read_lines(io_config.data_filename,
                                      skip_header=io_config.has_header)
        parsed = parser.parse(lines)
        del lines
        all_features = parsed.features
        all_labels = parsed.labels
        total_rows = all_features.shape[0]
        self.global_num_data = total_rows

        # distributed row sharding at load time (dataset.cpp:172-216):
        # random per-record assignment, query-atomic when queries exist
        self.used_data_indices = self._draw_shard_mask(io_config, rank,
                                                       num_machines,
                                                       total_rows)

        # sample ≤50k global rows for bin finding (dataset.cpp:218-273)
        rng = np.random.RandomState(io_config.data_random_seed)
        if total_rows > SAMPLE_CNT:
            sample_idx = np.sort(rng.choice(total_rows, SAMPLE_CNT, replace=False))
            sample = all_features[sample_idx]
        else:
            sample = all_features

        self.num_total_features = all_features.shape[1]
        self.feature_names = _make_feature_names(header_names, label_idx,
                                                 self.num_total_features)

        # bin mappers + trivial/ignored feature removal (dataset.cpp:334-350)
        self._build_bin_mappers(sample, io_config.max_bin, bin_finder,
                                ignore_set)

        # capture weight/group columns from the data file (overrides side
        # files, ExtractFeaturesFromMemory dataset.cpp:536-545)
        if weight_idx >= 0:
            log.info("using weight in data file, and ignore additional weight file")
            self.metadata.weights = all_features[:, weight_idx].astype(np.float32)
        if group_idx >= 0:
            log.info("using query id in data file, and ignore additional query file")
            self.metadata.query_boundaries = None
            self.metadata.set_queries_from_column(all_features[:, group_idx])

        # shard rows
        if self.used_data_indices is not None:
            features = all_features[self.used_data_indices]
            self.metadata.set_label(all_labels)
            if self.metadata.queries is not None:
                self.metadata.queries = self.metadata.queries[self.used_data_indices]
            self.metadata.partition(self.used_data_indices, total_rows)
        else:
            features = all_features
            self.metadata.set_label(all_labels)
        self.num_data = features.shape[0]

        # the dense bin matrix — THE device array
        self._binarize(features)
        self.metadata.finalize(self.num_data)

        self._attach_init_score_values(features, predict_fun)
        if io_config.is_save_binary_file and not foreign_bin:
            self._save_binary_as(io_config, bin_path)
        return self

    def _save_binary_as(self, io_config, bin_path: str) -> None:
        """save_binary_format dispatch: "native" (default; pickle header +
        raw bin matrix) or "reference" (the reference's own .bin layout —
        its binary trains directly from our cache)."""
        if io_config.save_binary_format == "reference":
            self.save_binary_reference(bin_path)
        else:
            self.save_binary(bin_path)

    def _draw_shard_mask(self, io_config, rank, num_machines, total_rows):
        """Distributed row sharding at load time (dataset.cpp:172-216):
        random per-record assignment, query-atomic when query boundaries
        exist (at this point: from side files — in-file group columns
        override boundaries only AFTER sharding, matching the one-round
        order of operations).  Returns used row indices or None."""
        if num_machines <= 1 or io_config.is_pre_partition:
            return None
        # record whether the draw could honor query atomicity: an in-file
        # group column is only extracted AFTER sharding, so its queries
        # are cut per-record — distributed lambdarank must reject that
        # (gbdt.init guard) rather than silently mis-train
        self.shard_query_atomic = self.metadata.query_boundaries is not None
        rng = np.random.RandomState(io_config.data_random_seed)
        if self.metadata.query_boundaries is not None:
            nq = self.metadata.num_queries
            q_owner = rng.randint(0, num_machines, size=nq)
            row_query = np.searchsorted(self.metadata.query_boundaries,
                                        np.arange(total_rows),
                                        side="right") - 1
            mask = q_owner[row_query] == rank
        else:
            mask = rng.randint(0, num_machines, size=total_rows) == rank
        return np.nonzero(mask)[0].astype(np.int64)

    def _build_bin_mappers(self, sample, max_bin, bin_finder,
                           ignore_set) -> None:
        """Bin mappers for every raw feature column plus trivial/ignored
        feature removal (dataset.cpp:275-350)."""
        if bin_finder is not None:
            raw_mappers = bin_finder(sample, max_bin)
        else:
            raw_mappers = []
            for j in range(self.num_total_features):
                if j in ignore_set:
                    raw_mappers.append(None)
                    continue
                m = BinMapper()
                m.find_bin(sample[:, j], max_bin)
                raw_mappers.append(m)
        for j, mapper in enumerate(raw_mappers):
            if mapper is None or j in ignore_set:
                if j not in ignore_set:
                    log.warning("Ignore Feature %s" % self.feature_names[j])
                continue
            if mapper.is_trivial:
                log.warning("Feature %s only contains one value, will be "
                            "ignored" % self.feature_names[j])
                continue
            self.used_feature_map[j] = len(self.bin_mappers)
            self.bin_mappers.append(mapper)
        self.real_feature_idx = np.array(sorted(self.used_feature_map),
                                         dtype=np.int32)
        self.num_bins = np.array([m.num_bin for m in self.bin_mappers],
                                 dtype=np.int32)

    def _load_train_two_round(self, io_config, parser, rank, num_machines,
                              predict_fun, bin_finder, weight_idx, group_idx,
                              ignore_set, header_names) -> None:
        """Streaming two-pass training load (``use_two_round_loading``,
        dataset.cpp:430-452 / text_reader SampleFromFile): peak host memory
        is one parse chunk plus the ≤50k-row bin-finding sample plus the
        int8/int16 bin matrix — never the full float64 feature matrix."""
        chunk_rows = 200_000
        rng_sample = np.random.RandomState(io_config.data_random_seed)

        # ---- pass 1: count rows, reservoir-sample for binning, collect
        # labels and in-file weight/query columns.  The reservoir is a
        # preallocated matrix COPIED into — retaining views of chunk rows
        # would pin every chunk's full float64 array and defeat the memory
        # bound this path exists for
        reservoir = None          # [SAMPLE_CNT, F] float64
        labels_parts, weight_parts, group_parts = [], [], []
        total_rows = 0
        num_cols = None
        for lines in parser_mod.prefetch_chunks(parser_mod.read_line_chunks(
                io_config.data_filename, skip_header=io_config.has_header,
                chunk_lines=chunk_rows)):
            parsed = parser.parse(lines)
            feats = parsed.features
            num_cols = feats.shape[1]
            if reservoir is None:
                reservoir = np.empty((SAMPLE_CNT, num_cols), np.float64)
            labels_parts.append(parsed.labels)
            if weight_idx >= 0:
                weight_parts.append(feats[:, weight_idx].astype(np.float32))
            if group_idx >= 0:
                group_parts.append(feats[:, group_idx].copy())
            # algorithm-R reservoir, vectorized per chunk (utils/random.h
            # Sample semantics: every row equally likely)
            c = feats.shape[0]
            global_idx = total_rows + np.arange(c)
            if total_rows < SAMPLE_CNT:
                take = min(SAMPLE_CNT - total_rows, c)
                reservoir[total_rows:total_rows + take] = feats[:take]
                start = take
            else:
                start = 0
            if start < c:
                accept = (rng_sample.rand(c - start)
                          < SAMPLE_CNT / (global_idx[start:] + 1.0))
                for i in np.nonzero(accept)[0]:
                    reservoir[rng_sample.randint(SAMPLE_CNT)] = \
                        feats[start + i]
            total_rows += c
        self.global_num_data = total_rows
        sample = (reservoir[:min(total_rows, SAMPLE_CNT)]
                  if reservoir is not None
                  else np.zeros((0, 0), np.float64))

        all_labels = np.concatenate(labels_parts) if labels_parts else \
            np.zeros((0,), np.float32)
        self.num_total_features = num_cols or 0
        self.feature_names = _make_feature_names(header_names,
                                                 self.label_idx,
                                                 self.num_total_features)

        # distributed row sharding mask BEFORE the in-file group column
        # overrides query boundaries — the one-round path's order (side-file
        # boundaries drive query-atomic sharding; the group column is
        # extracted later, dataset.cpp:536-545)
        self.used_data_indices = self._draw_shard_mask(io_config, rank,
                                                       num_machines,
                                                       total_rows)
        mask = None
        if self.used_data_indices is not None:
            mask = np.zeros(total_rows, dtype=bool)
            mask[self.used_data_indices] = True
        if group_idx >= 0:
            log.info("using query id in data file, and ignore additional "
                     "query file")
            self.metadata.query_boundaries = None
            self.metadata.set_queries_from_column(
                np.concatenate(group_parts))

        # bin mappers from the sample (local or distributed)
        self._build_bin_mappers(sample, io_config.max_bin, bin_finder,
                                ignore_set)
        del sample

        if weight_idx >= 0:
            log.info("using weight in data file, and ignore additional "
                     "weight file")
            self.metadata.weights = np.concatenate(weight_parts)

        self.metadata.set_label(all_labels)
        if self.used_data_indices is not None:
            if self.metadata.queries is not None:
                self.metadata.queries = \
                    self.metadata.queries[self.used_data_indices]
            self.metadata.partition(self.used_data_indices, total_rows)
            self.num_data = len(self.used_data_indices)
        else:
            self.num_data = total_rows

        # ---- pass 2: quantize chunks straight into the bin matrix
        dtype = _bin_dtype(int(self.num_bins.max())
                           if len(self.bin_mappers) else 256)
        bins = np.empty((len(self.bin_mappers), self.num_data), dtype=dtype)
        init_scores = [] if predict_fun is not None else None
        cursor = 0
        start = 0
        for lines in parser_mod.prefetch_chunks(parser_mod.read_line_chunks(
                io_config.data_filename, skip_header=io_config.has_header,
                chunk_lines=chunk_rows)):
            feats = parser.parse(lines).features
            c = feats.shape[0]
            if mask is not None:
                feats = feats[mask[start:start + c]]
            n = feats.shape[0]
            for j_raw, j_inner in self.used_feature_map.items():
                bins[j_inner, cursor:cursor + n] = \
                    self.bin_mappers[j_inner].value_to_bin(
                        feats[:, j_raw]).astype(dtype)
            if init_scores is not None:
                init_scores.append(np.asarray(predict_fun(feats),
                                              np.float32).reshape(-1))
            cursor += n
            start += c
        # the file could change between the two streaming passes; a size
        # mismatch must be a hard error, not uninitialized bin memory
        log.check(start == total_rows and cursor == self.num_data,
                  "Input file changed between the two loading passes "
                  f"(pass 1: {total_rows} rows, pass 2: {start})")
        self.bins = bins
        if init_scores is not None:
            self.metadata.init_score = np.concatenate(init_scores)

    @classmethod
    def load_valid(cls, train: "Dataset", filename: str,
                   predict_fun: Optional[Callable] = None,
                   io_config=None) -> "Dataset":
        """LoadValidationData (dataset.cpp:467-511): bin with the TRAIN
        dataset's mappers; honors has_header and in-file weight/group
        columns like the train load (dataset.cpp:474)."""
        self = cls()
        self.data_filename = filename
        self.max_bin = train.max_bin
        self.label_idx = train.label_idx
        self.bin_mappers = train.bin_mappers
        self.num_bins = train.num_bins
        self.real_feature_idx = train.real_feature_idx
        self.used_feature_map = train.used_feature_map
        self.num_total_features = train.num_total_features
        self.feature_names = train.feature_names

        has_header = bool(io_config.has_header) if io_config else False
        weight_idx = group_idx = -1
        if io_config is not None and (io_config.weight_column
                                      or io_config.group_column):
            import dataclasses as _dc
            cfg = _dc.replace(io_config, data_filename=filename)
            _, weight_idx, group_idx, _, _ = _resolve_columns(cfg)

        self.metadata.init_from_files(filename, "")
        parser = parser_mod.create_parser(filename, has_header, 0,
                                          train.label_idx)
        lines = parser_mod.read_lines(filename, skip_header=has_header)
        parsed = parser.parse(lines)
        features = parsed.features
        if weight_idx >= 0 and weight_idx < features.shape[1]:
            self.metadata.weights = features[:, weight_idx].astype(np.float32)
        if group_idx >= 0 and group_idx < features.shape[1]:
            self.metadata.query_boundaries = None
            self.metadata.set_queries_from_column(features[:, group_idx])
        if features.shape[1] < self.num_total_features:
            pad = np.zeros((features.shape[0],
                            self.num_total_features - features.shape[1]))
            features = np.concatenate([features, pad], axis=1)
        self.num_data = features.shape[0]
        self.global_num_data = self.num_data
        self.metadata.set_label(parsed.labels)
        self._binarize(features)
        self.metadata.finalize(self.num_data)
        self._attach_init_score_values(features, predict_fun)
        return self

    @classmethod
    def from_arrays(cls, features: np.ndarray, labels: np.ndarray,
                    max_bin: int = 256,
                    weights: Optional[np.ndarray] = None,
                    query_boundaries: Optional[np.ndarray] = None,
                    sample_cnt: int = SAMPLE_CNT,
                    seed: int = 1,
                    reference: Optional["Dataset"] = None) -> "Dataset":
        """Library entry: build a Dataset from in-memory arrays (no reference
        analog — the reference is file-only; this is the Python-API path).

        ``reference``: an existing (training) Dataset whose bin mappers are
        reused — required for validation sets, which must be quantized with
        the TRAINING distribution's bins (Dataset::LoadValidationData,
        dataset.cpp:467-511)."""
        self = cls()
        features = np.asarray(features, dtype=np.float64)
        self.max_bin = max_bin
        self.num_total_features = features.shape[1]
        self.feature_names = [f"Column_{i}" for i in range(features.shape[1])]
        total_rows = features.shape[0]
        if reference is not None:
            if features.shape[1] != reference.num_total_features:
                log.fatal("valid data has different number of features")
            self.max_bin = reference.max_bin
            self.used_feature_map = dict(reference.used_feature_map)
            self.bin_mappers = reference.bin_mappers
        else:
            rng = np.random.RandomState(seed)
            if total_rows > sample_cnt:
                sample = features[np.sort(rng.choice(total_rows, sample_cnt,
                                                     replace=False))]
            else:
                sample = features
            for j in range(features.shape[1]):
                m = BinMapper()
                m.find_bin(sample[:, j], max_bin)
                if m.is_trivial:
                    continue
                self.used_feature_map[j] = len(self.bin_mappers)
                self.bin_mappers.append(m)
        self.real_feature_idx = np.array(sorted(self.used_feature_map),
                                         dtype=np.int32)
        self.num_bins = np.array([m.num_bin for m in self.bin_mappers],
                                 dtype=np.int32)
        self.num_data = total_rows
        self.global_num_data = total_rows
        self.metadata.set_label(np.asarray(labels, dtype=np.float32))
        if weights is not None:
            self.metadata.weights = np.asarray(weights, dtype=np.float32)
        if query_boundaries is not None:
            self.metadata.query_boundaries = np.asarray(query_boundaries,
                                                        dtype=np.int32)
            self.metadata._load_query_weights()
        self._binarize(features)
        self.metadata.finalize(self.num_data)
        return self

    # ------------------------------------------------------------- internals

    def _binarize(self, features: np.ndarray) -> None:
        """Quantize the dense value matrix into the [F, N] bin matrix."""
        num_features = len(self.bin_mappers)
        dtype = _bin_dtype(int(self.num_bins.max()) if num_features else 256)
        bins = np.empty((num_features, features.shape[0]), dtype=dtype)
        for j_raw, j_inner in self.used_feature_map.items():
            mapper = self.bin_mappers[j_inner]
            bins[j_inner] = mapper.value_to_bin(features[:, j_raw]).astype(dtype)
        self.bins = bins

    def _attach_init_score_values(self, features: np.ndarray,
                                  predict_fun) -> None:
        """Continued training: score every row with the old model
        (dataset.cpp:546-581)."""
        if predict_fun is not None:
            self.metadata.init_score = np.asarray(
                predict_fun(features), dtype=np.float32).reshape(-1)

    def _attach_init_score(self, path: str, predict_fun) -> None:
        if path:
            self.metadata._load_init_score(path)

    @property
    def num_features(self) -> int:
        return len(self.bin_mappers)

    def plan_packing(self, mode: str = "auto", block: int = 0,
                     shards: int = 0):
        """Mixed-bin layout plan for THIS dataset's per-feature bin counts
        (io/binning.plan_feature_packing): the bin-width-class partition a
        booster uses to reorder the bin matrix at attach time.  None when
        packing cannot help (single class) or is disabled.  The Dataset
        itself stays canonical — validation sets, tree replay and the
        binary cache all speak canonical feature order; only a training
        booster's device copy of ``bins`` is reordered.

        ``block`` > 0: the BLOCK-LOCAL plan for a contiguous feature-block
        ownership layout (the hybrid/voting 2-D mesh learners,
        io/binning.plan_feature_packing_blocked) — the permutation never
        crosses an ownership block boundary, so packing commutes with
        block ownership."""
        from .binning import (plan_feature_packing,
                              plan_feature_packing_blocked)
        if not len(self.bin_mappers):
            return None
        if block > 0:
            return plan_feature_packing_blocked(
                self.num_bins, int(self.num_bins.max()), block, mode=mode,
                shards=shards)
        return plan_feature_packing(self.num_bins,
                                    int(self.num_bins.max()), mode=mode)

    def bin_upper_bounds_matrix(self) -> np.ndarray:
        """[F, max_bins] float64, padded with +inf; device-side threshold
        real-value lookup."""
        max_b = int(self.num_bins.max()) if self.num_features else 1
        out = np.full((self.num_features, max_b), np.inf, dtype=np.float64)
        for i, m in enumerate(self.bin_mappers):
            out[i, :m.num_bin] = m.bin_upper_bound
        return out

    # ---------------------------------------------------------- binary cache

    def _binary_header(self, bins_dtype, bins_shape) -> dict:
        """The native binary cache's pickled header — shared by the
        resident ``save_binary`` and the streaming loader's pass-2 memmap
        cache writer (io/streaming._CacheWriter), so both produce
        byte-identical files."""
        return {
            "num_data": self.num_data,
            "global_num_data": self.global_num_data,
            "num_total_features": self.num_total_features,
            "label_idx": self.label_idx,
            "feature_names": self.feature_names,
            "used_feature_map": self.used_feature_map,
            "max_bin": self.max_bin,
            "mappers": [m.to_bytes() for m in self.bin_mappers],
            "bins_dtype": str(np.dtype(bins_dtype)),
            "bins_shape": tuple(bins_shape),
            "label": self.metadata.label,
            "weights": self.metadata.weights,
            "query_boundaries": self.metadata.query_boundaries,
        }

    def save_binary(self, path: str) -> None:
        """Binary dataset cache (dataset.cpp:653-713).  Own format: magic +
        pickled header + raw bin matrix."""
        log.check(self.bins is not None,
                  "save_binary needs a host-resident bin matrix (a "
                  "streamed dataset writes its cache during ingestion — "
                  "set is_save_binary_file at load time)")
        header = self._binary_header(self.bins.dtype, self.bins.shape)
        # atomic write (temp + rename): a crash mid-save must not leave a
        # partial cache that a later run would misparse
        tmp = path + ".%d.tmp" % os.getpid()
        try:
            with open(tmp, "wb") as f:
                f.write(BINARY_MAGIC)
                blob = pickle.dumps(header)
                f.write(len(blob).to_bytes(8, "little"))
                f.write(blob)
                f.write(np.ascontiguousarray(self.bins).tobytes())
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        log.info("Saved binary data file to %s" % path)

    def save_binary_reference(self, path: str) -> None:
        """Write the REFERENCE's binary cache layout
        (Dataset::SaveBinaryFile, dataset.cpp:653-713) so the reference
        binary can train directly from our cache — the write-side twin of
        the native reader below.  Dense columns only (the reference's
        loader picks DenseBin whenever the file says is_sparse=false,
        bin.cpp:202-210; sparse delta-streams are a CPU cache layout with
        no value in our matrix pipeline).

        Layout quirk inherited from the reference: its own
        Metadata::LoadFromMemory mis-advances past the label block when
        queries are present WITHOUT weights (metadata.cpp:313 advances by
        num_weights, not num_data) — a file we write with that shape is
        byte-faithful to SaveBinaryFile yet unreadable by the reference's
        own loader, exactly like the reference's own caches
        (PARITY.md)."""
        import struct

        md = self.metadata
        n = self.num_data
        weights = md.weights
        qb = md.query_boundaries
        qw = getattr(md, "query_weights", None)
        n_map = self.num_total_features
        fmap = np.full(n_map, -1, dtype=np.int32)
        for real, inner in self.used_feature_map.items():
            fmap[real] = inner
        names = list(self.feature_names)
        if len(names) < n_map:
            names += ["Column_%d" % i for i in range(len(names), n_map)]

        header = b"".join(
            [struct.pack("<Q", int(self.global_num_data or n)),
             struct.pack("<?", False),          # is_enable_sparse
             struct.pack("<iiii", int(self.max_bin), n,
                         self.num_features, n_map),
             struct.pack("<Q", n_map), fmap.tobytes()]
            + [struct.pack("<i", len(s.encode())) + s.encode()
               for s in names])

        meta = [struct.pack("<iii", n,
                            0 if weights is None else len(weights),
                            0 if qb is None else len(qb) - 1),
                np.asarray(md.label, "<f4").tobytes()]
        if weights is not None:
            meta.append(np.asarray(weights, "<f4").tobytes())
        if qb is not None:
            meta.append(np.asarray(qb, "<i4").tobytes())
            if qw is not None:
                meta.append(np.asarray(qw, "<f4").tobytes())
        meta = b"".join(meta)

        # inner features in REAL-index order, like features_ in the
        # reference (construction order = real feature order)
        tmp = path + ".%d.tmp" % os.getpid()
        try:
            with open(tmp, "wb") as f:
                f.write(struct.pack("<Q", len(header)) + header)
                f.write(struct.pack("<Q", len(meta)) + meta)
                for real in self.real_feature_idx:
                    inner = self.used_feature_map[int(real)]
                    m = self.bin_mappers[inner]
                    # single source of the <=256/<=65536 width rule
                    vt = np.dtype(_bin_dtype(m.num_bin)).newbyteorder("<")
                    blob = b"".join([
                        struct.pack("<i?", int(real), False),  # dense
                        struct.pack("<i?d", int(m.num_bin),
                                    bool(m.is_trivial),
                                    float(m.sparse_rate)),
                        np.asarray(m.bin_upper_bound, "<f8").tobytes(),
                        np.ascontiguousarray(
                            self.bins[inner]).astype(vt).tobytes(),
                    ])
                    f.write(struct.pack("<Q", len(blob)) + blob)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        log.info("Saved binary data file to %s" % path)

    @staticmethod
    def _classify_binary_cache(path: str) -> str:
        """'ours' (magic match) / 'corrupt' (a damaged lightgbm_tpu cache
        — recognizable magic prefix but not the full magic) / 'foreign'
        (anything else: the reference's .bin layout, dataset.cpp:653-898,
        starts with a raw size_t header size and carries no magic, and a
        0-byte crash artifact from any other tool is equally not ours).
        save_binary writes atomically, so 'corrupt' is a best-effort
        diagnosis for caches damaged after the fact; _load_binary's parser
        reports anything that slips through."""
        with open(path, "rb") as f:
            head = f.read(len(BINARY_MAGIC))
        if head == BINARY_MAGIC:
            return "ours"
        if head[:8] == b"LGBM_TPU":
            return "corrupt"
        return "foreign"

    def _load_binary(self, path: str, rank: int, num_machines: int,
                     is_pre_partition: bool, data_random_seed: int = 1) -> None:
        try:
            with open(path, "rb") as f:
                # format already validated by _classify_binary_cache (the
                # only caller gates on it); skip past the magic
                f.read(len(BINARY_MAGIC))
                size = int.from_bytes(f.read(8), "little")
                header = pickle.loads(f.read(size))
                bins = np.frombuffer(f.read(),
                                     dtype=np.dtype(header["bins_dtype"]))
        except log.LightGBMError:
            raise
        except Exception as e:
            log.fatal("Binary file %s is a damaged lightgbm_tpu cache "
                      "(%s) — delete it to regenerate" % (path, e))
        self._apply_binary_header(header)
        self.bins = bins.reshape(header["bins_shape"]).copy()
        self._reshard_rows(rank, num_machines, is_pre_partition,
                           data_random_seed)
        self.metadata.finalize(self.num_data)

    def _apply_binary_header(self, header: dict) -> None:
        """Install every non-bin field of a native cache header — shared
        by the resident loader and the streaming (memmap) cache loader."""
        self.num_data = header["num_data"]
        self.global_num_data = header["global_num_data"]
        self.num_total_features = header["num_total_features"]
        self.label_idx = header["label_idx"]
        self.feature_names = header["feature_names"]
        self.used_feature_map = header["used_feature_map"]
        self.max_bin = header["max_bin"]
        self.bin_mappers = [BinMapper.from_bytes(b) for b in header["mappers"]]
        self.real_feature_idx = np.array(sorted(self.used_feature_map),
                                         dtype=np.int32)
        self.num_bins = np.array([m.num_bin for m in self.bin_mappers],
                                 dtype=np.int32)
        self.metadata.set_label(header["label"])
        self.metadata.weights = header["weights"]
        self.metadata.query_boundaries = header["query_boundaries"]
        if (self.metadata.weights is not None
                and self.metadata.query_boundaries is not None):
            # same recompute as the reference-cache loader: finalize()
            # only derives query weights on the queries-column path
            self.metadata._load_query_weights()

    def _reshard_rows(self, rank: int, num_machines: int,
                      is_pre_partition: bool, data_random_seed: int) -> None:
        """Re-shard cached rows for distributed training
        (dataset.cpp:840-872); query-atomic when query boundaries exist,
        same seed as the fresh-load path so cached and fresh runs shard
        identically."""
        if num_machines <= 1 or is_pre_partition:
            return
        rng = np.random.RandomState(data_random_seed)
        qb = self.metadata.query_boundaries
        if qb is not None:
            q_owner = rng.randint(0, num_machines, size=qb.size - 1)
            row_query = np.searchsorted(qb, np.arange(self.num_data),
                                        side="right") - 1
            mask = q_owner[row_query] == rank
        else:
            mask = rng.randint(0, num_machines, size=self.num_data) == rank
        idx = np.nonzero(mask)[0]
        self.bins = np.ascontiguousarray(self.bins[:, idx])
        self.metadata.partition(idx, self.num_data)
        self.num_data = idx.size

    def _load_reference_binary(self, path: str, rank: int,
                               num_machines: int, is_pre_partition: bool,
                               data_random_seed: int = 1) -> None:
        """Load a binary cache WRITTEN BY THE REFERENCE BINARY
        (Dataset::SaveBinaryFile, dataset.cpp:653-713): little-endian,
        tightly packed —

          size_t header_size; { size_t global_num_data; bool sparse;
          int max_bin; int32 num_data; int num_features;
          int num_total_features; size_t n_map; int map[n_map];
          (int len, char[len]) x num_total_features names }
          size_t metadata_size; { int32 num_data, num_weights,
          num_queries; float label[num_data]; float weights[]?;
          int32 query_boundaries[num_queries+1]?; float query_weights[]? }
          per feature: size_t size; { int feature_index; bool is_sparse;
          BinMapper{int num_bin; bool is_trival; double sparse_rate;
          double upper[num_bin]} ; bin data }

        Dense bin data is a raw uint8/16/32 row (width by num_bin,
        bin.cpp:202-210); sparse is the delta stream of
        sparse_bin.hpp:178-187 (int32 n; uint8 delta[n+1]; VAL_T vals[n])
        whose positions are the running delta sum and whose absent rows
        read back as bin 0 (SparseBinIterator::Get) — gap-filler entries
        carry val 0 and land harmlessly.  NOTE: we parse the layout
        SaveBinaryToFile actually WRITES; the reference's own
        Metadata::LoadFromMemory advances by num_weights (not num_data)
        floats past the label block (metadata.cpp:313), a defect that
        garbles its own caches when a query file is present without
        weights.  Raises ValueError on malformed input (the caller falls
        back to re-binning the text file)."""
        import struct

        def take(buf, fmt, off):
            vals = struct.unpack_from("<" + fmt, buf, off)
            return vals, off + struct.calcsize("<" + fmt)

        with open(path, "rb") as f:
            def read_block(what):
                raw = f.read(8)
                if len(raw) != 8:
                    raise ValueError("truncated at %s size" % what)
                n = struct.unpack("<Q", raw)[0]
                if n > (64 << 30):
                    raise ValueError("implausible %s size %d" % (what, n))
                blob = f.read(n)
                if len(blob) != n:
                    raise ValueError("truncated %s" % what)
                return blob

            head = read_block("header")
            (global_num_data,), off = take(head, "Q", 0)
            off += 1                                  # is_enable_sparse
            (max_bin, num_data, num_features,
             num_total_features), off = take(head, "iiii", off)
            (n_map,), off = take(head, "Q", off)
            if not (0 < num_features <= n_map
                    and num_features <= num_total_features):
                raise ValueError("inconsistent feature counts")
            off += 4 * n_map                          # used_feature_map:
            # rebuilt below from each Feature's own feature_index
            names = []
            for _ in range(num_total_features):
                (ln,), off = take(head, "i", off)
                if ln < 0 or off + ln > len(head):
                    raise ValueError("bad feature-name length")
                names.append(head[off:off + ln].decode("utf-8", "replace"))
                off += ln

            meta = read_block("metadata")
            (md_n, md_w, md_q), off = take(meta, "iii", 0)
            if md_n != num_data:
                raise ValueError("metadata/header row-count mismatch")
            label = np.frombuffer(meta, "<f4", md_n, off).copy()
            off += 4 * md_n
            weights = qb = None
            if md_w > 0:
                weights = np.frombuffer(meta, "<f4", md_w, off).copy()
                off += 4 * md_w
            if md_q > 0:
                qb = np.frombuffer(meta, "<i4", md_q + 1, off).copy()
                off += 4 * (md_q + 1)
            # query_weights (if present) are recomputed by finalize()

            mappers: List[BinMapper] = []
            real_idx: List[int] = []
            cols: List[np.ndarray] = []
            for i in range(num_features):
                blob = read_block("feature %d" % i)
                (fidx,), off = take(blob, "i", 0)
                is_sparse = blob[off] != 0
                off += 1
                (num_bin,), off = take(blob, "i", off)
                is_trivial = blob[off] != 0
                off += 1
                (sparse_rate,), off = take(blob, "d", off)
                if not (0 < num_bin <= (1 << 24)):
                    raise ValueError("bad num_bin %d" % num_bin)
                upper = np.frombuffer(blob, "<f8", num_bin, off).copy()
                off += 8 * num_bin
                vt = ("<u1" if num_bin <= 256
                      else "<u2" if num_bin <= 65536 else "<u4")
                if not is_sparse:
                    # a view into blob is fine: the blob IS the column
                    # (astype/stack below materialize fresh memory)
                    col = np.frombuffer(blob, vt, num_data, off)
                else:
                    (nv,), off = take(blob, "i", off)
                    delta = np.frombuffer(blob, "<u1", nv + 1, off)
                    off += nv + 1
                    vals = np.frombuffer(blob, vt, nv, off)
                    pos = np.cumsum(delta[:nv].astype(np.int64))
                    if nv and pos[-1] >= num_data:
                        raise ValueError("sparse position out of range")
                    col = np.zeros(num_data, dtype=vt)
                    col[pos] = vals
                mappers.append(BinMapper(num_bin=num_bin,
                                         is_trivial=bool(is_trivial),
                                         sparse_rate=float(sparse_rate),
                                         bin_upper_bound=upper))
                real_idx.append(fidx)
                cols.append(col)

        order = np.argsort(np.asarray(real_idx, dtype=np.int64),
                           kind="stable")
        self.num_data = num_data
        self.global_num_data = int(global_num_data) or num_data
        self.num_total_features = num_total_features
        self.feature_names = names
        self.max_bin = max_bin
        self.bin_mappers = [mappers[j] for j in order]
        self.used_feature_map = {int(real_idx[j]): k
                                 for k, j in enumerate(order)}
        self.real_feature_idx = np.array(sorted(self.used_feature_map),
                                         dtype=np.int32)
        self.num_bins = np.array([m.num_bin for m in self.bin_mappers],
                                 dtype=np.int32)
        dtype = _bin_dtype(int(self.num_bins.max()))
        self.bins = np.ascontiguousarray(
            np.stack([cols[j].astype(dtype, copy=False) for j in order],
                     axis=0))
        self.metadata.set_label(label)
        self.metadata.weights = weights
        self.metadata.query_boundaries = qb
        if weights is not None and qb is not None:
            # finalize() only derives query weights on the queries-column
            # path; side-file-style weights+queries need the explicit
            # recompute (metadata.cpp:286-298)
            self.metadata._load_query_weights()
        self._reshard_rows(rank, num_machines, is_pre_partition,
                           data_random_seed)
        self.metadata.finalize(self.num_data)


def _label_idx_without_text_load(io_config) -> int:
    """Resolve label_column to an index for binary-cache loads, where no
    text parse happens: numeric directly; ``name:`` via the text header
    if the file is still on disk (application.cpp resolves names the same
    way before any data read)."""
    lc = io_config.label_column
    if not lc:
        return 0
    if not lc.startswith("name:"):
        try:
            return int(lc)
        except ValueError:
            log.fatal("label_column is not a number, if you want to use "
                      "column name, please add prefix \"name:\" before "
                      "column name")
    name = lc[len("name:"):]
    if io_config.has_header and os.path.exists(io_config.data_filename):
        with open(io_config.data_filename, "r") as f:
            first = f.readline().rstrip("\r\n")
        delim = "\t" if first.count("\t") > first.count(",") else ","
        names = first.split(delim)
        if name in names:
            return names.index(name)
        log.fatal("cannot find label column: %s in data file" % name)
    log.warning("label_column=%s cannot be resolved without the text "
                "file's header; keeping label_index=0 (only the saved "
                "model's label_index field is affected)" % lc)
    return 0


def _resolve_columns(io_config) -> Tuple[int, int, int, set, Optional[List[str]]]:
    """Column-role resolution by index or ``name:`` prefix
    (dataset.cpp:44-146).  Returns (label_idx, weight_idx, group_idx,
    ignore_set, header_names); weight/group/ignore indices are in
    label-removed feature space."""
    header_names: Optional[List[str]] = None
    name2idx: Dict[str, int] = {}
    if io_config.has_header:
        with open(io_config.data_filename, "r") as f:
            first = f.readline().rstrip("\r\n")
        delim = "\t" if first.count("\t") > first.count(",") else ","
        header_names = first.split(delim)
        name2idx = {name: i for i, name in enumerate(header_names)}

    def resolve(column: str, what: str) -> int:
        if column.startswith("name:"):
            name = column[len("name:"):]
            if name in name2idx:
                log.info("use %s column as %s" % (name, what))
                return name2idx[name]
            log.fatal("cannot find %s column: %s in data file" % (what, name))
        try:
            idx = int(column)
        except ValueError:
            log.fatal("%s_column is not a number, if you want to use column "
                      "name, please add prefix \"name:\" before column name"
                      % what)
        log.info("use %d-th column as %s" % (idx, what))
        return idx

    label_idx = 0
    if io_config.label_column:
        label_idx = resolve(io_config.label_column, "label")
    if header_names is not None:
        header_names = list(header_names)
        del header_names[label_idx]

    ignore_set: set = set()
    if io_config.ignore_column:
        spec = io_config.ignore_column
        if spec.startswith("name:"):
            for name in spec[len("name:"):].split(","):
                if name not in name2idx:
                    log.fatal("cannot find column: %s in data file" % name)
                idx = name2idx[name]
                if idx > label_idx:
                    idx -= 1
                ignore_set.add(idx)
        else:
            for token in spec.split(","):
                idx = int(token)
                if idx > label_idx:
                    idx -= 1
                ignore_set.add(idx)

    weight_idx = -1
    if io_config.weight_column:
        weight_idx = resolve(io_config.weight_column, "weight")
        if weight_idx > label_idx:
            weight_idx -= 1
        ignore_set.add(weight_idx)

    group_idx = -1
    if io_config.group_column:
        group_idx = resolve(io_config.group_column, "group/query id")
        if group_idx > label_idx:
            group_idx -= 1
        ignore_set.add(group_idx)

    return label_idx, weight_idx, group_idx, ignore_set, header_names


def _make_feature_names(header_names: Optional[List[str]], label_idx: int,
                        num_total: int) -> List[str]:
    if header_names is not None and len(header_names) >= num_total:
        return header_names[:num_total]
    return [f"Column_{i}" for i in range(num_total)]
