"""Streaming ingestion tier (ISSUE 8): sharded out-of-core parse→bin,
double-buffered host→HBM feeds, explicit dataset placement.

The resident loader (``Dataset.load_train``) materializes every line of
the text file as one host ``[N, F]`` float64 matrix before binning — a
~25 GB host allocation at 100M x 28 that caps training around 11M rows.
The reference's own TextReader pipelines 16MB blocks through a bounded
queue (utils/pipeline_reader.h); this module is that idea rebuilt as an
async DEVICE feed:

- **Pass 0** counts data rows with a raw line scan (no parse), sharing
  ``read_line_chunks``'s exact header/blank-line semantics.
- **Pinned-index sample**: the binning sample indices are drawn exactly
  like the resident loader (``RandomState(seed).choice(N, SAMPLE_CNT)``,
  sorted) — an algorithm-R reservoir cannot reproduce those draws, and
  bit-identity with the resident dataset (mappers, bin codes, trained
  model text) is this tier's correctness bar.  ``find_bin`` is
  order-invariant over the sample (np.unique), so gathering the pinned
  rows in file order reproduces the resident mappers bit-for-bit.
- **Pass 1** parses bounded row chunks on a prefetch thread, collecting
  labels/weight/group columns and filling the pinned-index sample
  matrix; bin mappers are fit from the sample (local or distributed
  ``bin_finder``).
- **Pass 2** re-parses chunks, quantizes each against the mappers, and
  lands it straight in device memory through ``DeviceRowWriter``:
  ``jax.device_put`` transfers are dispatched asynchronously and at most
  ``depth`` stay in flight, so the NEXT chunk parses and bins on the
  host while the previous transfer (and its donated
  ``dynamic_update_slice`` into the preallocated ``[F, N]`` HBM matrix)
  is still moving — the double buffer.  ``LGBM_TPU_INGEST_SYNC=1``
  forces depth 0 for the bench lane's A/B.

Placement is explicit: the device matrix carries a ``NamedSharding``
over the ``(data,)`` mesh axis (``parallel.mesh.dataset_row_sharding``).
A single-process PARALLEL consumer gets the matrix committed on the
learner's exact ``get_mesh`` device set — row-sharded for
tree_learner=data when the row count divides the mesh, replicated on
that mesh otherwise (a multi-device shard_map rejects a one-device
commit) — while the serial consumer gets a one-device ``(data,)`` mesh
so serial training computes bit-identically to the resident path.
Multi-PROCESS runs (including feature-parallel, which loads with
num_machines=1 but still runs multi-process — ``single_process()``
gates on the process count, not the shard count) keep the binned LOCAL
shard host-side (bounded by the shard, not the dataset) and ride the
existing ``make_global_rows`` NamedSharding lift in gbdt.init, so
per-host row sharding composes with the DP reduce_scatter ownership
schedule unchanged.

Binary caches stream both ways: a native cache is READ via ``np.memmap``
row-chunks (no full host materialization), and ``is_save_binary_file``
under streaming WRITES the cache through a memmap during pass 2 —
byte-identical to the resident ``save_binary`` output.  Because the
cache is byte-identical and the memmap reader takes the consuming
learner's ``shard_rows``/``shard_devices`` at LOAD time, the cache is
also the elastic-restart re-shard vehicle (ISSUE 14): a ``task=train``
restart on a SHRUNK topology (fewer ``num_machines`` after a
preemption) re-opens the same cache and commits the identical bin
matrix onto the re-factored mesh — the dryrun harness's kill-restart
row and the checkpoint restore's bit-exactness guarantees ride exactly
this property.

Telemetry: the whole load runs under an ``ingest`` span (sub-spans
``ingest_count``/``ingest_pass1``/``ingest_bin``/``ingest_h2d``) and
files the ``ingest/*`` counter family — chunks, rows, h2d_bytes,
h2d_wait_us, overlap_hidden_us (see telemetry.py's docstring;
scripts/telemetry_report.py renders the family with derived GB/s).
"""
from __future__ import annotations

import collections
import os
import pickle
import time
from typing import List, Optional

import numpy as np

from .. import hatches, telemetry, tracing
from ..utils import log
from . import parser as parser_mod

# "auto" engages streaming when the text/cache file is at least this
# large (a resident load of a smaller file is cheap and keeps the
# historical code path); override per-run with streaming=true|false.
AUTO_MIN_BYTES = 256 * 1024 * 1024

# env hatch: force synchronous (depth-0) transfers — the bench lane's
# double-buffer A/B (bench.py --bench-ingest)
SYNC_ENV = "LGBM_TPU_INGEST_SYNC"


def resolve_streaming(io_config, path: str) -> bool:
    """The ``streaming=`` resolution rule, single-homed: "true"/"false"
    force; "auto" engages when ``path`` is at least AUTO_MIN_BYTES."""
    mode = getattr(io_config, "streaming", "auto")
    if mode == "true":
        return True
    if mode == "false":
        return False
    try:
        return os.path.getsize(path) >= AUTO_MIN_BYTES
    except OSError:
        return False


def double_buffer_on() -> bool:
    return not hatches.flag(SYNC_ENV)


def single_process() -> bool:
    """Device residency is single-process only: a multi-process run's
    GBDT paths (_host_inputs) build their global NamedSharding lift from
    HOST arrays — including the feature-parallel learner, which loads
    with num_machines=1 (full rows per process) but still runs
    multi-process."""
    import jax
    return jax.process_count() == 1


# ---------------------------------------------------------------- writers


class HostRowWriter:
    """Row-chunk assembly into a host numpy matrix — the multi-process
    shard target (the global NamedSharding lift happens in gbdt.init via
    make_global_rows, exactly as for a resident dataset)."""

    def __init__(self, num_features: int, num_rows: int, dtype):
        self.bins = np.empty((num_features, num_rows), dtype=dtype)

    def append(self, chunk: np.ndarray, start: int) -> None:
        self.bins[:, start:start + chunk.shape[1]] = chunk

    def finish(self):
        return self.bins


class DeviceRowWriter:
    """Assembles the ``[F, N]`` bin matrix in device memory from host row
    chunks with bounded, double-buffered host→device transfers.

    Each ``append`` dispatches an async ``device_put`` of the binned
    chunk plus a donated ``dynamic_update_slice`` into the preallocated
    device matrix; at most ``depth`` transfers stay in flight (the host
    source buffers of older transfers are released by blocking on them),
    so chunk i+1's parse/bin overlaps chunk i's wire time.  On the CPU
    backend "device" memory IS host RAM and XLA cannot donate, so the
    per-chunk update would copy the whole [F, N] matrix once per chunk
    (O(chunks) full-matrix memcpy for zero memory benefit) — chunks are
    staged into a host matrix instead and committed with ONE sharded
    ``device_put`` in ``finish()``: same values, same placement.

    Counters: ``ingest/h2d_bytes`` (payload), ``ingest/h2d_wait_us``
    (host time actually blocked on transfers) and
    ``ingest/overlap_hidden_us`` (upper-bound estimate of wire time that
    ran behind host parse/bin work: dispatch→wait gaps)."""

    def __init__(self, num_features: int, num_rows: int, dtype, *,
                 sharding=None, depth: int = 2):
        import jax
        import jax.numpy as jnp
        from ..parallel.mesh import dataset_row_sharding
        self._jax = jax
        self.num_rows = int(num_rows)
        self.sharding = (sharding if sharding is not None
                         else dataset_row_sharding(num_rows))
        self._depth = depth if double_buffer_on() else 0
        telemetry.count_route(
            "ingest", "ingest/double_buffer_on" if self._depth
            else "ingest/double_buffer_off")
        dtype = np.dtype(dtype)
        self._pending: "collections.deque" = collections.deque()
        self.h2d_bytes = 0
        self.wait_s = 0.0
        self.hidden_s = 0.0
        if jax.default_backend() == "cpu":
            self._stage = np.empty((num_features, self.num_rows), dtype)
            self._buf = None
            return
        self._stage = None
        try:
            self._buf = jax.jit(
                lambda: jnp.zeros((num_features, self.num_rows),
                                  dtype.name),
                out_shardings=self.sharding)()
        except TypeError:  # older jax without out_shardings
            self._buf = jax.device_put(
                jnp.zeros((num_features, self.num_rows), dtype.name),
                self.sharding)
        self._update = _update_program(donate=True)

    def append(self, chunk: np.ndarray, start: int) -> None:
        """Dispatch one ``[F, c]`` chunk landing at column ``start``."""
        if chunk.shape[1] == 0:
            return
        assert start + chunk.shape[1] <= self.num_rows
        if self._stage is not None:
            self._stage[:, start:start + chunk.shape[1]] = chunk
            self.h2d_bytes += chunk.nbytes
            telemetry.count("ingest/h2d_bytes", chunk.nbytes)
            return
        dev = self._jax.device_put(np.ascontiguousarray(chunk))
        self._buf = self._update(self._buf, dev, np.int32(start))
        self._pending.append((dev, time.perf_counter()))
        self.h2d_bytes += chunk.nbytes
        telemetry.count("ingest/h2d_bytes", chunk.nbytes)
        while len(self._pending) > self._depth:
            self._drain_one()

    def _drain_one(self) -> None:
        dev, t_dispatch = self._pending.popleft()
        t0 = time.perf_counter()
        self._jax.block_until_ready(dev)
        t1 = time.perf_counter()
        self.wait_s += t1 - t0
        self.hidden_s += max(0.0, t0 - t_dispatch)
        telemetry.count("ingest/h2d_wait_us", int((t1 - t0) * 1e6))
        telemetry.count("ingest/overlap_hidden_us",
                        int(max(0.0, t0 - t_dispatch) * 1e6))

    def finish(self):
        """Drain in-flight transfers and return the device matrix."""
        with telemetry.span("ingest_h2d"):
            if self._stage is not None:
                t0 = time.perf_counter()
                self._buf = self._jax.device_put(self._stage,
                                                 self.sharding)
                self._jax.block_until_ready(self._buf)
                self.wait_s = time.perf_counter() - t0
                telemetry.count("ingest/h2d_wait_us",
                                int(self.wait_s * 1e6))
                # the one-shot staged commit hides nothing behind host
                # work — file the zero explicitly so the derived overlap
                # column (telemetry_report) has its counter on CPU
                # rounds instead of dividing by a missing key
                telemetry.count("ingest/overlap_hidden_us", 0)
                self._stage = None
            else:
                while self._pending:
                    self._drain_one()
                self._jax.block_until_ready(self._buf)
        return self._buf


# one instrumented update program per donation mode, shared process-wide
# (jit re-traces per chunk shape: full chunks and the ragged tail are the
# only two shapes of a load)
_UPDATE_PROGRAMS: dict = {}


def _update_program(donate: bool):
    prog = _UPDATE_PROGRAMS.get(donate)
    if prog is None:
        import jax
        import jax.numpy as jnp

        def _update(buf, chunk, start):
            return jax.lax.dynamic_update_slice(
                buf, chunk, (jnp.int32(0), start))

        jitted = jax.jit(_update,
                         donate_argnums=(0,) if donate else ())
        from .. import costmodel
        prog = costmodel.instrument("ingest/update", jitted,
                                    phase="ingest")
        _UPDATE_PROGRAMS[donate] = prog
    return prog


# ------------------------------------------------------- streaming cache


class _CacheWriter:
    """Write the native binary cache during pass 2 through a memmap —
    the streamed twin of ``Dataset.save_binary`` (same magic + pickled
    header + raw ``[F, N]`` bin matrix bytes, written atomically via
    temp + rename), without ever holding the full bin matrix on host."""

    def __init__(self, header: dict, bin_path: str, dtype, shape):
        from .dataset import BINARY_MAGIC
        self._path = bin_path
        self._tmp = bin_path + ".%d.tmp" % os.getpid()
        blob = pickle.dumps(header)
        dtype = np.dtype(dtype)
        total = int(shape[0]) * int(shape[1]) * dtype.itemsize
        with open(self._tmp, "wb") as f:
            f.write(BINARY_MAGIC)
            f.write(len(blob).to_bytes(8, "little"))
            f.write(blob)
            self._offset = f.tell()
            if total:
                f.seek(self._offset + total - 1)
                f.write(b"\0")
        self._mm = (np.memmap(self._tmp, dtype=dtype, mode="r+",
                              offset=self._offset, shape=tuple(shape))
                    if total else None)

    def write(self, chunk: np.ndarray, start: int) -> None:
        if self._mm is not None:
            self._mm[:, start:start + chunk.shape[1]] = chunk

    def finish(self) -> None:
        if self._mm is not None:
            self._mm.flush()
            self._mm = None
        os.replace(self._tmp, self._path)
        log.info("Saved binary data file to %s" % self._path)

    def abort(self) -> None:
        self._mm = None
        try:
            os.unlink(self._tmp)
        except OSError:
            pass


# ------------------------------------------------------------ train load


def pinned_sample_indices(total_rows: int, seed: int,
                          sample_cnt: int) -> Optional[np.ndarray]:
    """The resident loader's binning-sample draw, verbatim
    (dataset.py load_train): sorted ``choice(total_rows, sample_cnt)``
    from a fresh ``RandomState(seed)``, or None when every row is the
    sample.  Single-homed so streaming reproduces the resident mappers
    bit-for-bit (and so the determinism test pins ONE rule)."""
    if total_rows <= sample_cnt:
        return None
    rng = np.random.RandomState(seed)
    return np.sort(rng.choice(total_rows, sample_cnt, replace=False))


def load_train_streaming(ds, io_config, parser, rank: int,
                         num_machines: int, predict_fun, bin_finder,
                         weight_idx: int, group_idx: int, ignore_set,
                         header_names, shard_rows: bool = False,
                         shard_devices: Optional[int] = None,
                         device_type: str = "",
                         foreign_bin: bool = False) -> None:
    """The chunked parse→sample-for-binning→bin→transfer training load.

    Fills ``ds`` (a fresh Dataset) with the exact state the resident
    loader would produce — same mappers, same bin codes, same metadata,
    same shard draw — while holding at most one parse chunk (plus the
    ≤SAMPLE_CNT binning sample and the label/side columns) on the host.
    Single-process loads land the bin matrix directly in device memory
    (``ds.device_bins``; ``ds.bins`` stays None); multi-process loads
    keep the binned LOCAL shard host-side for gbdt's global
    NamedSharding lift."""
    from . import dataset as dataset_mod

    filename = io_config.data_filename
    chunk_rows = getattr(io_config, "ingest_chunk_rows", 200_000)
    device_resident = num_machines <= 1 and single_process()

    # parallel byte-range ingest (ISSUE 18, io/parallel_ingest.py):
    # engaged by ingest_workers > 1, and by ANY multi-process load (the
    # pod-sharded parse: each host tokenizes only its own row shard).
    # Bit-identical to the serial passes below by construction and by
    # test pin (tests/test_parallel_ingest.py).
    workers = int(getattr(io_config, "ingest_workers", 1) or 1)
    ds.ingest_workers_requested = workers
    if workers > 1 or num_machines > 1:
        from . import parallel_ingest
        if parallel_ingest.available():
            return parallel_ingest.load_train_streaming_parallel(
                ds, io_config, parser, rank, num_machines, predict_fun,
                bin_finder, weight_idx, group_idx, ignore_set,
                header_names, shard_rows=shard_rows,
                shard_devices=shard_devices, device_type=device_type,
                foreign_bin=foreign_bin, workers=workers)
        if workers > 1:
            log.warning(
                "ingest_workers=%d requested but no worker interpreter "
                "can be exec'd — parallel parse resolved to the serial "
                "loader" % workers)
    ds.ingest_workers_effective = 1

    with telemetry.span("ingest"):
        # ---- pass 0: count data rows (raw scan, no parse)
        t_pass = time.perf_counter()
        with telemetry.span("ingest_count"):
            total_rows = parser_mod.count_data_rows(
                filename, skip_header=io_config.has_header)
        tracing.record_ingest_pass(0, time.perf_counter() - t_pass,
                                   total_rows)
        ds.global_num_data = total_rows
        sample_cnt = dataset_mod.SAMPLE_CNT
        sample_idx = pinned_sample_indices(
            total_rows, io_config.data_random_seed, sample_cnt)

        # ---- pass 1: labels + side columns + pinned-index sample
        labels_parts: List[np.ndarray] = []
        weight_parts: List[np.ndarray] = []
        group_parts: List[np.ndarray] = []
        sample_parts: List[np.ndarray] = []
        reservoir = None
        num_cols = None
        start = 0
        chunk1_no = 0
        t_pass = time.perf_counter()
        with telemetry.span("ingest_pass1"):
            for lines in parser_mod.prefetch_chunks(
                    parser_mod.read_line_chunks(
                        filename, skip_header=io_config.has_header,
                        chunk_lines=chunk_rows)):
                t0 = time.perf_counter()
                parsed = parser.parse(lines)
                # pass-1 tokenization is parse cost too: without this the
                # ingest/parse_us family under-reports exactly half the
                # tokenizer wall (and the parallel path's selective
                # pass-1 saving would be invisible to the attribution)
                parse_us = (time.perf_counter() - t0) * 1e6
                telemetry.count("ingest/parse_us", int(parse_us))
                tracing.record_ingest_chunk(1, chunk1_no, len(lines),
                                            parse_us, 0.0, 0.0)
                chunk1_no += 1
                feats = parsed.features
                num_cols = feats.shape[1]
                labels_parts.append(parsed.labels)
                if weight_idx >= 0:
                    weight_parts.append(
                        feats[:, weight_idx].astype(np.float32))
                if group_idx >= 0:
                    group_parts.append(feats[:, group_idx].copy())
                c = feats.shape[0]
                if sample_idx is None:
                    # every row is the sample (total <= SAMPLE_CNT); the
                    # concatenation below reproduces the resident
                    # loader's whole-matrix sample in file order
                    sample_parts.append(feats)
                else:
                    if reservoir is None:
                        reservoir = np.empty((sample_idx.size, num_cols),
                                             np.float64)
                    lo = np.searchsorted(sample_idx, start)
                    hi = np.searchsorted(sample_idx, start + c)
                    if hi > lo:
                        reservoir[lo:hi] = feats[sample_idx[lo:hi] - start]
                start += c
        tracing.record_ingest_pass(1, time.perf_counter() - t_pass, start)
        log.check(start == total_rows,
                  "Input file changed between the streaming passes "
                  f"(pass 0: {total_rows} rows, pass 1: {start})")
        if sample_idx is None:
            sample = (np.concatenate(sample_parts) if sample_parts
                      else np.zeros((0, 0), np.float64))
        else:
            sample = reservoir
        del sample_parts, reservoir

        ds.num_total_features = num_cols or 0
        ds.feature_names = dataset_mod._make_feature_names(
            header_names, ds.label_idx, ds.num_total_features)

        # shard mask BEFORE the in-file group column overrides query
        # boundaries — the resident loader's order of operations
        # (side-file boundaries drive query-atomic sharding)
        ds.used_data_indices = ds._draw_shard_mask(io_config, rank,
                                                   num_machines,
                                                   total_rows)
        mask = None
        if ds.used_data_indices is not None:
            mask = np.zeros(total_rows, dtype=bool)
            mask[ds.used_data_indices] = True

        ds._build_bin_mappers(sample, io_config.max_bin, bin_finder,
                              ignore_set)
        del sample

        if weight_idx >= 0:
            log.info("using weight in data file, and ignore additional "
                     "weight file")
            ds.metadata.weights = np.concatenate(weight_parts)
        if group_idx >= 0:
            log.info("using query id in data file, and ignore additional "
                     "query file")
            ds.metadata.query_boundaries = None
            ds.metadata.set_queries_from_column(np.concatenate(group_parts))

        all_labels = (np.concatenate(labels_parts) if labels_parts
                      else np.zeros((0,), np.float32))
        ds.metadata.set_label(all_labels)
        if ds.used_data_indices is not None:
            if ds.metadata.queries is not None:
                ds.metadata.queries = \
                    ds.metadata.queries[ds.used_data_indices]
            ds.metadata.partition(ds.used_data_indices, total_rows)
            ds.num_data = len(ds.used_data_indices)
        else:
            ds.num_data = total_rows
        # finalized BEFORE pass 2: the streamed cache header needs the
        # final query boundaries (finalize is idempotent — the outer
        # loader's second call is a no-op check)
        ds.metadata.finalize(ds.num_data)

        # ---- pass 2: quantize chunks straight into the bin matrix
        F_used = len(ds.bin_mappers)
        dtype = dataset_mod._bin_dtype(
            int(ds.num_bins.max()) if F_used else 256)
        writer = (DeviceRowWriter(
                      F_used, ds.num_data, dtype,
                      sharding=_placement(ds.num_data, shard_rows,
                                          shard_devices, device_type))
                  if device_resident
                  else HostRowWriter(F_used, ds.num_data, dtype))
        cache = _open_cache(ds, io_config, dtype, (F_used, ds.num_data),
                            foreign_bin)
        init_scores = [] if predict_fun is not None else None
        cursor = 0
        start = 0
        chunk_no = 0
        t_pass = time.perf_counter()
        try:
            for lines in parser_mod.prefetch_chunks(
                    parser_mod.read_line_chunks(
                        filename, skip_header=io_config.has_header,
                        chunk_lines=chunk_rows)):
                with telemetry.span("ingest_bin"):
                    # per-chunk tokenizer/bin/H2D split (ISSUE 17): the
                    # attribution that turns an ingest_rows_per_sec
                    # regression into a named phase.  perf_counter pairs
                    # around the three stages; the spans above stay the
                    # coarse (gated) lane.
                    t0 = time.perf_counter()
                    feats = parser.parse(lines).features
                    c0 = feats.shape[0]
                    if mask is not None:
                        feats = feats[mask[start:start + c0]]
                    t1 = time.perf_counter()
                    n = feats.shape[0]
                    t2 = t_h2d = t1
                    if n:
                        binned = np.empty((F_used, n), dtype=dtype)
                        for j_raw, j_inner in ds.used_feature_map.items():
                            binned[j_inner] = \
                                ds.bin_mappers[j_inner].value_to_bin(
                                    feats[:, j_raw]).astype(dtype)
                        if init_scores is not None:
                            init_scores.append(np.asarray(
                                predict_fun(feats),
                                np.float32).reshape(-1))
                        t2 = time.perf_counter()
                        if cache is not None:
                            cache.write(binned, cursor)
                        writer.append(binned, cursor)
                        t_h2d = time.perf_counter()
                parse_us = (t1 - t0) * 1e6
                bin_us = (t2 - t1) * 1e6
                h2d_us = (t_h2d - t2) * 1e6
                telemetry.count("ingest/chunks")
                telemetry.count("ingest/rows", n)
                telemetry.count("ingest/parse_us", int(parse_us))
                telemetry.count("ingest/bin_us", int(bin_us))
                telemetry.count("ingest/h2d_us", int(h2d_us))
                tracing.record_ingest_chunk(2, chunk_no, n, parse_us,
                                            bin_us, h2d_us)
                chunk_no += 1
                cursor += n
                start += c0
            log.check(start == total_rows and cursor == ds.num_data,
                      "Input file changed between the streaming passes "
                      f"(pass 1: {total_rows} rows, pass 2: {start})")
            tracing.record_ingest_pass(2, time.perf_counter() - t_pass,
                                       cursor)
            # the final drain (device_put commit / in-flight transfers)
            # belongs to the H2D phase too — without it the attribution
            # would under-report exactly the part that scales with data
            t_fin = time.perf_counter()
            out = writer.finish()
            telemetry.count("ingest/h2d_us",
                            int((time.perf_counter() - t_fin) * 1e6))
            if device_resident:
                ds.device_bins = out
                ds.bins = None
            else:
                ds.bins = out
            if init_scores is not None:
                ds.metadata.init_score = np.concatenate(init_scores)
            if cache is not None:
                cache.finish()
        except BaseException:
            if cache is not None:
                cache.abort()
            raise


def _placement(num_rows: int, shard_rows: bool,
               shard_devices: Optional[int] = None,
               device_type: str = ""):
    """``shard_devices is not None`` marks a single-process PARALLEL
    consumer (its value = the learner's get_mesh size): the matrix must
    then live on the learner's mesh even when rows aren't sharded, or
    the learner's multi-device shard_map would see an incompatible
    one-device commit."""
    from ..parallel.mesh import dataset_row_sharding
    return dataset_row_sharding(
        num_rows, shard_rows=shard_rows, num_machines=shard_devices,
        device_type=device_type,
        parallel_consumer=shard_devices is not None)


def _open_cache(ds, io_config, dtype, shape,
                foreign_bin: bool = False) -> Optional[_CacheWriter]:
    if not io_config.is_save_binary_file:
        return None
    bin_path = io_config.data_filename + ".bin"
    if foreign_bin:
        # load_train already warned ("NOT overwriting it"): a foreign
        # .bin next to the data file must never be clobbered
        return None
    if io_config.save_binary_format == "reference":
        log.warning("save_binary_format=reference is not supported by "
                    "the streaming loader (the reference layout is "
                    "per-feature-major); skipping the cache write — use "
                    "streaming=false to write a reference cache")
        return None
    return _CacheWriter(ds._binary_header(dtype, shape), bin_path,
                        dtype, shape)


# ---------------------------------------------------- binary-cache load


def load_binary_streaming(ds, path: str, io_config,
                          shard_rows: bool = False,
                          shard_devices: Optional[int] = None,
                          device_type: str = "") -> None:
    """Stream a NATIVE binary cache into device memory: the header is
    parsed as usual, but the ``[F, N]`` bin-matrix region is memmapped
    and fed to the device in row chunks (bounded host RSS) instead of
    being read into one host array.  Single-process only — multi-process
    cache loads reshard rows host-side and keep the resident path."""
    from .dataset import BINARY_MAGIC

    chunk_rows = getattr(io_config, "ingest_chunk_rows", 200_000)
    with telemetry.span("ingest"):
        try:
            with open(path, "rb") as f:
                f.read(len(BINARY_MAGIC))
                size = int.from_bytes(f.read(8), "little")
                header = pickle.loads(f.read(size))
                offset = f.tell()
        except log.LightGBMError:
            raise
        except Exception as e:
            log.fatal("Binary file %s is a damaged lightgbm_tpu cache "
                      "(%s) — delete it to regenerate" % (path, e))
        ds._apply_binary_header(header)
        dtype = np.dtype(header["bins_dtype"])
        shape = tuple(header["bins_shape"])
        mm = np.memmap(path, dtype=dtype, mode="r", offset=offset,
                       shape=shape) if shape[0] * shape[1] else None
        writer = DeviceRowWriter(
            shape[0], shape[1], dtype,
            sharding=_placement(shape[1], shard_rows, shard_devices,
                                device_type))
        # cache loads file the same pass/chunk attribution as the text
        # path (pass 2 only, parse_us=0: there is no tokenizer here), so
        # trace dumps and pod_report ingest attribution aren't blind on
        # the fast path
        t_pass = time.perf_counter()
        chunk_no = 0
        if mm is not None:
            for s in range(0, shape[1], chunk_rows):
                e = min(s + chunk_rows, shape[1])
                with telemetry.span("ingest_bin"):
                    t0 = time.perf_counter()
                    chunk = np.ascontiguousarray(mm[:, s:e])
                    t1 = time.perf_counter()
                    writer.append(chunk, s)
                    t2 = time.perf_counter()
                bin_us = (t1 - t0) * 1e6
                h2d_us = (t2 - t1) * 1e6
                telemetry.count("ingest/chunks")
                telemetry.count("ingest/rows", e - s)
                telemetry.count("ingest/bin_us", int(bin_us))
                telemetry.count("ingest/h2d_us", int(h2d_us))
                tracing.record_ingest_chunk(2, chunk_no, e - s, 0.0,
                                            bin_us, h2d_us)
                chunk_no += 1
        t_fin = time.perf_counter()
        ds.device_bins = writer.finish()
        telemetry.count("ingest/h2d_us",
                        int((time.perf_counter() - t_fin) * 1e6))
        tracing.record_ingest_pass(2, time.perf_counter() - t_pass,
                                   shape[1])
        ds.bins = None
        ds.metadata.finalize(ds.num_data)
