"""Feature binning: value → bin quantization.

Re-implements the reference BinMapper (/root/reference/src/io/bin.cpp:42-132,
include/LightGBM/bin.h:47-119, 296-309) with NumPy.  The FindBin algorithm is
reproduced step-for-step (distinct-values fast path, dedicated bins for
high-count values, equal-frequency remainder) because differential tests
against the reference depend on identical bin boundaries.

TPU-first difference: there is no per-feature Bin object zoo
(DenseBin/SparseBin/OrderedSparseBin are CPU cache optimizations,
dense_bin.hpp/sparse_bin.hpp) — the whole dataset becomes one dense
``[num_features, num_rows]`` integer matrix living in HBM; see
lightgbm_tpu/io/dataset.py.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, NamedTuple, Optional

import numpy as np

from .. import hatches


@dataclass
class BinMapper:
    """Quantization map for one feature (bin.h:47-119)."""
    num_bin: int = 0
    is_trivial: bool = False
    sparse_rate: float = 0.0
    # bin i covers values <= bin_upper_bound[i]; last entry is +inf
    bin_upper_bound: np.ndarray = field(default_factory=lambda: np.zeros(0))

    def find_bin(self, values: np.ndarray, max_bin: int) -> None:
        """BinMapper::FindBin (bin.cpp:42-132), literal algorithm port.

        ``values`` are the sampled values for this feature, zeros included
        (dataset.cpp:278-305 pushes an explicit 0.0 per sampled row).
        """
        values = np.asarray(values, dtype=np.float64)
        sample_size = values.size
        distinct_values, counts = np.unique(values, return_counts=True)
        distinct_values = list(distinct_values)
        counts = [int(c) for c in counts]
        num_values = len(distinct_values)
        cnt_in_bin0 = 0

        if num_values <= max_bin:
            # distinct values are enough: midpoints as boundaries
            self.num_bin = num_values
            upper = np.empty(num_values, dtype=np.float64)
            for i in range(num_values - 1):
                upper[i] = (distinct_values[i] + distinct_values[i + 1]) / 2.0
            if num_values > 0:
                cnt_in_bin0 = counts[0]
                upper[num_values - 1] = np.inf
            self.bin_upper_bound = upper
        else:
            # hybrid: dedicated bins for large-count values, then
            # equal-frequency for the remainder
            mean_bin_size = sample_size / float(max_bin)
            rest_sample_cnt = sample_size
            bin_cnt = 0
            self.num_bin = max_bin
            upper_bounds = [np.inf] * max_bin
            lower_bounds = [np.inf] * max_bin
            # sort by count, descending.  Tie order among equal counts is
            # provably irrelevant to the resulting bounds (dedicated-bin
            # membership is a strict threshold over a contiguous tie run,
            # and both the remainder and the final bins are re-sorted by
            # value) — proven adversarially in tests/test_binning.py.
            # DELIBERATE DIVERGENCE (PARITY.md): the reference's remainder
            # value sort goes through Common::SortForPair
            # (common.h:362-381), whose write-back is off by `start`; with
            # start=bin_cnt>0 (bin.cpp:93) it DROPS the bin_cnt smallest
            # remainder values and leaves a stale std::sort-order-dependent
            # tail, silently losing bin boundaries on features with
            # dedicated bins.  We implement the intended algorithm
            # (tests/test_reference_differential.py::
            # test_binning_count_ties_reference_sortforpair_defect pins
            # both behaviors).
            order = sorted(range(num_values), key=lambda i: -counts[i])
            counts = [counts[i] for i in order]
            distinct_values = [distinct_values[i] for i in order]
            # fetch big slots as dedicated bins
            while bin_cnt < num_values and counts[bin_cnt] > mean_bin_size:
                upper_bounds[bin_cnt] = distinct_values[bin_cnt]
                lower_bounds[bin_cnt] = distinct_values[bin_cnt]
                rest_sample_cnt -= counts[bin_cnt]
                bin_cnt += 1
            # process remainder bins
            if bin_cnt < max_bin:
                # sort rest by value ascending
                rest = sorted(range(bin_cnt, num_values),
                              key=lambda i: distinct_values[i])
                distinct_values[bin_cnt:] = [distinct_values[i] for i in rest]
                counts[bin_cnt:] = [counts[i] for i in rest]
                mean_bin_size = rest_sample_cnt / float(max_bin - bin_cnt)
                lower_bounds[bin_cnt] = distinct_values[bin_cnt]
                cur_cnt_inbin = 0
                for i in range(bin_cnt, num_values - 1):
                    rest_sample_cnt -= counts[i]
                    cur_cnt_inbin += counts[i]
                    if cur_cnt_inbin >= mean_bin_size:
                        upper_bounds[bin_cnt] = distinct_values[i]
                        if bin_cnt == 0:
                            cnt_in_bin0 = cur_cnt_inbin
                        bin_cnt += 1
                        lower_bounds[bin_cnt] = distinct_values[i + 1]
                        if bin_cnt >= max_bin - 1:
                            break
                        cur_cnt_inbin = 0
                        mean_bin_size = rest_sample_cnt / float(max_bin - bin_cnt)
            # sort (lower, upper) pairs by lower bound
            pairs = sorted(zip(lower_bounds, upper_bounds), key=lambda p: p[0])
            lower_bounds = [p[0] for p in pairs]
            upper_bounds = [p[1] for p in pairs]
            self.num_bin = bin_cnt
            upper = np.empty(bin_cnt, dtype=np.float64)
            for i in range(bin_cnt - 1):
                upper[i] = (upper_bounds[i] + lower_bounds[i + 1]) / 2.0
            if bin_cnt > 0:
                upper[bin_cnt - 1] = np.inf
            self.bin_upper_bound = upper

        self.is_trivial = self.num_bin <= 1
        self.sparse_rate = (cnt_in_bin0 / float(sample_size)
                            if sample_size > 0 else 0.0)

    def value_to_bin(self, value):
        """ValueToBin binary search (bin.h:296-309): first bin whose upper
        bound >= value.  Vectorized: accepts scalars or arrays."""
        bounds = self.bin_upper_bound[:-1]  # last is +inf
        return np.searchsorted(bounds, np.asarray(value), side="left").astype(np.int32)

    def bin_to_value(self, bin_idx: int) -> float:
        """Upper bound of a bin; used as the real-valued split threshold
        (serial_tree_learner.cpp:418 BinToValue)."""
        return float(self.bin_upper_bound[bin_idx])

    def bin_representatives(self) -> np.ndarray:
        """One finite real value per bin that ``value_to_bin`` maps back
        to that bin — the decode table for predicting straight from a
        columnar-binary cache (predictor.predict_file on a ``.bin``
        input).  Bin b < num_bin-1 uses its own upper bound: bounds are
        strictly increasing and the searchsorted is side="left", so
        ``value_to_bin(upper[b]) == b`` exactly.  The last bin's bound is
        +inf — any value strictly above the previous bound lands there,
        so ``upper[-2] + 1`` does (single-bin mappers are trivial; 0.0
        keeps them finite)."""
        vals = self.bin_upper_bound.astype(np.float64).copy()
        if vals.size and not np.isfinite(vals[-1]):
            vals[-1] = vals[-2] + 1.0 if vals.size > 1 else 0.0
        return vals

    @property
    def default_bin(self) -> int:
        """Bin of value 0 — the implicit bin for unseen entries
        (bin.h CreateBin default_bin = ValueToBin(0))."""
        return int(self.value_to_bin(0.0))

    # --- serialization (bin.cpp:144-175 fixed layout, used by the binary
    # dataset cache and distributed bin-mapper gathers) ---

    def to_bytes(self) -> bytes:
        import struct
        head = struct.pack("<i?7x d", self.num_bin, self.is_trivial, self.sparse_rate)
        return head + np.asarray(self.bin_upper_bound, dtype=np.float64).tobytes()

    @classmethod
    def from_bytes(cls, buffer: bytes) -> "BinMapper":
        import struct
        num_bin, is_trivial, sparse_rate = struct.unpack_from("<i?7x d", buffer, 0)
        offset = struct.calcsize("<i?7x d")
        upper = np.frombuffer(buffer, dtype=np.float64, count=num_bin,
                              offset=offset).copy()
        return cls(num_bin=num_bin, is_trivial=bool(is_trivial),
                   sparse_rate=sparse_rate, bin_upper_bound=upper)


# ---------------------------------------------------------------------------
# Mixed-bin feature packing (ISSUE 6).
#
# The reference pays per-feature bin counts: BinMapper.find_bin emits
# ``num_bin <= max_bin`` PER FEATURE, and the CPU scatter-add loop touches
# only the bins a feature actually has.  The TPU one-hot-matmul kernels
# instead price every feature at the uniform ``num_bins_max`` histogram
# width — a 3-distinct-value flag column costs the same 255-wide pass as a
# fully continuous one.  The fix is a LAYOUT decision made once at Dataset
# build time: partition features into bin-WIDTH classes (narrow: num_bin
# fits the 64-wide kernel class — the measured-fast ``maxbin63`` shape;
# wide: everything else at the dataset's num_bins_max), reorder the bin
# matrix so each class is a contiguous feature block, and run one histogram
# pass per class.  The per-class histograms are concatenated back into
# CANONICAL feature order before split finding, so feature indices,
# argmax tie-breaks, ownership blocks and trees are exactly the uniform
# path's — a narrow feature's bins beyond its num_bin are all zero in the
# uniform pass too, so the reassembled histogram is value-identical.
#
# The spec is a NamedTuple of plain tuples: hashable, so it rides the
# growers' jit static args and the chunk-program cache keys.

# bin-width classes: features with num_bin <= NARROW_BINS take the narrow
# kernel class (one 64-wide histogram pass — the ``maxbin63`` kernel shape
# measured at 2.6x the 255-wide pass); everything else pays num_bins_max.
# scripts/hist_kernel_bench.py --sweep-classes re-derives this threshold
# from measurement when kernel economics change.
NARROW_BINS = 64


class PackSpec(NamedTuple):
    """Static description of a packed bin-matrix layout.

    widths : per-class histogram width, ascending (e.g. ``(64, 255)``)
    counts : features per class, same order; ``sum(counts) == F``
    perm   : packed position -> canonical inner feature index (stable
             within each class, so the packed order is reproducible)
    """
    widths: tuple
    counts: tuple
    perm: tuple

    @property
    def num_features(self) -> int:
        return len(self.perm)

    @property
    def ranges(self):
        """Per-class ``(start, count, width)`` in packed feature order."""
        out, start = [], 0
        for cnt, width in zip(self.counts, self.widths):
            out.append((start, cnt, width))
            start += cnt
        return tuple(out)

    @property
    def c2p(self) -> tuple:
        """Canonical inner feature index -> packed position (inverse of
        ``perm``)."""
        inv = [0] * len(self.perm)
        for p, f in enumerate(self.perm):
            inv[f] = p
        return tuple(inv)


class BlockedPackSpec(NamedTuple):
    """Block-local mixed-bin layout for feature-block ownership meshes
    (ISSUE 12): the bin-width-class permutation is computed PER owned
    feature block of width ``block`` and never crosses a block boundary,
    so packing COMMUTES with contiguous feature-block ownership — the
    storage positions of ownership block ``b`` are exactly the canonical
    positions ``[b*block, (b+1)*block)``, only the inner order changes.
    The owned-block psum / psum_scatter and the packed-SplitInfo
    allreduce therefore ride unchanged, and the hybrid/voting learners
    no longer force the uniform layout.

    SPMD constraint: every shard traces ONE program, so the per-block
    class counts must be identical across blocks.  ``counts`` is the
    per-block split ``(narrow, block - narrow)`` with ``narrow`` = the
    MINIMUM narrow-feature count over all blocks; each block stores its
    first ``narrow`` narrow features (canonical order, stable) in the
    narrow segment and everything else — surplus narrow features
    included — in the wide segment at the full width (a narrow feature
    histogrammed at the wide width is value-identical: its bins beyond
    ``num_bin`` are zero either way).  The plan degenerates to None
    (uniform layout) when the narrowest block contributes no narrow
    feature — see :func:`plan_feature_packing_blocked`.

    widths : per-class histogram width ``(narrow_bins, num_bins_max)``
    counts : per-BLOCK features per class ``(c_n, block - c_n)``
    block  : the ownership block width ``Fb = ceil(F / feature_shards)``
    perm   : packed storage position -> canonical feature (global, len F;
             a concatenation of within-block permutations)
    """
    widths: tuple
    counts: tuple
    block: int
    perm: tuple

    @property
    def num_features(self) -> int:
        return len(self.perm)

    @property
    def c2p(self) -> tuple:
        """Canonical feature -> packed storage position (global)."""
        inv = [0] * len(self.perm)
        for p, f in enumerate(self.perm):
            inv[f] = p
        return tuple(inv)

    @property
    def ranges(self):
        """Global per-class ``(start, count, width)`` segments in packed
        storage order: per-block interleaved ``narrow`` then ``wide``
        segments (the full-F histogram routes run one pass per segment
        and reassemble via ``c2p`` — the generic PackSpec contract)."""
        F = len(self.perm)
        c_n = self.counts[0]
        out = []
        for start in range(0, F, self.block):
            width = min(self.block, F - start)
            if c_n:
                out.append((start, c_n, self.widths[0]))
            if width > c_n:
                out.append((start + c_n, width - c_n, self.widths[1]))
        return tuple(out)

    @property
    def block_view(self) -> PackSpec:
        """The per-owned-block view the SHARDED histogram passes use: the
        sliced ``[Fb, N]`` owned block is already in packed order and
        STAYS in packed order (identity perm) — feature identity is
        restored at the split finder's storage->canonical remap, so the
        pass structure is shard-uniform (SPMD) even though each block's
        inner permutation differs."""
        return PackSpec(widths=self.widths,
                        counts=(self.counts[0],
                                self.block - self.counts[0]),
                        perm=tuple(range(self.block)))


def plan_feature_packing_blocked(num_bins, num_bins_max: int,
                                 block: int,
                                 mode: str = "auto",
                                 narrow_bins: int = NARROW_BINS,
                                 shards: int = 0
                                 ) -> Optional[BlockedPackSpec]:
    """Block-local mixed-bin plan for a contiguous feature-block
    ownership layout (``block`` = the per-shard block width, ``shards``
    the feature-shard count when known).  Returns None — the uniform
    layout — when packing cannot help or cannot hold: single global
    class (same rule as :func:`plan_feature_packing`), a shard that owns
    ONLY ownership padding (``block * (shards-1) >= F``: its clamped
    duplicate lanes would land a wide feature in the narrow segment —
    garbage outside the masked lanes, but the degenerate mesh isn't
    worth serving), or a narrowest block with no narrow feature (the
    uniform per-block class counts would be ``(0, block)`` — one
    class)."""
    if mode == "false" or hatches.flag("LGBM_TPU_NO_MIXEDBIN"):
        return None
    nb = np.asarray(num_bins)
    F = nb.size
    if F == 0 or num_bins_max <= narrow_bins or block <= 0:
        return None
    if shards > 1 and block * (shards - 1) >= F:
        return None
    narrow = nb <= narrow_bins
    if not narrow.any() or narrow.all():
        return None
    starts = list(range(0, F, block))
    c_n = min(int(narrow[s:s + block].sum()) for s in starts)
    if c_n == 0:
        return None
    perm = []
    for s in starts:
        width = min(block, F - s)
        local = np.arange(s, s + width)
        is_n = narrow[s:s + width]
        first_n = local[is_n][:c_n]
        rest = np.array([f for f in local if f not in set(first_n)],
                        dtype=np.int64)
        perm.extend(int(i) for i in np.concatenate([first_n, rest]))
    return BlockedPackSpec(
        widths=(int(narrow_bins), int(num_bins_max)),
        counts=(int(c_n), int(block - c_n)),
        block=int(block),
        perm=tuple(perm))


def plan_feature_packing(num_bins, num_bins_max: int,
                         mode: str = "auto",
                         narrow_bins: int = NARROW_BINS
                         ) -> Optional[PackSpec]:
    """Decide the packed layout for a dataset's per-feature bin counts.

    Returns None when packing cannot help — a single bin-width class
    (every feature wide, or every feature already within the narrow
    width so ``num_bins_max`` is small anyway) collapses to the existing
    single-pass path with no layout change at all.  ``mode``:
    "auto"/"true" enable (auto and true only differ for callers that log
    the decision), "false" disables.  The ``LGBM_TPU_NO_MIXEDBIN=1`` env
    hatch forces off for A/B timing without touching configs."""
    if mode == "false" or hatches.flag("LGBM_TPU_NO_MIXEDBIN"):
        return None
    nb = np.asarray(num_bins)
    if nb.size == 0 or num_bins_max <= narrow_bins:
        return None
    narrow = nb <= narrow_bins
    if not narrow.any() or narrow.all():
        # degenerate: one class only — the uniform path IS the packed
        # path (all-narrow datasets already ride a small num_bins_max)
        return None
    order = np.concatenate([np.nonzero(narrow)[0], np.nonzero(~narrow)[0]])
    return PackSpec(
        widths=(int(narrow_bins), int(num_bins_max)),
        counts=(int(narrow.sum()), int((~narrow).sum())),
        perm=tuple(int(i) for i in order))


def find_bins_for_matrix(sample: np.ndarray, max_bin: int) -> List[BinMapper]:
    """Compute a BinMapper per column of a dense sample matrix
    (ConstructBinMappers single-machine path, dataset.cpp:322-350)."""
    mappers = []
    for j in range(sample.shape[1]):
        mapper = BinMapper()
        mapper.find_bin(sample[:, j], max_bin)
        mappers.append(mapper)
    return mappers
