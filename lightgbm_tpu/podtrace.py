"""Pod-scope trace merge: clock alignment, global timeline, seam
roofline and ingest attribution over per-host flight-recorder dumps
(ISSUE 17).

PR 16's recorder (tracing.py) is deliberately per-host: one process,
one ring, one dump.  Every interesting production question — which host
stalled the collective, whether a seam is wire-bound, where the ingest
regression lives — is a POD question.  This module turns a set of
per-host dumps into one answer:

**Clock alignment.**  Hosts' ``time.time()`` clocks disagree.  But
every participant of a blocking collective exits it within that
collective's own blocked window of the last arrival, so matched
``collective_sync`` events (same site, same iteration, recorded by
``tracing.record_collective_sync`` with ``pod=True`` when the
collective truly spanned processes) estimate the pairwise clock offset
with error bounded by ``max(duration_a, duration_b)``.  :func:`align`
picks, per host, the matched event with the SMALLEST such bound,
records ``offset_s`` AND ``bound_s`` — the bound is part of the
answer, never pretend better — and cross-checks every other estimate
against it (two estimates of the same offset may differ by at most the
sum of their bounds; a violation means the dumps do not describe one
run, or a clock stepped mid-run).

**Merge algebra.**  :func:`merge_timeline` shifts each host's events
onto the reference clock and sorts by the total order
``(t_aligned, host_label, per-host sequence)`` — associative and
host-order-independent by construction (test-pinned).  Latency
families merge via the sketches' associative bucket addition
(:func:`merge_sketches`).  Events are copied, never mutated: the
per-host ``sum(components) == wall`` identity must survive the merge
bit-for-bit, and :func:`check` re-validates it on the merged timeline
(a tampered per-host dump surfaces here).

**Seam roofline.**  ``wire_model`` events (telemetry stamps its
per-site logical-byte model into the ring at session close) joined
against measured ``collective_sync`` span seconds give per-seam
attained GB/s; divided by the caller-supplied interconnect peak
(``costmodel.resolve_peaks()['ici_bytes_per_sec']``) that becomes the
attained-vs-roofline fraction — None, honestly, on CPU/unknown chips.

**File barrier.**  :func:`file_barrier` is a stdlib cross-process
rendezvous over a shared directory with the same exit-window property
as a real collective — the multi-process dryrun smoke uses it as its
pod-wide sync point, so the recorded bound is honest there too.

Stdlib + tracing only (no JAX, no numpy): usable from crash-forensics
tooling on hosts without the accelerator stack.  ``scripts/
pod_report.py`` is the CLI face.
"""
from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional, Tuple

from . import tracing


class PodTraceError(Exception):
    """Unusable input: junk dump, mixed runs, unmergeable sketches."""


# ------------------------------------------------------------------ loading

def load_dump(path: str) -> dict:
    """One per-host dump -> {path, header, events, label}.  Raises
    PodTraceError on junk (mirrors trace_report.load, kept in-package
    so the merge library works without the script)."""
    try:
        f = open(path)
    except OSError as e:
        raise PodTraceError("cannot read %s: %s" % (path, e))
    header, events = None, []
    with f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError as e:
                raise PodTraceError("%s:%d: unparseable JSONL (%s)"
                                    % (path, lineno, e))
            if lineno == 1:
                if not isinstance(rec, dict) or "trace_header" not in rec:
                    raise PodTraceError(
                        "%s:1: first line is not a trace_header" % path)
                header = rec["trace_header"]
            elif not isinstance(rec, dict) or "kind" not in rec:
                raise PodTraceError("%s:%d: event line without a kind"
                                    % (path, lineno))
            else:
                events.append(rec)
    if header is None:
        raise PodTraceError("%s: empty dump (no trace_header line)" % path)
    return {"path": path, "header": header, "events": events,
            "label": host_label(header)}


def host_label(header: dict) -> str:
    """Stable per-host merge label: ``p<i>`` when the dump carries a
    process index (matches timeline_report's shard labels, so skew rows
    compare across both artifact kinds), else ``<host>-<pid>``."""
    idx = header.get("process_index")
    if isinstance(idx, int):
        return "p%d" % idx
    return "%s-%s" % (header.get("host", "unknown"), header.get("pid", 0))


def check_headers(dumps: List[dict]) -> List[str]:
    """Cross-host header bookkeeping (empty list = mergeable): one run
    id, consistent process_count, distinct in-range process indices."""
    bad: List[str] = []
    run_ids = {}
    counts = {}
    labels: Dict[str, str] = {}
    for d in dumps:
        h, path = d["header"], d["path"]
        run_ids.setdefault(str(h.get("run_id") or ""), []).append(path)
        idx, cnt = h.get("process_index"), h.get("process_count")
        if cnt is not None:
            if not isinstance(cnt, int) or cnt < 1:
                bad.append("%s: header process_count=%r is not a "
                           "positive int" % (path, cnt))
            else:
                counts.setdefault(cnt, []).append(path)
        if idx is not None:
            if not isinstance(idx, int) or idx < 0 or (
                    isinstance(cnt, int) and cnt >= 1 and idx >= cnt):
                bad.append("%s: header process_index=%r out of range for "
                           "process_count=%r" % (path, idx, cnt))
        prev = labels.get(d["label"])
        if prev is not None:
            bad.append("%s: duplicate host identity %s (also %s) — two "
                       "dumps from one process cannot merge as a pod"
                       % (path, d["label"], prev))
        labels[d["label"]] = path
    if len(run_ids) > 1:
        bad.append("mixing dumps from different runs: run_id %s — a "
                   "cross-run merge would be silently wrong"
                   % (" vs ".join(repr(r) for r in sorted(run_ids))))
    if len(counts) > 1:
        bad.append("inconsistent process_count across dumps: %s"
                   % sorted(counts))
    return bad


# ------------------------------------------------------------ clock alignment

def sync_points(dumps: List[dict]) -> Dict[Tuple[str, int], Dict[str, dict]]:
    """Matched pod-wide sync events: {(site, iter): {label: event}}.
    Only ``pod=True`` collective_sync events qualify — a process-local
    collective says nothing about another host's clock.  The LAST event
    per key wins (re-recorded iterations supersede)."""
    out: Dict[Tuple[str, int], Dict[str, dict]] = {}
    for d in dumps:
        for ev in d["events"]:
            if ev.get("kind") != "collective_sync" or not ev.get("pod"):
                continue
            key = (str(ev.get("site")), int(ev.get("iter", -1)))
            out.setdefault(key, {})[d["label"]] = ev
    return {k: v for k, v in out.items() if len(v) > 1}


def align(dumps: List[dict]) -> dict:
    """Per-host clock offsets onto the reference host's clock.

    Reference = lexicographically smallest label.  For host ``h``, each
    matched sync key gives the estimate ``t1_ref - t1_h`` (exit-stamp
    difference; add ``offset_s`` to h's clock to land on the
    reference's) with error bound ``max(dur_ref, dur_h)``.  The
    estimate with the smallest bound wins and its bound is recorded —
    ``bound_s`` is the honest error bar, never better than the slowest
    of the two matched collectives.  ``consistent`` is False when any
    other estimate disagrees by more than the sum of the two bounds
    (impossible for one run with stable clocks)."""
    labels = sorted(d["label"] for d in dumps)
    ref = labels[0] if labels else None
    points = sync_points(dumps)
    offsets: Dict[str, dict] = {}
    ok = True
    for lab in labels:
        if lab == ref:
            offsets[lab] = {"offset_s": 0.0, "bound_s": 0.0,
                            "sync_points": 0, "consistent": True}
            continue
        ests: List[Tuple[float, float]] = []  # (bound, estimate)
        for key, by_host in points.items():
            a, b = by_host.get(ref), by_host.get(lab)
            if a is None or b is None:
                continue
            dur_a = max(float(a["t1"]) - float(a["t0"]), 0.0)
            dur_b = max(float(b["t1"]) - float(b["t0"]), 0.0)
            ests.append((max(dur_a, dur_b),
                         float(a["t1"]) - float(b["t1"])))
        if not ests:
            offsets[lab] = {"offset_s": None, "bound_s": None,
                            "sync_points": 0, "consistent": False}
            ok = False
            continue
        ests.sort()
        bound, offset = ests[0]
        consistent = all(abs(e - offset) <= b + bound + 1e-9
                        for b, e in ests)
        offsets[lab] = {"offset_s": round(offset, 6),
                        "bound_s": round(bound, 6),
                        "sync_points": len(ests),
                        "consistent": consistent}
        ok = ok and consistent
    return {"reference": ref, "offsets": offsets, "ok": ok,
            "matched_keys": len(points)}


# ------------------------------------------------------------------- merging

def merge_timeline(dumps: List[dict],
                   alignment: Optional[dict] = None) -> List[dict]:
    """All hosts' events on the reference clock, one global timeline.

    Each event is COPIED with ``_host`` (label) added and ``t`` shifted
    by the host's alignment offset (unaligned hosts shift by 0 — their
    events still merge, on their own clock, and --check flags it).  The
    sort key ``(t, _host, _seq)`` is a total order, so the result is
    independent of the order dumps are passed in and the merge is
    associative (merging [A,B] then C equals merging [A,[B,C]] equals
    one [A,B,C] pass) — the algebra tests pin this."""
    if alignment is None:
        alignment = align(dumps)
    out: List[dict] = []
    for d in sorted(dumps, key=lambda d: d["label"]):
        off = (alignment["offsets"].get(d["label"], {}) or {}) \
            .get("offset_s") or 0.0
        for seq, ev in enumerate(d["events"]):
            ev = dict(ev)
            ev["_host"] = d["label"]
            ev["_seq"] = seq
            if isinstance(ev.get("t"), (int, float)):
                ev["t"] = round(float(ev["t"]) + off, 6)
            out.append(ev)
    out.sort(key=lambda e: (e.get("t", 0.0), e["_host"], e["_seq"]))
    return out


def merge_sketch_dicts(a: dict, b: dict) -> dict:
    """Serialized-form sketch merge (growth/zero/buckets dicts) — the
    same bucket-count addition LatencySketch.merge performs, usable on
    dumps without rehydrating.  Raises on growth mismatch."""
    ga, gb = float(a.get("growth", 0)), float(b.get("growth", 0))
    if abs(ga - gb) > 1e-12:
        raise PodTraceError("cannot merge sketches with different growth "
                            "factors (%g vs %g)" % (ga, gb))
    buckets = {str(i): int(c) for i, c in (a.get("buckets") or {}).items()}
    for i, c in (b.get("buckets") or {}).items():
        buckets[str(i)] = buckets.get(str(i), 0) + int(c)
    return {"growth": ga, "zero": int(a.get("zero", 0)) + int(b.get("zero", 0)),
            "buckets": buckets}


def merge_sketches(dumps: List[dict]) -> Dict[str, dict]:
    """Per-family pod-wide sketches: associative fold of every host's
    serialized sketches (order-independent because bucket addition
    commutes — pinned together with the timeline algebra)."""
    out: Dict[str, dict] = {}
    for d in sorted(dumps, key=lambda d: d["label"]):
        for fam, sk in (d["header"].get("sketches") or {}).items():
            out[fam] = (merge_sketch_dicts(out[fam], sk)
                        if fam in out else merge_sketch_dicts(
                            sk, {"growth": sk.get("growth"), "zero": 0,
                                 "buckets": {}}))
    return out


def merged_quantile(sk: dict, q: float) -> Optional[float]:
    """Nearest-rank quantile of one serialized sketch."""
    return tracing.LatencySketch.from_dict(sk).quantile(q)


# ------------------------------------------------------------ derived reports

def skew_rows(dumps: List[dict]) -> Dict[int, Dict[str, Dict[str, float]]]:
    """``{iteration: {host: {phase: seconds}}}`` from train_iter events
    — the exact row shape ``elastic.skew_from_rows`` consumes, so the
    post-mortem verdict and the live StragglerTracker share one rule."""
    rows: Dict[int, Dict[str, Dict[str, float]]] = {}
    for d in dumps:
        for ev in d["events"]:
            if ev.get("kind") != "train_iter":
                continue
            phases = ev.get("phase_times") or {}
            rows.setdefault(int(ev.get("iter", -1)), {})[d["label"]] = {
                str(k): float(v) for k, v in phases.items()}
    return rows


def compute_wait(dumps: List[dict]) -> Dict[str, dict]:
    """Per-host compute vs collective-wait split per iteration:
    compute_s from train_iter phase seconds, collective_wait_s from the
    same iteration's collective_sync blocked windows."""
    out: Dict[str, dict] = {}
    for d in sorted(dumps, key=lambda d: d["label"]):
        iters: Dict[int, Dict[str, float]] = {}
        for ev in d["events"]:
            if ev.get("kind") == "train_iter":
                it = iters.setdefault(int(ev.get("iter", -1)),
                                      {"compute_s": 0.0,
                                       "collective_wait_s": 0.0})
                it["compute_s"] += float(
                    sum((ev.get("phase_times") or {}).values()))
            elif ev.get("kind") == "collective_sync":
                it = iters.setdefault(int(ev.get("iter", -1)),
                                      {"compute_s": 0.0,
                                       "collective_wait_s": 0.0})
                it["collective_wait_s"] += max(
                    float(ev.get("t1", 0)) - float(ev.get("t0", 0)), 0.0)
        out[d["label"]] = {
            "iterations": {k: {m: round(v, 6) for m, v in it.items()}
                           for k, it in sorted(iters.items())},
            "compute_s": round(sum(i["compute_s"]
                                   for i in iters.values()), 6),
            "collective_wait_s": round(sum(i["collective_wait_s"]
                                           for i in iters.values()), 6),
        }
    return out


def ingest_breakdown(dumps: List[dict]) -> Dict[str, dict]:
    """Per-host tokenizer/bin/H2D attribution summed over ingest_chunk
    events, with phase percentages, plus the coarse per-pass seconds."""
    out: Dict[str, dict] = {}
    for d in sorted(dumps, key=lambda d: d["label"]):
        tot = {"parse_us": 0.0, "bin_us": 0.0, "h2d_us": 0.0}
        chunks = rows = 0
        passes: Dict[int, dict] = {}
        for ev in d["events"]:
            if ev.get("kind") == "ingest_chunk":
                chunks += 1
                # rows counts ingested rows: pass-2 chunks only — the
                # pass-1 label/sample chunks cover the same rows again
                # and would double the count (phase sums stay all-pass)
                if int(ev.get("pass", 2)) == 2:
                    rows += int(ev.get("rows", 0))
                for k in tot:
                    tot[k] += float(ev.get(k, 0.0))
            elif ev.get("kind") == "ingest_pass":
                passes[int(ev.get("pass", -1))] = {
                    "seconds": float(ev.get("seconds", 0.0)),
                    "rows": int(ev.get("rows", 0))}
        if not chunks and not passes:
            continue
        total = sum(tot.values())
        out[d["label"]] = {
            "chunks": chunks, "rows": rows,
            **{k: round(v, 1) for k, v in tot.items()},
            "pcts": {k.replace("_us", "_pct"):
                     (round(100.0 * v / total, 2) if total > 0 else None)
                     for k, v in tot.items()},
            "passes": passes,
        }
    return out


def wire_model(dumps: List[dict],
               extra_sites: Optional[dict] = None) -> Dict[str, dict]:
    """Union per-site byte model from the dumps' ``wire_model`` events
    (largest est_bytes wins across hosts — same shape-superseding rule
    telemetry applies) plus caller-supplied ``extra_sites``
    ({site: est_bytes} or {site: {est_bytes, ...}})."""
    model: Dict[str, dict] = {}
    for d in dumps:
        for ev in d["events"]:
            if ev.get("kind") != "wire_model":
                continue
            for site, rec in (ev.get("sites") or {}).items():
                cur = model.get(site)
                if cur is None or int(rec.get("est_bytes", 0)) > \
                        int(cur.get("est_bytes", 0)):
                    model[site] = dict(rec)
    for site, rec in (extra_sites or {}).items():
        rec = rec if isinstance(rec, dict) else {"est_bytes": int(rec)}
        cur = model.get(site)
        if cur is None or int(rec.get("est_bytes", 0)) > \
                int(cur.get("est_bytes", 0)):
            model[site] = {**(cur or {}), **rec}
    return model


def seam_roofline(dumps: List[dict],
                  peaks: Optional[dict] = None,
                  extra_sites: Optional[dict] = None) -> dict:
    """Per-seam attained-vs-roofline table: measured collective_sync
    seconds joined against the per-site byte model; divided by the
    interconnect peak (``peaks['ici_bytes_per_sec']``, from
    costmodel.resolve_peaks) when one exists — ``frac_of_ici_peak`` is
    None on CPU/unknown chips rather than a made-up number.  Sites in
    the byte model without a measured span stay in the table (coverage
    is the contract) with null attained columns; measured sites MISSING
    from the model are flagged ``unmodeled`` — that's byte-model drift,
    pod_report --check fails on it."""
    model = wire_model(dumps, extra_sites)
    spans: Dict[str, dict] = {}
    for d in dumps:
        for ev in d["events"]:
            if ev.get("kind") != "collective_sync":
                continue
            site = str(ev.get("site"))
            rec = spans.setdefault(site, {"calls": 0, "span_s": 0.0})
            rec["calls"] += 1
            rec["span_s"] += max(float(ev.get("t1", 0))
                                 - float(ev.get("t0", 0)), 0.0)
    ici = None
    if peaks and peaks.get("ici_bytes_per_sec"):
        ici = float(peaks["ici_bytes_per_sec"])
    sites: Dict[str, dict] = {}
    unmodeled: List[str] = []
    for site in sorted(set(model) | set(spans)):
        m, sp = model.get(site), spans.get(site)
        row = {
            "est_bytes": int(m.get("est_bytes", 0)) if m else None,
            "kind": m.get("kind") if m else None,
            "calls": sp["calls"] if sp else 0,
            "span_s": round(sp["span_s"], 6) if sp else None,
            "attained_gb_per_s": None,
            "frac_of_ici_peak": None,
            "modeled": m is not None,
        }
        if m is None:
            unmodeled.append(site)
        elif sp and sp["span_s"] > 0:
            per_call = int(m.get("bytes_per_call",
                                 m.get("est_bytes", 0)))
            rate = per_call * sp["calls"] / sp["span_s"]
            row["attained_gb_per_s"] = round(rate / 1e9, 6)
            if ici:
                row["frac_of_ici_peak"] = round(rate / ici, 6)
        sites[site] = row
    return {"sites": sites, "unmodeled": unmodeled,
            "ici_bytes_per_sec": ici,
            "note": "logical payload bytes over host-blocked seconds; "
                    "fraction is a lower bound on link saturation"}


# ----------------------------------------------------------------- validation

# mirrors tracing.COMPONENTS — the merged-timeline identity re-check
_COMPONENTS = ("queue", "linger", "coalesce", "dispatch", "walk", "scatter")


def check(dumps: List[dict], alignment: Optional[dict] = None,
          merged: Optional[List[dict]] = None) -> List[str]:
    """Every pod-merge contract violation (empty list = clean):

    - header bookkeeping drift / run mixing (:func:`check_headers`);
    - alignment: a host with no pod-wide sync points, or estimates
      inconsistent beyond their recorded bounds;
    - the merged timeline: event conservation (merge drops/invents
      nothing) and the per-request sum(components)==wall identity on
      every merged serve_complete — a tampered per-host dump fails
      here even though its own header still parses."""
    bad = check_headers(dumps)
    if alignment is None:
        alignment = align(dumps)
    for lab, off in sorted(alignment["offsets"].items()):
        if off.get("offset_s") is None:
            bad.append("%s: no pod-wide collective_sync points match the "
                       "reference %s — clocks cannot be aligned"
                       % (lab, alignment["reference"]))
        elif not off.get("consistent", True):
            bad.append("%s: alignment estimates disagree beyond their "
                       "recorded collective-duration bounds "
                       "(offset=%ss bound=%ss over %d sync points)"
                       % (lab, off["offset_s"], off["bound_s"],
                          off["sync_points"]))
    if merged is None:
        merged = merge_timeline(dumps, alignment)
    want = sum(len(d["events"]) for d in dumps)
    if len(merged) != want:
        bad.append("merge conservation broken: %d input events -> %d "
                   "merged" % (want, len(merged)))
    for ev in merged:
        if ev.get("kind") != "serve_complete":
            continue
        comps, wall = ev.get("components_ns"), ev.get("wall_ns")
        if not isinstance(comps, dict) or not isinstance(wall, int):
            bad.append("%s: merged trace %s serve_complete missing "
                       "components_ns/wall_ns"
                       % (ev.get("_host"), ev.get("trace")))
            continue
        missing = [c for c in _COMPONENTS if c not in comps]
        if missing:
            bad.append("%s: merged trace %s missing component(s) %s"
                       % (ev.get("_host"), ev.get("trace"),
                          ",".join(missing)))
            continue
        total = sum(int(comps[c]) for c in _COMPONENTS)
        if total != wall:
            bad.append("%s: merged trace %s attribution identity broken: "
                       "sum(components)=%d != wall=%d"
                       % (ev.get("_host"), ev.get("trace"), total, wall))
    return bad


# ---------------------------------------------------------------- file barrier

def file_barrier(dirpath: str, name: str, index: int, count: int,
                 payload=None, timeout: float = 120.0,
                 poll: float = 0.002) -> Tuple[dict, float, float]:
    """Cross-process rendezvous over a shared directory.

    Each participant atomically publishes ``<name>.<index>`` (JSON
    ``payload``) and polls until all ``count`` files exist.  Everyone
    exits within one poll interval (plus read latency) of the LAST
    arrival — the same exit-window property a real blocking collective
    has — so feeding the returned ``(t0, t1)`` edges to
    ``tracing.record_collective_sync(..., pod=True)`` yields an HONEST
    alignment bound: ``max`` of the participants' blocked windows
    covers their exit-stamp spread.  Returns ``({index: payload}, t0,
    t1)``.  Raises TimeoutError when a peer never shows."""
    t0 = time.time()
    mine = os.path.join(dirpath, "%s.%d" % (name, int(index)))
    tmp = "%s.tmp-%d" % (mine, os.getpid())
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, mine)
    peers: Dict[int, object] = {}
    deadline = t0 + float(timeout)
    while len(peers) < int(count):
        for i in range(int(count)):
            if i in peers:
                continue
            p = os.path.join(dirpath, "%s.%d" % (name, i))
            try:
                with open(p) as f:
                    peers[i] = json.load(f)
            except (OSError, ValueError):
                pass  # not published yet (or mid-replace) — keep polling
        if len(peers) < int(count):
            if time.time() > deadline:
                raise TimeoutError(
                    "file_barrier %s: %d/%d peers after %.0fs"
                    % (name, len(peers), count, timeout))
            time.sleep(poll)
    return peers, t0, time.time()
