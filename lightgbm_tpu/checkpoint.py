"""Preemption-safe training checkpoints (ISSUE 14).

A checkpoint is ONE self-verifying file holding everything a ``task=train``
restart needs to continue **bit-identically** on the same topology: the
training-side tree arrays (the reference text format drops the inner
``split_feature``/``threshold_bin`` the binned score replay needs, so trees
are serialized in full — JSON floats round-trip f64 exactly via ``repr``),
the sampler/RNG counters (``gbdt._bag_snapshot`` state: the device draw
counter, or the host MT19937 state + current mask), the iteration count,
the ``best_score``/``best_iter`` early-stopping state, the raw f32
train/valid score arrays (TRUE rows only — the per-topology padding is
rebuilt at restore, which is what makes the file topology-independent: an
elastic restart re-runs ``factor_machines`` on the surviving machine
count and re-lifts the stored rows onto the new layout), and a config
fingerprint compared FIELD BY FIELD on load (a mismatch is rejected
loudly, naming the field).  Scores must be STORED, not replayed: the
host-side tree replay recomputes the shrunk leaf values through an f64
learning-rate product (``0.1`` is not f32-representable, so the f64 and
f32 products round differently) and lands 1 ulp off the in-grow f32
update — fine for the rollback paths whose both sides share it, fatal
for a bit-identical restore.

File format (atomicity + truncation/corruption detection)::

    lightgbm_tpu_checkpoint v1 sha256=<hex> bytes=<payload-len>\\n
    <payload JSON, exactly bytes long>

Writes go to a temp file in the same directory, fsync, then one
``os.replace`` — a crash mid-write leaves the previous checkpoint loadable
and at worst a stray ``.tmp-*`` file the loader ignores.  ``load``
verifies the payload length (a short read names the truncation), the
sha256 (corruption), and then every required field (a missing/mistyped
field is named in the error).

``CheckpointWriter`` is the asynchronous path ``run_training`` uses: the
hot loop enqueues a cheap raw snapshot (list copy + RNG ``get_state``)
and a background thread serializes + writes it, so checkpointing rides
OFF the pipelined readback path.  The queue holds ONE pending snapshot
(latest wins — a slow disk can never stall training; replaced snapshots
count ``ckpt/dropped``).  Live writers are registered module-globally so
the test-suite leak guard can fail a test that leaves a writer thread
running (tests/conftest.py).
"""
from __future__ import annotations

import base64
import hashlib
import json
import os
import re
import threading
import time
from typing import List, Optional

import numpy as np

from . import lifecycle, telemetry, tracing
from .utils import log

MAGIC = "lightgbm_tpu_checkpoint"
VERSION = 1
_HEADER_RE = re.compile(
    r"^lightgbm_tpu_checkpoint v(\d+) sha256=([0-9a-f]{64}) bytes=(\d+)\n")
_CKPT_NAME_RE = re.compile(r"^ckpt-(\d{8})\.json$")

# the shared lifecycle inventory's kind tag for async writers: the
# conftest leak guard (and graftlint C1) consume lifecycle.py's single
# registry instead of a per-module set (ISSUE 15)
WRITER_KIND = "ckpt-writer"


class CheckpointError(Exception):
    """A checkpoint file that must not be restored: truncated, corrupt,
    malformed, or config-mismatched.  The message names the failing
    field/section precisely."""


def live_writers() -> int:
    """Number of CheckpointWriter threads still registered live (the
    lifecycle inventory view; kept as the module's historical API)."""
    return lifecycle.live_count(WRITER_KIND)


# ---------------------------------------------------------- serialization

def _rng_state_to_json(state) -> dict:
    """numpy RandomState.get_state() tuple -> JSON-safe dict."""
    alg, keys, pos, has_gauss, cached = state
    return {"alg": str(alg), "keys": np.asarray(keys, np.uint32).tolist(),
            "pos": int(pos), "has_gauss": int(has_gauss),
            "cached_gaussian": float(cached)}


def _rng_state_from_json(obj):
    return (obj["alg"], np.asarray(obj["keys"], np.uint32), int(obj["pos"]),
            int(obj["has_gauss"]), float(obj["cached_gaussian"]))


def _mask_to_json(mask: np.ndarray) -> dict:
    packed = np.packbits(np.asarray(mask, bool))
    return {"n": int(np.asarray(mask).size),
            "bits": base64.b64encode(packed.tobytes()).decode("ascii")}


def _mask_from_json(obj) -> np.ndarray:
    packed = np.frombuffer(base64.b64decode(obj["bits"]), np.uint8)
    return np.unpackbits(packed)[:int(obj["n"])].astype(bool)


def array_to_json(arr) -> dict:
    """Raw little-endian f32 bytes, base64 — bit-exact, no text-float
    round trip on the score arrays."""
    arr = np.ascontiguousarray(np.asarray(arr, np.float32))
    return {"shape": list(arr.shape), "dtype": "float32",
            "data": base64.b64encode(arr.tobytes()).decode("ascii")}


def array_from_json(obj) -> np.ndarray:
    arr = np.frombuffer(base64.b64decode(obj["data"]), np.float32)
    return arr.reshape(obj["shape"]).copy()


def bag_snapshot_to_json(snap) -> Optional[dict]:
    """``gbdt._bag_snapshot`` -> JSON.  The device stream's whole state is
    the draw counter (the current mask is a pure function of it); the
    host stream is the MT19937 state + the current mask."""
    if snap is None:
        return None
    if snap[0] == "device":
        return {"mode": "device", "draw_idx": int(snap[1])}
    _, state, mask, _mask_dev = snap
    return {"mode": "host", "state": _rng_state_to_json(state),
            "mask": _mask_to_json(mask)}


def tree_to_json(tree) -> dict:
    """Full TRAINING-SIDE tree arrays.  ``Tree.from_string`` reconstructs
    only the reference surface (inner split_feature and threshold_bin are
    dropped), but the binned score replay needs exactly those — so
    checkpoints carry every array.  JSON floats are written with
    ``repr``-shortest precision and round-trip f64 bitwise."""
    return {
        "num_leaves": int(tree.num_leaves),
        "split_feature": tree.split_feature.tolist(),
        "split_feature_real": tree.split_feature_real.tolist(),
        "threshold_bin": tree.threshold_bin.tolist(),
        "threshold": tree.threshold.tolist(),
        "split_gain": tree.split_gain.tolist(),
        "left_child": tree.left_child.tolist(),
        "right_child": tree.right_child.tolist(),
        "leaf_parent": tree.leaf_parent.tolist(),
        "leaf_value": tree.leaf_value.tolist(),
    }


def tree_from_json(obj) -> "object":
    from .models.tree import Tree
    return Tree(
        num_leaves=int(obj["num_leaves"]),
        split_feature=np.asarray(obj["split_feature"], np.int32),
        split_feature_real=np.asarray(obj["split_feature_real"], np.int32),
        threshold_bin=np.asarray(obj["threshold_bin"], np.int32),
        threshold=np.asarray(obj["threshold"], np.float64),
        split_gain=np.asarray(obj["split_gain"], np.float64),
        left_child=np.asarray(obj["left_child"], np.int32),
        right_child=np.asarray(obj["right_child"], np.int32),
        leaf_parent=np.asarray(obj["leaf_parent"], np.int32),
        leaf_value=np.asarray(obj["leaf_value"], np.float64),
    )


def serialize_state(raw: dict) -> dict:
    """Raw booster snapshot (``GBDT.checkpoint_state``: live Tree refs +
    RNG state tuples) -> the JSON-safe checkpoint payload.  Runs on the
    writer THREAD in the async path — tree serialization is O(trees) and
    must never ride the hot loop."""
    bag, ff = raw["rng"]
    return {
        "magic": MAGIC,
        "version": VERSION,
        "iteration": int(raw["iteration"]),
        "num_class": int(raw["num_class"]),
        "trees": [tree_to_json(t) for t in raw["models"]],
        "best_score": [list(map(float, row)) for row in raw["best_score"]],
        "best_iter": [list(map(int, row)) for row in raw["best_iter"]],
        "rng": {
            "bagging": bag_snapshot_to_json(bag),
            "feature_fraction": ([_rng_state_to_json(s) for s in ff]
                                 if ff is not None else None),
        },
        # score arrays materialize HERE — on the writer thread in the
        # async path (np.asarray on an already-computed device array;
        # the hot loop only passed references)
        "score": array_to_json(raw["score"]),
        "valid_scores": [array_to_json(s) for s in raw["valid_scores"]],
        "config": dict(raw["config"]),
        "dataset": dict(raw["dataset"]),
        "topology": dict(raw["topology"]),
        "wall_time": time.time(),
    }


# --------------------------------------------------------------- file I/O

def checkpoint_path(directory: str, iteration: int) -> str:
    return os.path.join(directory, "ckpt-%08d.json" % iteration)


def list_checkpoints(directory: str) -> List[str]:
    """Finished checkpoint files in the directory, oldest first.  Stray
    ``.tmp-*`` files (a killed writer) are ignored by construction."""
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    found = []
    for name in names:
        m = _CKPT_NAME_RE.match(name)
        if m:
            found.append((int(m.group(1)), os.path.join(directory, name)))
    return [p for _, p in sorted(found)]


def latest_checkpoint(directory: str) -> Optional[str]:
    paths = list_checkpoints(directory)
    return paths[-1] if paths else None


def write_checkpoint(directory: str, payload: dict,
                     keep: int = 2) -> str:
    """Atomic write: temp file in the SAME directory + fsync +
    ``os.replace``.  A crash at any point leaves the previous checkpoint
    loadable.  Prunes to the newest ``keep`` finished files after the
    rename (the new file counts)."""
    os.makedirs(directory, exist_ok=True)
    body = json.dumps(payload).encode("utf-8")
    header = ("%s v%d sha256=%s bytes=%d\n"
              % (MAGIC, VERSION, hashlib.sha256(body).hexdigest(),
                 len(body))).encode("ascii")
    final = checkpoint_path(directory, int(payload["iteration"]))
    tmp = os.path.join(directory,
                       ".tmp-%d-%d" % (os.getpid(), threading.get_ident()))
    with open(tmp, "wb") as f:
        f.write(header)
        f.write(body)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, final)
    telemetry.count("ckpt/written")
    if tracing.active():
        tracing.event("ckpt_write", iter=int(payload["iteration"]),
                      bytes=len(body))
    if keep >= 1:
        for old in list_checkpoints(directory)[:-keep]:
            try:
                os.unlink(old)
                telemetry.count("ckpt/pruned")
            except OSError:
                pass
    return final


def _require(payload: dict, field: str, typ, what: str = "checkpoint"):
    if field not in payload:
        raise CheckpointError(
            "%s field '%s' is missing" % (what, field))
    v = payload[field]
    if not isinstance(v, typ):
        raise CheckpointError(
            "%s field '%s' has the wrong type (%s, expected %s)"
            % (what, field, type(v).__name__,
               getattr(typ, "__name__", str(typ))))
    return v


def load_checkpoint(path: str) -> dict:
    """Read + verify one checkpoint file.  Raises CheckpointError naming
    exactly what is wrong: header, truncation (with byte counts), sha256
    corruption, or the first missing/mistyped payload field."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError as e:
        raise CheckpointError("%s: unreadable (%s)" % (path, e))
    nl = data.find(b"\n")
    if nl < 0:
        raise CheckpointError(
            "%s: truncated before the end of the header line" % path)
    m = _HEADER_RE.match(data[:nl + 1].decode("ascii", "replace"))
    if m is None:
        raise CheckpointError(
            "%s: not a %s file (bad header line)" % (path, MAGIC))
    version, digest, nbytes = int(m.group(1)), m.group(2), int(m.group(3))
    if version != VERSION:
        raise CheckpointError(
            "%s: checkpoint version %d unsupported (this build reads v%d)"
            % (path, version, VERSION))
    body = data[nl + 1:]
    if len(body) != nbytes:
        raise CheckpointError(
            "%s: truncated payload — %d of %d declared bytes present"
            % (path, len(body), nbytes))
    if hashlib.sha256(body).hexdigest() != digest:
        raise CheckpointError(
            "%s: payload sha256 mismatch (corrupt checkpoint)" % path)
    try:
        payload = json.loads(body.decode("utf-8"))
    except ValueError as e:
        raise CheckpointError("%s: payload is not valid JSON (%s)"
                              % (path, e))
    if not isinstance(payload, dict):
        raise CheckpointError("%s: payload is not a JSON object" % path)
    if payload.get("magic") != MAGIC:
        raise CheckpointError(
            "checkpoint field 'magic' is missing or wrong")
    _require(payload, "iteration", int)
    _require(payload, "num_class", int)
    _require(payload, "trees", list)
    _require(payload, "best_score", list)
    _require(payload, "best_iter", list)
    rng = _require(payload, "rng", dict)
    if "bagging" not in rng or "feature_fraction" not in rng:
        raise CheckpointError(
            "checkpoint field 'rng' is missing its "
            "'bagging'/'feature_fraction' entries")
    _require(payload, "config", dict)
    _require(payload, "dataset", dict)
    _require(payload, "topology", dict)
    score = _require(payload, "score", dict)
    if "shape" not in score or "data" not in score:
        raise CheckpointError(
            "checkpoint field 'score' is missing its 'shape'/'data' "
            "entries")
    _require(payload, "valid_scores", list)
    for i, t in enumerate(payload["trees"]):
        if not isinstance(t, dict) or "num_leaves" not in t:
            raise CheckpointError(
                "checkpoint field 'trees[%d]' is not a serialized tree"
                % i)
    return payload


def check_fingerprint(payload: dict, config: dict, dataset: dict) -> None:
    """Field-by-field comparison of the checkpoint's semantic config and
    dataset fingerprints against the restoring run's.  Topology fields
    (num_machines, tree_learner, ...) are deliberately NOT here — an
    elastic restart changes them by design; the semantic fields decide
    whether continuing the boost is even meaningful."""
    for section, want in (("config", config), ("dataset", dataset)):
        have = payload[section]
        for field in sorted(set(want) | set(have)):
            if field not in have:
                raise CheckpointError(
                    "checkpoint %s field '%s' is missing (written by an "
                    "older build?)" % (section, field))
            if field not in want:
                # a newer writer recorded a field this build doesn't
                # know; refusing would break forward compat for no
                # semantic reason
                continue
            if have[field] != want[field]:
                raise CheckpointError(
                    "checkpoint %s field '%s' mismatch: checkpoint has "
                    "%r, this run has %r — refusing to continue a "
                    "different training run" % (section, field,
                                                have[field], want[field]))


# ---------------------------------------------------------- async writer

class CheckpointWriter:
    """Background checkpoint writer: ``submit(raw_state)`` replaces the
    single pending slot and returns immediately; the thread serializes
    and writes atomically.  ``write_sync`` serializes + writes on the
    calling thread (final checkpoint / elastic drain).  ``close`` drains
    the pending slot and joins the thread — always call it (the conftest
    leak guard fails tests that leave a writer alive)."""

    def __init__(self, directory: str, keep: int = 2):
        self.directory = directory
        self.keep = max(int(keep), 1)
        self._cv = threading.Condition()
        self._pending: Optional[dict] = None
        self._closing = False
        self._error: Optional[BaseException] = None
        self.written = 0
        self.dropped = 0
        self._thread = threading.Thread(
            target=self._run, name="lgbm-tpu-ckpt-writer", daemon=True)
        lifecycle.track(WRITER_KIND, self, self.close)
        self._thread.start()

    def submit(self, raw_state: dict) -> None:
        """Enqueue a raw snapshot (latest wins; never blocks)."""
        with self._cv:
            if self._closing:
                raise RuntimeError("CheckpointWriter is closed")
            if self._pending is not None:
                self.dropped += 1
                telemetry.count("ckpt/dropped")
                if tracing.active():
                    tracing.event("ckpt_drop")
            self._pending = raw_state
            telemetry.count("ckpt/snapshots")
            self._cv.notify()

    def write_sync(self, raw_state: dict) -> str:
        """Serialize + write on the calling thread (the final checkpoint
        at loop end, and the elastic-shrink drain point)."""
        path = write_checkpoint(self.directory, serialize_state(raw_state),
                                keep=self.keep)
        self.written += 1
        return path

    def _run(self) -> None:
        while True:
            with self._cv:
                while self._pending is None and not self._closing:
                    self._cv.wait()
                raw, self._pending = self._pending, None
                if raw is None and self._closing:
                    return
            try:
                t0 = time.perf_counter()
                write_checkpoint(self.directory, serialize_state(raw),
                                 keep=self.keep)
                self.written += 1
                telemetry.count("ckpt/async_write_us",
                                int(1e6 * (time.perf_counter() - t0)))
            except BaseException as e:  # pragma: no cover - disk trouble
                self._error = e
                log.warning("async checkpoint write failed: %s" % e)

    def close(self, join_s: float = 10.0) -> None:
        with self._cv:
            self._closing = True
            self._cv.notify()
        self._thread.join(join_s)
        if self._thread.is_alive():
            # a writer wedged on a hung disk stays REGISTERED: the leak
            # guard exists precisely to surface a thread that outlives
            # its training run — deregistering it here would hide that
            log.warning("checkpoint writer thread did not exit within "
                        "%.1fs (hung write?); leaving it registered for "
                        "the leak guard" % join_s)
        else:
            lifecycle.untrack(self)
        if self._error is not None:
            log.warning("checkpoint writer had failed earlier: %s"
                        % self._error)

    @property
    def alive(self) -> bool:
        return self._thread.is_alive()
