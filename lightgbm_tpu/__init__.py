"""lightgbm_tpu — a TPU-native gradient-boosted-decision-tree framework.

Brand-new JAX/XLA re-design of early LightGBM (reference at
/root/reference): histogram-based leaf-wise GBDT with serial,
feature-parallel and data-parallel tree learning — the compute path is
jitted XLA programs over a dense ``[features, rows]`` bin matrix in HBM, and
distribution is ``shard_map`` over a ``jax.sharding.Mesh`` with XLA
collectives instead of sockets/MPI.

Public surface:
- CLI: ``python -m lightgbm_tpu task=train config=train.conf`` (the
  reference's ``lightgbm`` executable surface; examples/ configs run
  unchanged).
- Python API: :class:`Dataset`, :func:`train`, :class:`GBDT`.
"""
from __future__ import annotations

import os

__version__ = "0.1.0"

# Exec'd parallel-parse workers (io/parallel_ingest.py) import this
# package but touch only the numpy parse stack: skip the JAX surface so
# worker startup is milliseconds, not a backend import.
_INGEST_WORKER = os.environ.get("LIGHTGBM_TPU_INGEST_WORKER") == "1"

# Persistent XLA compilation cache: the unrolled tree-grower programs take
# minutes to compile; caching makes every process after the first start hot.
# TPU-only — CPU AOT artifacts are host-feature-specific and a cache shared
# across heterogeneous hosts can SIGILL.
if not _INGEST_WORKER:
    try:  # pragma: no cover - environment dependent
        import jax

        if (jax.config.jax_compilation_cache_dir is None
                and "cpu" not in os.environ.get("JAX_PLATFORMS",
                                                "").lower()):
            jax.config.update(
                "jax_compilation_cache_dir",
                os.environ.get(
                    "LIGHTGBM_TPU_CACHE",
                    os.path.expanduser("~/.cache/lightgbm_tpu_xla")))
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass

    from . import telemetry
    from .config import OverallConfig, load_config
    from .io.dataset import Dataset
    from .models.gbdt import GBDT
    from .models.tree import Tree


def train(params: dict, train_set: Dataset, valid_sets=(), valid_names=None):
    """Convenience training entry for library users.

    ``params`` uses the reference's key=value names (aliases applied).
    """
    from .config import OverallConfig
    from .metrics import create_metric
    from .objectives import create_objective

    config = OverallConfig()
    config.set({k: str(v) for k, v in params.items()}, require_data=False)
    io = config.io_config
    mem_on = io.memory_stats_enabled()
    armed_telemetry = bool(io.metrics_out) or mem_on
    if armed_telemetry:
        telemetry.enable(io.metrics_out or None,
                         fence=io.metrics_fence, memory=mem_on)
        # fresh registry per armed run: a second train() in the same
        # process must not ship the first run's counters in its records
        telemetry.reset()
    booster = GBDT()
    objective = create_objective(config.objective_type,
                                 config.objective_config)
    train_metrics = []
    if config.boosting_config.is_provide_training_metric:
        train_metrics = [m for m in
                         (create_metric(t, config.metric_config)
                          for t in config.metric_types) if m is not None]
    learner = None
    if config.boosting_config.tree_learner != "serial":
        from .parallel import create_parallel_learner
        learner = create_parallel_learner(config)
    booster.init(config.boosting_config, train_set, objective, train_metrics,
                 learner=learner)
    for i, valid in enumerate(valid_sets):
        name = (valid_names[i] if valid_names else f"valid_{i + 1}")
        metrics = [m for m in (create_metric(t, config.metric_config)
                               for t in config.metric_types) if m is not None]
        booster.add_valid_dataset(valid, metrics, name=name)
    is_eval = bool(train_metrics) or bool(valid_sets)
    try:
        booster.run_training(config.boosting_config.num_iterations, is_eval)
    finally:
        if armed_telemetry:
            # this call armed the sink, so it closes it: a later train()
            # without metrics_out must not append records (and a later
            # fence-free run must not inherit fence mode).  snapshot()
            # still serves the accumulated data after disable
            telemetry.disable()
    return booster
