"""Evaluation metrics.

Re-design of /root/reference/src/metric/ as NumPy evaluators (metrics run
once per iteration on host-resident score vectors).  Factory mirrors
metric.cpp:9-28; display names and Eval semantics match the reference
(weighted means, L2 reported as RMSE, AUC tie handling, NDCG per-k with
all-negative queries scoring 1.0).
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..utils import log
from .dcg import DCGCalculator


class Metric:
    name: str = ""
    is_bigger_better: bool = False

    def init(self, test_name: str, metadata, num_data: int) -> None:
        raise NotImplementedError

    def eval(self, score: np.ndarray) -> List[float]:
        raise NotImplementedError

    def device_spec(self):
        """(key, params, fn) for in-program evaluation (metrics/device.py),
        or None when this metric has no device formulation."""
        return None

    def n_values(self) -> int:
        """Number of values eval()/device fn produce (NDCG: one per k)."""
        return 1


class _PointwiseMetric(Metric):
    """Weighted-mean pointwise losses (regression_metric.hpp:16-121,
    binary_metric.hpp:18-141, multiclass_metric.hpp:16-135)."""
    loss_name = ""

    def __init__(self, config):
        self.config = config
        self.weights = None

    def init(self, test_name, metadata, num_data):
        self.name = f"{test_name}'s {self.loss_name}"
        self.num_data = num_data
        self.label = np.asarray(metadata.label)
        self.weights = (np.asarray(metadata.weights)
                        if metadata.weights is not None else None)
        self.sum_weights = (float(self.weights.sum())
                            if self.weights is not None else float(num_data))

    def eval(self, score):
        loss = self._point_loss(score)
        if self.weights is not None:
            loss = loss * self.weights
        return [self._transform(float(loss.sum()) / self.sum_weights)]

    def _transform(self, mean_loss: float) -> float:
        return mean_loss

    def _point_loss(self, score):
        raise NotImplementedError

    def _device_params(self):
        import jax.numpy as jnp
        return {"label": jnp.asarray(self.label, jnp.float32),
                "weights": (jnp.asarray(self.weights, jnp.float32)
                            if self.weights is not None else None),
                "sum_weights": jnp.float32(self.sum_weights)}


class L2Metric(_PointwiseMetric):
    loss_name = "l2 loss"

    def _point_loss(self, score):
        d = score - self.label
        return d * d

    def _transform(self, mean_loss):
        # L2 metric reports RMSE (regression_metric.hpp:100-103)
        return float(np.sqrt(mean_loss))

    def device_spec(self):
        from . import device
        return (("l2", self.weights is not None), self._device_params(),
                device.l2_metric)


class L1Metric(_PointwiseMetric):
    loss_name = "l1 loss"

    def _point_loss(self, score):
        return np.abs(score - self.label)

    def device_spec(self):
        from . import device
        return (("l1", self.weights is not None), self._device_params(),
                device.l1_metric)


class _BinaryMetric(_PointwiseMetric):
    def __init__(self, config):
        super().__init__(config)
        self.sigmoid = float(config.sigmoid)
        if self.sigmoid <= 0.0:
            log.fatal("Sigmoid param %f should greater than zero" % self.sigmoid)

    def _prob(self, score):
        return 1.0 / (1.0 + np.exp(-2.0 * self.sigmoid * score))


class BinaryLoglossMetric(_BinaryMetric):
    loss_name = "log loss"

    def _point_loss(self, score):
        prob = self._prob(score)
        # LossOnPoint (binary_metric.hpp:105-126): -log(p) label-sided
        eps = 1e-15
        prob = np.clip(prob, eps, 1 - eps)
        return np.where(self.label == 1, -np.log(prob), -np.log(1.0 - prob))

    def device_spec(self):
        import jax.numpy as jnp
        from . import device
        params = self._device_params()
        params["sigmoid"] = jnp.float32(self.sigmoid)
        return (("binary_logloss", self.weights is not None), params,
                device.binary_logloss_metric)


class BinaryErrorMetric(_BinaryMetric):
    loss_name = "error rate"

    def _point_loss(self, score):
        prob = self._prob(score)
        # error rate (binary_metric.hpp:131-141): prob>0.5 predicted positive
        pred_pos = prob > 0.5
        return np.where(pred_pos == (self.label == 1), 0.0, 1.0)

    def device_spec(self):
        import jax.numpy as jnp
        from . import device
        params = self._device_params()
        params["sigmoid"] = jnp.float32(self.sigmoid)
        return (("binary_error", self.weights is not None), params,
                device.binary_error_metric)


class AUCMetric(Metric):
    """AUC with tie handling (binary_metric.hpp:146-254)."""
    is_bigger_better = True

    def __init__(self, config):
        self.weights = None

    def init(self, test_name, metadata, num_data):
        self.name = f"{test_name}'s AUC"
        self.num_data = num_data
        self.label = np.asarray(metadata.label)
        self.weights = (np.asarray(metadata.weights)
                        if metadata.weights is not None else None)
        self.sum_weights = (float(self.weights.sum())
                            if self.weights is not None else float(num_data))

    def eval(self, score):
        score = np.asarray(score)
        label = self.label
        w = self.weights if self.weights is not None else np.ones_like(label)
        order = np.argsort(-score, kind="stable")
        s, l, wt = score[order], label[order], w[order]
        pos = l * wt
        neg = (1.0 - l) * wt
        # group ties: boundaries where score changes
        change = np.nonzero(s[1:] != s[:-1])[0] + 1
        starts = np.concatenate(([0], change))
        grp_pos = np.add.reduceat(pos, starts)
        grp_neg = np.add.reduceat(neg, starts)
        pos_before = np.cumsum(grp_pos) - grp_pos
        accum = float(np.sum(grp_neg * (grp_pos * 0.5 + pos_before)))
        sum_pos = float(grp_pos.sum())
        auc = 1.0
        if sum_pos > 0.0 and sum_pos != self.sum_weights:
            auc = accum / (sum_pos * (self.sum_weights - sum_pos))
        return [auc]

    def device_spec(self):
        import jax.numpy as jnp
        from . import device
        params = {"label": jnp.asarray(self.label, jnp.float32),
                  "weights": (jnp.asarray(self.weights, jnp.float32)
                              if self.weights is not None else None),
                  "sum_weights": jnp.float32(self.sum_weights)}
        return (("auc", self.weights is not None), params,
                device.auc_metric)


class _MulticlassMetric(Metric):
    """Score layout [K, N] flattened row-major like the reference's
    score[k * num_data + i] (multiclass_metric.hpp:49-94)."""

    def __init__(self, config):
        self.num_class = int(config.num_class)
        self.weights = None

    def _device_params(self):
        import jax.numpy as jnp
        return {"label": jnp.asarray(self.label, jnp.int32),
                "weights": (jnp.asarray(self.weights, jnp.float32)
                            if self.weights is not None else None),
                "sum_weights": jnp.float32(self.sum_weights)}

    def init(self, test_name, metadata, num_data):
        self.name = f"{test_name}'s {self.loss_name}"
        self.num_data = num_data
        self.label = np.asarray(metadata.label).astype(np.int64)
        self.weights = (np.asarray(metadata.weights)
                        if metadata.weights is not None else None)
        self.sum_weights = (float(self.weights.sum())
                            if self.weights is not None else float(num_data))

    def eval(self, score):
        score = np.asarray(score).reshape(self.num_class, self.num_data)
        loss = self._point_loss(score)
        if self.weights is not None:
            loss = loss * self.weights
        return [float(loss.sum()) / self.sum_weights]


class MultiErrorMetric(_MulticlassMetric):
    loss_name = "multi error"

    def _point_loss(self, score):
        pred = np.argmax(score, axis=0)
        return np.where(pred == self.label, 0.0, 1.0)

    def device_spec(self):
        from . import device
        return (("multi_error", self.num_class,
                 self.weights is not None), self._device_params(),
                device.multi_error_metric)


class MultiLoglossMetric(_MulticlassMetric):
    loss_name = "multi logloss"

    def _point_loss(self, score):
        z = score - score.max(axis=0, keepdims=True)
        p = np.exp(z)
        p = p / p.sum(axis=0, keepdims=True)
        eps = 1e-15
        picked = np.clip(p[self.label, np.arange(self.num_data)], eps, 1.0)
        return -np.log(picked)

    def device_spec(self):
        from . import device
        return (("multi_logloss", self.num_class,
                 self.weights is not None), self._device_params(),
                device.multi_logloss_metric)


class NDCGMetric(Metric):
    """NDCG@ks (rank_metric.hpp:16-167)."""
    is_bigger_better = True

    def __init__(self, config):
        self.eval_at = list(config.eval_at)
        self.dcg = DCGCalculator(config.label_gain)

    def n_values(self) -> int:
        return len(self.eval_at)

    def init(self, test_name, metadata, num_data):
        self.name = (f"{test_name}'s "
                     + " ".join(f"NDCG@{k}" for k in self.eval_at))
        self.num_data = num_data
        self.label = np.asarray(metadata.label)
        if metadata.query_boundaries is None:
            log.fatal("For NDCG metric, there should be query information")
        self.boundaries = np.asarray(metadata.query_boundaries)
        self.query_weights = metadata.query_weights
        nq = self.boundaries.size - 1
        self.sum_query_weights = (float(np.sum(self.query_weights))
                                  if self.query_weights is not None
                                  else float(nq))
        # cache inverse max DCG per query; ≤0 ⇒ all-negative query → NDCG 1
        self.inv_max = []
        for q in range(nq):
            lo, hi = self.boundaries[q], self.boundaries[q + 1]
            maxes = self.dcg.cal_max_dcg(self.eval_at, self.label[lo:hi])
            self.inv_max.append([1.0 / m if m > 0 else -1.0 for m in maxes])

    def eval(self, score):
        score = np.asarray(score)
        nq = self.boundaries.size - 1
        result = np.zeros(len(self.eval_at))
        for q in range(nq):
            lo, hi = self.boundaries[q], self.boundaries[q + 1]
            w = (float(self.query_weights[q])
                 if self.query_weights is not None else 1.0)
            if self.inv_max[q][0] <= 0.0:
                # all-negative query counts as 1.0 even when weighted —
                # reference quirk (rank_metric.hpp:98-101, 120-124)
                result += 1.0
                continue
            dcgs = self.dcg.cal_dcg(self.eval_at, self.label[lo:hi],
                                    score[lo:hi])
            for j, d in enumerate(dcgs):
                result[j] += d * self.inv_max[q][j] * w
        return [float(r / self.sum_query_weights) for r in result]

    def device_spec(self):
        import jax.numpy as jnp
        from . import device
        nq = self.boundaries.size - 1
        qmax = int(np.diff(self.boundaries).max())
        doc_index = np.zeros((nq, qmax), dtype=np.int32)
        valid = np.zeros((nq, qmax), dtype=bool)
        labels = np.zeros((nq, qmax), dtype=np.int32)
        for q in range(nq):
            lo, hi = self.boundaries[q], self.boundaries[q + 1]
            m = hi - lo
            doc_index[q, :m] = np.arange(lo, hi)
            valid[q, :m] = True
            labels[q, :m] = self.label[lo:hi].astype(np.int32)
        block = max(1, min(nq, (1 << 22) // max(qmax, 1)))
        params = {
            "doc_index": jnp.asarray(doc_index),
            "valid": jnp.asarray(valid),
            "labels": jnp.asarray(labels),
            "inv_max": jnp.asarray(np.asarray(self.inv_max, np.float32)),
            "gains": jnp.asarray(self.dcg.label_gain, jnp.float32),
            "discount": jnp.asarray(self.dcg.discount[:qmax], jnp.float32),
            "query_weights": (jnp.asarray(self.query_weights, jnp.float32)
                              if self.query_weights is not None else None),
            "sum_query_weights": jnp.float32(self.sum_query_weights),
        }
        ks = tuple(int(k) for k in self.eval_at)
        return (("ndcg", ks, block, self.query_weights is not None),
                params, device.ndcg_fn(ks, block))


def create_metric(metric_type: str, config) -> Optional[Metric]:
    """CreateMetric (metric.cpp:9-28)."""
    if metric_type == "l2":
        return L2Metric(config)
    if metric_type == "l1":
        return L1Metric(config)
    if metric_type == "auc":
        return AUCMetric(config)
    if metric_type == "binary_logloss":
        return BinaryLoglossMetric(config)
    if metric_type == "binary_error":
        return BinaryErrorMetric(config)
    if metric_type == "ndcg":
        return NDCGMetric(config)
    if metric_type == "multi_logloss":
        return MultiLoglossMetric(config)
    if metric_type == "multi_error":
        return MultiErrorMetric(config)
    return None
