"""Device-side (jit-traceable) metric evaluation.

The reference evaluates metrics on CPU threads over host score vectors
(src/metric/*.hpp with OpenMP).  Here every metric also has a pure-JAX
formulation so evaluation can run INSIDE the fused multi-iteration training
program (models/gbdt.py train_chunk): scores never leave the device and the
CLI's metric-every-iteration cadence costs no extra host round-trips.

Each host metric class (metrics/__init__.py) exposes ``device_spec()``
returning ``(key, params, fn)``:
- ``fn(params, score) -> [n_out] f32`` is a module-level pure function
  (no per-dataset constants), so compiled programs are shared across
  boosters/datasets of the same shape;
- ``params`` is a pytree of device arrays (labels, weights, query tables);
- ``key`` is hashable and pins fn's static behavior for program caching.

``score`` is `[N]` for single-class metrics and `[num_class, N]` for the
multiclass ones (the device layout; no reference-style flattening).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


# ---------------------------------------------------------------- pointwise

def _weighted_mean(loss, weights, sum_weights):
    if weights is not None:
        loss = loss * weights
    return jnp.sum(loss) / sum_weights


def l2_metric(params, score):
    d = score.astype(jnp.float32) - params["label"]
    mean = _weighted_mean(d * d, params["weights"], params["sum_weights"])
    return jnp.sqrt(mean)[None]          # L2 reports RMSE


def l1_metric(params, score):
    d = jnp.abs(score.astype(jnp.float32) - params["label"])
    return _weighted_mean(d, params["weights"], params["sum_weights"])[None]


def _binary_prob(params, score):
    return 1.0 / (1.0 + jnp.exp(-2.0 * params["sigmoid"]
                                * score.astype(jnp.float32)))


# host metric clips prob to [1e-15, 1-1e-15] (in double); the matching
# loss ceiling, applied in the log domain where f32 can express it
# (1 - 1e-15 rounds to 1.0 in f32, which would send -log(1-p) to inf)
_MAX_LOG_LOSS = 34.538776394910684   # -log(1e-15)


def binary_logloss_metric(params, score):
    x = 2.0 * params["sigmoid"] * score.astype(jnp.float32)
    # -log(sigmoid(x)) = softplus(-x); -log(1 - sigmoid(x)) = softplus(x)
    loss = jnp.where(params["label"] == 1, jax.nn.softplus(-x),
                     jax.nn.softplus(x))
    loss = jnp.minimum(loss, _MAX_LOG_LOSS)
    return _weighted_mean(loss, params["weights"],
                          params["sum_weights"])[None]


def binary_error_metric(params, score):
    pred_pos = _binary_prob(params, score) > 0.5
    loss = jnp.where(pred_pos == (params["label"] == 1), 0.0, 1.0)
    return _weighted_mean(loss, params["weights"],
                          params["sum_weights"])[None]


def multi_logloss_metric(params, score):
    p = jax.nn.softmax(score.astype(jnp.float32), axis=0)      # [K, N]
    n = score.shape[1]
    picked = jnp.clip(p[params["label"], jnp.arange(n)], 1e-15, 1.0)
    return _weighted_mean(-jnp.log(picked), params["weights"],
                          params["sum_weights"])[None]


def multi_error_metric(params, score):
    pred = jnp.argmax(score, axis=0)
    loss = jnp.where(pred == params["label"], 0.0, 1.0)
    return _weighted_mean(loss, params["weights"],
                          params["sum_weights"])[None]


# --------------------------------------------------------------------- AUC

def auc_metric(params, score):
    """Weighted AUC with tie handling (binary_metric.hpp:184-241): sweep
    score-descending tie GROUPS, each contributing
    grp_neg * (0.5*grp_pos + pos_before)."""
    score = score.astype(jnp.float32)
    label = params["label"]
    w = params["weights"]
    n = score.shape[0]
    wt = jnp.ones_like(score) if w is None else w
    order = jnp.argsort(-score, stable=True)
    s = score[order]
    pos = label[order] * wt[order]
    neg = (1.0 - label[order]) * wt[order]
    # tie-group id per element (first element group 0)
    new_grp = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                               (s[1:] != s[:-1]).astype(jnp.int32)])
    gid = jnp.cumsum(new_grp)
    grp_pos = jax.ops.segment_sum(pos, gid, num_segments=n)
    grp_neg = jax.ops.segment_sum(neg, gid, num_segments=n)
    pos_before = jnp.cumsum(grp_pos) - grp_pos
    accum = jnp.sum(grp_neg * (0.5 * grp_pos + pos_before))
    sum_pos = jnp.sum(grp_pos)
    sum_weights = params["sum_weights"]
    auc = jnp.where((sum_pos > 0.0) & (sum_pos != sum_weights),
                    accum / (sum_pos * (sum_weights - sum_pos)), 1.0)
    return auc[None]


# -------------------------------------------------------------------- NDCG

def _ndcg_metric(params, score, *, ks, block):
    """NDCG@ks over padded queries (rank_metric.hpp:16-167): queries are
    gathered into a [nq, qmax] layout (like the lambdarank objective),
    sorted per query, and DCG@k read off the sorted gains; all-negative
    queries (inv_max <= 0) count as 1.0 regardless of query weight."""
    score = score.astype(jnp.float32)
    doc_index = params["doc_index"]            # [nq, qmax]
    valid = params["valid"]
    labels = params["labels"]                  # [nq, qmax] int32
    inv_max = params["inv_max"]                # [nq, n_ks]
    gains_tbl = params["gains"]                # [max_label+1]
    discount = params["discount"]              # [qmax]
    qw = params["query_weights"]               # [nq] or None
    nq, qmax = doc_index.shape

    s_pad = jnp.where(valid, score[doc_index], -jnp.inf)

    def one_query(s, l):
        order = jnp.argsort(-s, stable=True)   # padded (-inf) sink last
        lg = gains_tbl[l[order]]
        contrib = lg * discount
        # dcg@k = sum of contrib over ranks < k (invalid ranks contribute 0
        # because their labels gather gain of label 0... mask explicitly)
        ok = jnp.isfinite(s[order])
        contrib = jnp.where(ok, contrib, 0.0)
        cum = jnp.cumsum(contrib)
        return jnp.stack([cum[min(k, qmax) - 1] for k in ks])

    pad_q = (-nq) % block
    def pad0(x):
        return jnp.pad(x, [(0, pad_q)] + [(0, 0)] * (x.ndim - 1))
    blocks = (nq + pad_q) // block

    def block_fn(args):
        s_b, l_b = args
        return jax.vmap(one_query)(s_b, l_b)

    dcgs = jax.lax.map(
        block_fn,
        (pad0(s_pad).reshape(blocks, block, qmax),
         pad0(labels).reshape(blocks, block, qmax))).reshape(-1, len(ks))[:nq]

    wq = jnp.ones((nq,), jnp.float32) if qw is None else qw
    all_neg = inv_max[:, 0] <= 0.0
    per_q = jnp.where(all_neg[:, None], 1.0, dcgs * inv_max * wq[:, None])
    return jnp.sum(per_q, axis=0) / params["sum_query_weights"]


# one callable per static key so program caches can use function identity
_NDCG_FNS: dict = {}


def ndcg_fn(ks: tuple, block: int):
    key = (ks, block)
    fn = _NDCG_FNS.get(key)
    if fn is None:
        fn = functools.partial(_ndcg_metric, ks=ks, block=block)
        _NDCG_FNS[key] = fn
    return fn
