"""DCG/NDCG calculator (/root/reference/src/metric/dcg_calculator.cpp:13-134).

Label-gain table from config (default 2^i − 1, config.cpp:226-232) and the
1/log2(2+i) discount table to position 10000.
"""
from __future__ import annotations

from typing import List, Sequence

import numpy as np

K_MAX_POSITION = 10000


class DCGCalculator:
    def __init__(self, label_gain: Sequence[float]):
        self.label_gain = np.asarray(label_gain, dtype=np.float64)
        self.discount = 1.0 / np.log2(2.0 + np.arange(K_MAX_POSITION))

    def cal_max_dcg_at_k(self, k: int, label: np.ndarray) -> float:
        """Max DCG@k: greedily place highest labels first
        (dcg_calculator.cpp:32-54)."""
        label = np.asarray(label).astype(np.int64)
        k = min(k, label.size)
        sorted_gain = np.sort(self.label_gain[label])[::-1]
        return float(np.sum(sorted_gain[:k] * self.discount[:k]))

    def cal_max_dcg(self, ks: Sequence[int], label: np.ndarray) -> List[float]:
        label = np.asarray(label).astype(np.int64)
        sorted_gain = np.sort(self.label_gain[label])[::-1]
        weighted = sorted_gain * self.discount[:sorted_gain.size]
        cum = np.concatenate(([0.0], np.cumsum(weighted)))
        return [float(cum[min(k, label.size)]) for k in ks]

    def cal_dcg(self, ks: Sequence[int], label: np.ndarray,
                score: np.ndarray) -> List[float]:
        """DCG@ks under the score ordering (dcg_calculator.cpp:111-134)."""
        label = np.asarray(label).astype(np.int64)
        order = np.argsort(-np.asarray(score), kind="stable")
        gains = self.label_gain[label[order]]
        weighted = gains * self.discount[:gains.size]
        cum = np.concatenate(([0.0], np.cumsum(weighted)))
        return [float(cum[min(k, label.size)]) for k in ks]
