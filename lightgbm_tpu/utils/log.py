"""Logging for lightgbm_tpu.

TPU-native re-design of the reference's static logger
(/root/reference/include/LightGBM/utils/log.h:12-90): same levels and
``[LightGBM] [Level]`` stdout prefix so CLI output is familiar, but built on a
plain Python module instead of a C++ static class.  ``Fatal`` raises instead of
calling ``exit(1)`` so library users get a catchable exception; the CLI
converts it to a non-zero exit.
"""
from __future__ import annotations

import sys

# Levels mirror log.h: Fatal=-1, Error=0, Warning=1, Info=2, Debug=3.
FATAL = -1
ERROR = 0
WARNING = 1
INFO = 2
DEBUG = 3

_level = INFO
_stream = None  # None → sys.stdout (reference parity, log.h:35-89)


class LightGBMError(RuntimeError):
    """Raised where the reference would Log::Fatal + exit(1)."""


def set_level(level: int) -> None:
    global _level
    _level = level


def set_level_from_verbosity(verbosity: int) -> None:
    """The reference's verbosity → level rule (config.cpp:59-70), single-
    homed: 1 → Info, 0 → Warning, >= 2 → Debug, < 0 → Fatal.  Called at
    CLI/config startup so ``verbosity=3`` actually enables ``debug``
    output."""
    if verbosity == 1:
        set_level(INFO)
    elif verbosity == 0:
        set_level(WARNING)
    elif verbosity >= 2:
        set_level(DEBUG)
    else:
        set_level(FATAL)


def get_level() -> int:
    return _level


def set_stream(stream) -> None:
    """Redirect log output (None restores stdout).  Harnesses that reserve
    stdout for machine-readable output route logs to stderr."""
    global _stream
    _stream = stream


def _write(tag: str, msg: str) -> None:
    out = _stream if _stream is not None else sys.stdout
    out.write(f"[LightGBM] [{tag}] {msg}\n")
    out.flush()


def debug(msg: str, *args) -> None:
    if _level >= DEBUG:
        _write("Debug", msg % args if args else msg)


def info(msg: str, *args) -> None:
    if _level >= INFO:
        _write("Info", msg % args if args else msg)


def warning(msg: str, *args) -> None:
    if _level >= WARNING:
        _write("Warning", msg % args if args else msg)


def error(msg: str, *args) -> None:
    if _level >= ERROR:
        _write("Error", msg % args if args else msg)


def fatal(msg: str, *args) -> None:
    """Equivalent of Log::Fatal (log.h:63-72) minus the process kill."""
    text = msg % args if args else msg
    _write("Fatal", text)
    raise LightGBMError(text)


def check(condition: bool, msg: str = "check failed") -> None:
    """CHECK macro equivalent (log.h:12-21)."""
    if not condition:
        fatal(msg)
