from . import log
