"""Configuration system.

Re-designs the reference's layered key=value config
(/root/reference/include/LightGBM/config.h:86-374, src/io/config.cpp:33-331)
as Python dataclasses.  Behavioral parity goals:

- same parameter names, aliases (config.h:301-374) and defaults,
- argv ``key=value`` pairs win over config-file lines (application.cpp:98),
- ``#`` comments in config files,
- the same conflict-resolution rules (config.cpp:133-182),
- typed getters that fail loudly on malformed values (config.h:246-299).

TPU additions: ``tree_learner`` keeps the reference's serial/feature/data
values; ``num_machines``/mesh setup maps to ``jax.sharding.Mesh`` axes rather
than socket/MPI ranks (see lightgbm_tpu/parallel/).
"""
from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional

from .utils import log

# Alias table: reference config.h:301-374 (KeyAliasTransform).
ALIAS_TABLE: Dict[str, str] = {
    "config": "config_file",
    "nthread": "num_threads",
    "num_thread": "num_threads",
    "boosting": "boosting_type",
    "boost": "boosting_type",
    "application": "objective",
    "app": "objective",
    "train_data": "data",
    "train": "data",
    "model_output": "output_model",
    "model_out": "output_model",
    "model_input": "input_model",
    "model_in": "input_model",
    "init_score": "input_init_score",
    "predict_result": "output_result",
    "prediction_result": "output_result",
    "valid": "valid_data",
    "test_data": "valid_data",
    "test": "valid_data",
    "is_sparse": "is_enable_sparse",
    "tranining_metric": "is_training_metric",
    "train_metric": "is_training_metric",
    "ndcg_at": "ndcg_eval_at",
    "min_data_per_leaf": "min_data_in_leaf",
    "min_data": "min_data_in_leaf",
    "min_sum_hessian_per_leaf": "min_sum_hessian_in_leaf",
    "min_sum_hessian": "min_sum_hessian_in_leaf",
    "min_hessian": "min_sum_hessian_in_leaf",
    "num_leaf": "num_leaves",
    "sub_feature": "feature_fraction",
    "num_iteration": "num_iterations",
    "num_tree": "num_iterations",
    "num_round": "num_iterations",
    "num_trees": "num_iterations",
    "num_rounds": "num_iterations",
    "sub_row": "bagging_fraction",
    "shrinkage_rate": "learning_rate",
    "tree": "tree_learner",
    "num_machine": "num_machines",
    "local_port": "local_listen_port",
    "two_round_loading": "use_two_round_loading",
    "two_round": "use_two_round_loading",
    "mlist": "machine_list_file",
    "is_save_binary": "is_save_binary_file",
    "save_binary": "is_save_binary_file",
    "early_stopping_rounds": "early_stopping_round",
    "early_stopping": "early_stopping_round",
    "verbosity": "verbose",
    "header": "has_header",
    "label": "label_column",
    "weight": "weight_column",
    "group": "group_column",
    "query": "group_column",
    "query_column": "group_column",
    "ignore_feature": "ignore_column",
    "blacklist": "ignore_column",
    "topk": "top_k",
}


def apply_aliases(params: Dict[str, str]) -> Dict[str, str]:
    """KeyAliasTransform (config.h:302-373): canonical key wins on conflict."""
    out = dict(params)
    for key, value in params.items():
        canon = ALIAS_TABLE.get(key)
        if canon is not None and canon not in out:
            out[canon] = value
    return out


def _get_int(params, name, default):
    if name in params:
        try:
            return int(params[name])
        except ValueError:
            log.fatal("Parameter %s should be int type, passed is [%s]" % (name, params[name]))
    return default


def _get_float(params, name, default):
    if name in params:
        try:
            return float(params[name])
        except ValueError:
            log.fatal("Parameter %s should be double type, passed is [%s]" % (name, params[name]))
    return default


def _get_bool(params, name, default):
    if name in params:
        value = params[name].lower()
        if value in ("false", "-"):
            return False
        if value in ("true", "+"):
            return True
        log.fatal('Parameter %s should be "true"/"+" or "false"/"-", passed is [%s]'
                  % (name, params[name]))
    return default


def _get_str(params, name, default):
    return params.get(name, default)


@dataclasses.dataclass
class IOConfig:
    """Reference config.h:86-118."""
    max_bin: int = 256
    data_random_seed: int = 1
    data_filename: str = ""
    valid_data_filenames: List[str] = dataclasses.field(default_factory=list)
    output_model: str = "LightGBM_model.txt"
    # TPU extension (SURVEY §5.1): write a jax.profiler trace of the
    # training loop to this directory (view with tensorboard / xprof)
    profile_dir: str = ""
    # Telemetry (ISSUE 1): per-iteration JSONL metrics sink — one record
    # per boosting iteration with phase timings, kernel-route counters and
    # eval metrics (lightgbm_tpu/telemetry.py; pretty-print with
    # scripts/telemetry_report.py).  metrics_fence=true additionally
    # block_until_ready-fences phase spans so async dispatch doesn't
    # attribute device time to the wrong phase (timing-accuracy mode;
    # slows training, never issues extra dispatches)
    metrics_out: str = ""
    metrics_fence: bool = False
    # Memory gauges (ISSUE 2): sample device.memory_stats() at telemetry
    # span boundaries (per-phase byte deltas + peak bytes_in_use
    # watermark) and emit a ``memory`` block in the JSONL records plus a
    # one-shot dataset-residency report at train start.  "auto" (default)
    # = on whenever metrics_out is set; "true"/"false" force it.
    memory_stats: str = "auto"
    # Distributed observability (ISSUE 5): timeline mode writes one JSONL
    # shard PER PROCESS (``<metrics_out>.shard-<i>of<n>.jsonl``, headed
    # by a host/clock record) instead of a leader-only file — merge with
    # scripts/timeline_report.py.  "auto" = on for multi-process runs
    # whenever metrics_out is set; "true"/"false" force it.
    timeline: str = "auto"
    # Hung-collective flight recorder: with stall_timeout > 0 (seconds)
    # a watchdog thread dumps the recent span/collective event ring, the
    # in-flight phase/iteration and all thread stacks to the sink when
    # training makes no progress for that long — before the runtime's
    # own opaque dispatch watchdog kills the job.  0 disables.
    stall_timeout: float = 0.0
    # Flight recorder (ISSUE 16, lightgbm_tpu/tracing.py): the always-on
    # per-event tier under telemetry — per-request serving latency
    # attribution, training timeline events, streaming percentile
    # sketches.  trace_ring_events bounds the preallocated event ring
    # (drops oldest past it, counted ``trace/dropped``); matches
    # tracing.DEFAULT_RING_EVENTS — perf_gate treats drops at THIS
    # default as an absolute finding.
    trace_ring_events: int = 65536
    # trace_dump_dir: where ring dumps land as JSONL (atomic tmp+rename)
    # on clean close AND from the fault/crash paths; "" = no dumps.
    # Render/validate with scripts/trace_report.py.
    trace_dump_dir: str = ""
    # trace_sketch_growth: log-bucket growth factor of the percentile
    # sketches — quantiles are exact to within a factor sqrt(growth)
    trace_sketch_growth: float = 1.05
    # trace_run_id: operator-assigned run tag stamped into every trace
    # dump header.  podtrace/pod_report refuse to merge dumps with
    # mismatched run ids (mixing runs is a loud BadDump, never a
    # silently wrong merge); "" leaves dumps untagged.
    trace_run_id: str = ""
    # Live monitoring (ISSUE 20, lightgbm_tpu/monitor.py): windowed
    # metrics / SLO burn rate / score drift, layered on telemetry +
    # tracing.  monitor_out: JSONL file the emitter thread appends one
    # windowed snapshot per interval to (render/validate with
    # scripts/monitor_report.py); "" = monitor off unless an SLO is
    # declared.
    monitor_out: str = ""
    # monitor_interval_s: window length of the snapshot ring (seconds,
    # > 0) — each window carries exact counter and sketch DELTAS since
    # the previous one.
    monitor_interval_s: float = 1.0
    # slo_p99_us: declarative latency objective for the serving front's
    # serve_wall_us family — a p99 target grants a 1% error budget;
    # breach = fast short-window burn >= 5x AND slow long-window burn
    # >= 1x.  0 disables SLO tracking (predict-task only: there is no
    # serving latency to burn under task=train).
    slo_p99_us: float = 0.0
    # slo_window_s: the SLO error-budget window (seconds, > 0); the
    # fast window is 1/12 of it.
    slo_window_s: float = 60.0
    output_result: str = "LightGBM_predict_result.txt"
    input_model: str = ""
    input_init_score: str = ""
    verbosity: int = 1
    num_model_predict: int = -1
    # Compiled serving engine (ISSUE 7, lightgbm_tpu/serving.py).
    # predict_buckets: the CLOSED ladder of compiled batch shapes —
    # batches pad up to the smallest bucket that holds them (larger
    # inputs chunk at the biggest bucket), so steady-state serving never
    # sees a new program shape and never recompiles.
    predict_buckets: str = "1,32,1024,65536"
    # predict_quantize: "int8" serves an int8-quantized leaf-value table
    # (per-tree symmetric scale; quarter the table traffic — the
    # memory-bound-ensemble mode).  Routing stays exact either way; only
    # leaf VALUES are quantized.  "float32" is bit-equal to the
    # training-side scorer.
    predict_quantize: str = "float32"
    # predict_donate: donate the padded codes buffer to the compiled
    # program so steady-state serving recycles it in place.  "auto" = on
    # for accelerator backends, off on CPU (which ignores donation with a
    # per-call warning).
    predict_donate: str = "auto"
    # predict_algo: "bfs" walks all trees breadth-first in lockstep (one
    # gather-based level step per depth — O(max_depth) fused steps);
    # "scan" keeps the training-side per-tree replay (O(T·L) steps) as
    # the A/B reference bench.py's bench_predict lane prices.
    predict_algo: str = "bfs"
    # Distributed elastic serving (ISSUE 13, lightgbm_tpu/serving.py).
    # serve_shards: shard the flattened ensemble's [T, ...] node tables
    # contiguously along a 1-D ("tree",) device mesh — each device holds
    # ONLY its tree block (the 10k+-tree / multi-GB-ensemble regime one
    # HBM cannot hold); scores stay BIT-equal to the single-device
    # engine (f32 and int8).  0 = single-device; >1 must not exceed the
    # available devices (the engine rejects loudly, never shrinks).
    serve_shards: int = 0
    # predict_linger_us: the ServingFront's max coalescing wait — a
    # queued request is dispatched no later than this many microseconds
    # after the FIRST request of its batch arrived (sooner when a full
    # top-bucket batch is available).  0 = dispatch immediately (still
    # coalesces whatever is queued at pop time).
    predict_linger_us: int = 200
    # predict_queue: bound on in-flight serving work, in TOP-BUCKET
    # batches — the ServingFront's queue holds at most
    # predict_queue * max(predict_buckets) rows (submit blocks when
    # full: backpressure, never load shedding), and predict_file keeps
    # this many parsed chunks in flight ahead of the device.
    predict_queue: int = 4
    is_pre_partition: bool = False
    is_enable_sparse: bool = True
    # Streaming ingestion (ISSUE 8, lightgbm_tpu/io/streaming.py):
    # chunked parse→sample→bin with double-buffered host→device feeds —
    # bit-identical datasets/models to the resident loader, host memory
    # bounded by one chunk instead of the full unbinned matrix.  "auto"
    # (default) engages when the data (or cache) file is at least
    # streaming.AUTO_MIN_BYTES (256 MB); "true"/"false" force.
    # Supersedes use_two_round_loading when both apply.
    streaming: str = "auto"
    # parse/bin/transfer chunk length (rows) of the streaming loader —
    # also the bound on how many raw rows are ever host-resident
    ingest_chunk_rows: int = 200_000
    # parse worker processes of the streaming loader (ISSUE 18,
    # io/parallel_ingest.py): > 1 fans tokenize+bin out over byte-range
    # workers (bit-identical datasets); "auto" = cpu_count; 1 (default)
    # keeps the serial passes
    ingest_workers: int = 1
    use_two_round_loading: bool = False
    is_save_binary_file: bool = False
    # format of the is_save_binary_file cache: "native" (pickle header +
    # raw bin matrix) or "reference" — the reference's own .bin layout
    # (dataset.cpp:653-713), which its binary can train from directly
    save_binary_format: str = "native"
    is_sigmoid: bool = True
    has_header: bool = False
    label_column: str = ""
    weight_column: str = ""
    group_column: str = ""
    ignore_column: str = ""

    def predict_bucket_list(self) -> tuple:
        """The ``predict_buckets=`` ladder parsed and validated: sorted
        unique positive ints (the serving engine's compiled batch
        shapes)."""
        try:
            buckets = tuple(sorted({int(b) for b in
                                    self.predict_buckets.split(",") if b}))
        except ValueError:
            log.fatal("predict_buckets should be comma-separated ints, "
                      "passed is [%s]" % self.predict_buckets)
        log.check(bool(buckets) and buckets[0] >= 1,
                  "predict_buckets must contain positive ints")
        return buckets

    def memory_stats_enabled(self) -> bool:
        """The ``memory_stats=`` resolution rule, single-homed (cli.py and
        lgb.train both consult it): "auto" follows the sink — gauges on
        whenever ``metrics_out`` is set; "true"/"false" force it."""
        return (self.memory_stats == "true"
                or (self.memory_stats == "auto" and bool(self.metrics_out)))

    def timeline_enabled(self) -> bool:
        """The ``timeline=`` resolution rule, single-homed: "auto" = per-
        process shards on for TRUE multi-process runs with a sink (the
        exact case where a leader-only file hides every other host);
        "true" forces shard mode even single-process, "false" keeps the
        leader-only sink.  Consulted AFTER distributed init (cli.py), so
        process_count is final."""
        if self.timeline == "true":
            return True
        if self.timeline != "auto" or not self.metrics_out:
            return False
        try:
            import jax
            return jax.process_count() > 1
        except Exception:
            return False

    def set(self, params: Dict[str, str], require_data: bool = True) -> None:
        self.max_bin = _get_int(params, "max_bin", self.max_bin)
        log.check(self.max_bin > 0, "max_bin should be > 0")
        self.data_random_seed = _get_int(params, "data_random_seed", self.data_random_seed)
        if "data" in params:
            self.data_filename = params["data"]
        elif require_data:
            log.fatal("No training/prediction data, application quit")
        self.verbosity = _get_int(params, "verbose", self.verbosity)
        self.profile_dir = _get_str(params, "profile_dir", self.profile_dir)
        self.metrics_out = _get_str(params, "metrics_out", self.metrics_out)
        self.metrics_fence = _get_bool(params, "metrics_fence",
                                       self.metrics_fence)
        if "memory_stats" in params:
            value = params["memory_stats"].lower()
            log.check(value in ("auto", "true", "false"),
                      "memory_stats must be auto, true or false")
            self.memory_stats = value
        if "timeline" in params:
            value = params["timeline"].lower()
            log.check(value in ("auto", "true", "false"),
                      "timeline must be auto, true or false")
            self.timeline = value
        self.stall_timeout = _get_float(params, "stall_timeout",
                                        self.stall_timeout)
        log.check(self.stall_timeout >= 0.0,
                  "stall_timeout should be >= 0")
        self.trace_ring_events = _get_int(params, "trace_ring_events",
                                          self.trace_ring_events)
        log.check(self.trace_ring_events > 0,
                  "trace_ring_events should be > 0 (preallocated "
                  "flight-recorder ring slots)")
        if "trace_dump_dir" in params:
            self.trace_dump_dir = params["trace_dump_dir"]
            if self.trace_dump_dir:
                # loud reject at parse time (ISSUE 16): a dump dir that
                # cannot take writes would otherwise fail silently at
                # the one moment it matters — inside a crash dump
                try:
                    os.makedirs(self.trace_dump_dir, exist_ok=True)
                except OSError:
                    pass
                log.check(os.path.isdir(self.trace_dump_dir)
                          and os.access(self.trace_dump_dir, os.W_OK),
                          "trace_dump_dir must be a writable directory")
        self.trace_sketch_growth = _get_float(params, "trace_sketch_growth",
                                              self.trace_sketch_growth)
        log.check(1.0005 <= self.trace_sketch_growth <= 2.0,
                  "trace_sketch_growth should be in [1.0005, 2.0]")
        if "trace_run_id" in params:
            value = str(params["trace_run_id"])
            log.check(len(value) <= 128
                      and not any(c.isspace() for c in value),
                      "trace_run_id must be <= 128 chars with no "
                      "whitespace (it lands verbatim in dump headers "
                      "and report keys)")
            self.trace_run_id = value
        if "monitor_out" in params:
            self.monitor_out = params["monitor_out"]
            if self.monitor_out:
                # loud reject at parse time (ISSUE 20): an unwritable
                # monitor sink would otherwise fail silently at the one
                # moment it matters — inside a crash flush
                parent = os.path.dirname(self.monitor_out) or "."
                log.check(os.path.isdir(parent)
                          and os.access(parent, os.W_OK),
                          "monitor_out parent must be a writable "
                          "directory")
        self.monitor_interval_s = _get_float(params, "monitor_interval_s",
                                             self.monitor_interval_s)
        log.check(self.monitor_interval_s > 0.0,
                  "monitor_interval_s should be > 0 (the windowed-"
                  "snapshot interval)")
        self.slo_p99_us = _get_float(params, "slo_p99_us", self.slo_p99_us)
        log.check(self.slo_p99_us >= 0.0,
                  "slo_p99_us should be >= 0 (0 disables SLO tracking)")
        self.slo_window_s = _get_float(params, "slo_window_s",
                                       self.slo_window_s)
        log.check(self.slo_window_s > 0.0,
                  "slo_window_s should be > 0 (the error-budget window)")
        self.num_model_predict = _get_int(params, "num_model_predict", self.num_model_predict)
        self.predict_buckets = _get_str(params, "predict_buckets",
                                        self.predict_buckets)
        self.predict_bucket_list()  # validate eagerly: fail at parse time
        if "predict_quantize" in params:
            value = params["predict_quantize"].lower()
            log.check(value in ("float32", "int8"),
                      "predict_quantize must be float32 or int8")
            self.predict_quantize = value
        if "predict_donate" in params:
            value = params["predict_donate"].lower()
            log.check(value in ("auto", "true", "false"),
                      "predict_donate must be auto, true or false")
            self.predict_donate = value
        if "predict_algo" in params:
            value = params["predict_algo"].lower()
            log.check(value in ("bfs", "scan"),
                      "predict_algo must be bfs or scan")
            self.predict_algo = value
        self.serve_shards = _get_int(params, "serve_shards",
                                     self.serve_shards)
        log.check(self.serve_shards >= 0,
                  "serve_shards should be >= 0 (0 = single-device)")
        if self.serve_shards > 1 and self.predict_algo == "scan":
            log.fatal("serve_shards > 1 requires predict_algo=bfs (the "
                      "per-tree scan replay is a single-device A/B path)")
        self.predict_linger_us = _get_int(params, "predict_linger_us",
                                          self.predict_linger_us)
        log.check(self.predict_linger_us >= 0,
                  "predict_linger_us should be >= 0")
        self.predict_queue = _get_int(params, "predict_queue",
                                      self.predict_queue)
        log.check(self.predict_queue >= 1,
                  "predict_queue should be >= 1 (in-flight batches)")
        self.is_pre_partition = _get_bool(params, "is_pre_partition", self.is_pre_partition)
        self.is_enable_sparse = _get_bool(params, "is_enable_sparse", self.is_enable_sparse)
        if "streaming" in params:
            value = params["streaming"].lower()
            log.check(value in ("auto", "true", "false"),
                      "streaming must be auto, true or false")
            self.streaming = value
        self.ingest_chunk_rows = _get_int(params, "ingest_chunk_rows",
                                          self.ingest_chunk_rows)
        log.check(self.ingest_chunk_rows > 0,
                  "ingest_chunk_rows should be > 0")
        if str(params.get("ingest_workers", "")).lower() == "auto":
            self.ingest_workers = os.cpu_count() or 1
        else:
            self.ingest_workers = _get_int(params, "ingest_workers",
                                           self.ingest_workers)
        log.check(self.ingest_workers > 0,
                  "ingest_workers should be > 0 (or auto = cpu_count)")
        self.use_two_round_loading = _get_bool(params, "use_two_round_loading",
                                               self.use_two_round_loading)
        self.is_save_binary_file = _get_bool(params, "is_save_binary_file",
                                             self.is_save_binary_file)
        if "save_binary_format" in params:
            value = params["save_binary_format"].lower()
            log.check(value in ("native", "reference"),
                      "save_binary_format must be native or reference")
            self.save_binary_format = value
        self.is_sigmoid = _get_bool(params, "is_sigmoid", self.is_sigmoid)
        self.output_model = _get_str(params, "output_model", self.output_model)
        self.input_model = _get_str(params, "input_model", self.input_model)
        self.output_result = _get_str(params, "output_result", self.output_result)
        self.input_init_score = _get_str(params, "input_init_score", self.input_init_score)
        if "valid_data" in params:
            self.valid_data_filenames = [s for s in params["valid_data"].split(",") if s]
        self.has_header = _get_bool(params, "has_header", self.has_header)
        self.label_column = _get_str(params, "label_column", self.label_column)
        self.weight_column = _get_str(params, "weight_column", self.weight_column)
        self.group_column = _get_str(params, "group_column", self.group_column)
        self.ignore_column = _get_str(params, "ignore_column", self.ignore_column)


def _default_label_gain() -> List[float]:
    # label_gain = 2^i - 1 up to 31 labels (config.cpp:226-232).
    return [0.0] + [float((1 << i) - 1) for i in range(1, 31)]


@dataclasses.dataclass
class ObjectiveConfig:
    """Reference config.h:120-134."""
    sigmoid: float = 1.0
    label_gain: List[float] = dataclasses.field(default_factory=_default_label_gain)
    max_position: int = 20
    is_unbalance: bool = False
    num_class: int = 1

    def set(self, params: Dict[str, str]) -> None:
        self.is_unbalance = _get_bool(params, "is_unbalance", self.is_unbalance)
        self.sigmoid = _get_float(params, "sigmoid", self.sigmoid)
        self.max_position = _get_int(params, "max_position", self.max_position)
        log.check(self.max_position > 0, "max_position should be > 0")
        self.num_class = _get_int(params, "num_class", self.num_class)
        log.check(self.num_class >= 1, "num_class should be >= 1")
        if "label_gain" in params:
            self.label_gain = _parse_label_gain(params["label_gain"])


def _parse_label_gain(value: str) -> List[float]:
    """Loud-reject parse of the comma-separated label_gain list — a junk
    token used to surface as a bare ValueError traceback instead of the
    typed-getter fatal every other knob gets."""
    try:
        return [float(x) for x in value.split(",") if x]
    except ValueError:
        log.fatal("Parameter label_gain should be comma-separated "
                  "doubles, passed is [%s]" % value)


@dataclasses.dataclass
class MetricConfig:
    """Reference config.h:136-145."""
    num_class: int = 1
    sigmoid: float = 1.0
    label_gain: List[float] = dataclasses.field(default_factory=_default_label_gain)
    eval_at: List[int] = dataclasses.field(default_factory=lambda: [1, 2, 3, 4, 5])

    def set(self, params: Dict[str, str]) -> None:
        self.sigmoid = _get_float(params, "sigmoid", self.sigmoid)
        self.num_class = _get_int(params, "num_class", self.num_class)
        log.check(self.num_class >= 1, "num_class should be >= 1")
        if "label_gain" in params:
            self.label_gain = _parse_label_gain(params["label_gain"])
        if "ndcg_eval_at" in params:
            self.eval_at = sorted(int(x) for x in params["ndcg_eval_at"].split(",") if x)
            for k in self.eval_at:
                log.check(k > 0, "ndcg_eval_at should be > 0")


@dataclasses.dataclass
class TreeConfig:
    """Reference config.h:148-165."""
    min_data_in_leaf: int = 100
    min_sum_hessian_in_leaf: float = 10.0
    num_leaves: int = 127
    feature_fraction_seed: int = 2
    feature_fraction: float = 1.0
    histogram_pool_size: float = -1.0
    max_depth: int = -1
    # TPU-native extension (no reference equivalent): "leafwise" reproduces
    # the reference's strict best-first growth (serial_tree_learner.cpp:119-153);
    # "depthwise" grows level-batched for MXU throughput (grower_depthwise.py)
    grow_policy: str = "leafwise"
    # TPU tuning knobs (no reference equivalent): row-chunk length of the
    # histogram scan (0 = per-policy default) and the one-hot/value operand
    # dtype of the histogram matmul.  On TPU all three dtypes run
    # hand-scheduled Pallas MXU kernels (ops/hist_pallas.py): "float32"
    # rides a two-pass hi/lo bf16 operand split (~16 operand mantissa
    # bits, f32 accumulation — the closest-to-reference mode), "bfloat16"
    # a single pass (grad/hess rounded to 8 mantissa bits; ~2x f32 speed
    # at a fraction of int8's quantization error), "int8" the
    # quantized-gradient kernel on the int8 MXU — fastest, grad/hess
    # rounded to 1/127 of their per-pass max; counts stay exact in every
    # mode.  hist_chunk tunes the XLA scan paths only; the Pallas kernels
    # use their own fixed VMEM block.
    # int8 is capped at ~16.9M GLOBAL rows (int32 accumulator: 127 x rows
    # can wrap past 2^31 when rows concentrate in one bin — see
    # models/gbdt.check_int8_row_capacity, which refuses loudly).
    hist_chunk: int = 0
    hist_dtype: str = "float32"
    # data-parallel histogram reduction schedule (TreeConfig extension):
    # "psum" allreduces the full [C,F,B,3] level histogram and searches
    # splits replicated; "reduce_scatter" is the reference's
    # bandwidth-optimal ownership schedule
    # (data_parallel_tree_learner.cpp:135-235) — psum_scatter the level
    # histograms by contiguous feature block, search only owned features,
    # and allreduce the packed SplitInfo: ~half the collective bytes and
    # 1/S of the split-search compute per level.  Applies to the fused
    # depthwise data-parallel chunk; identical trees either way.
    # "auto" resolves at learner creation: true multi-process runs take
    # reduce_scatter (the reference's N-machine mode IS that schedule);
    # single-process meshes keep psum (parallel/learners.py _schedule)
    dp_schedule: str = "auto"
    # leaf-wise dispatch segmentation (TreeConfig extension, grow_policy=
    # leafwise only): a 255-leaf leaf-wise tree is 254 sequential
    # histogram passes in ONE XLA dispatch; >1 splits that loop across N
    # dispatches with the grow state carried device-resident — bit-
    # identical trees (models/grower.grow_tree_segmented), just shorter
    # dispatches (runtime watchdogs, interactivity).  Default 1 = the
    # whole tree in one dispatch.
    leafwise_segments: int = 1
    # compacted leaf-wise growth (TreeConfig extension, grow_policy=
    # leafwise, serial learner only): keep every leaf's rows physically
    # contiguous (the reference's DataPartition asymptotic,
    # data_partition.hpp:93-139, recast as data movement — see
    # models/grower_leafcompact.py) so each split histograms only the
    # smaller child's rows instead of sweeping all N.  "auto" (default)
    # = on when the backend is TPU, off elsewhere (keeps CPU-golden
    # tests on the masked grower); "true"/"false" force it.  When on it
    # subsumes leafwise_segments: per-tree dispatches are already short.
    leafwise_compact: str = "auto"
    # mixed-bin feature packing (TreeConfig extension, ISSUE 6): partition
    # features into bin-width classes at Dataset-attach time (narrow:
    # num_bin <= 64 rides the measured-fast 64-wide kernel class; wide:
    # num_bins_max), reorder the bin matrix by class, and run one
    # histogram pass per class — split outputs are bit-identical to the
    # uniform single-pass path (per-class histograms are reassembled into
    # canonical feature order before split finding).  "auto"/"true" = on
    # whenever the dataset actually mixes narrow and wide features (a
    # single class collapses to the existing path); "false" = off.
    # LGBM_TPU_NO_MIXEDBIN=1 is the env A/B hatch.  The feature-parallel
    # learner keeps the uniform layout (its per-shard ownership slices
    # are arbitrary feature subsets).
    mixed_bin: str = "auto"
    # 2-D hybrid mesh factoring (ISSUE 9, tree_learner=hybrid|voting):
    # num_machines = data_shards x feature_shards.  0 = auto
    # (parallel/mesh.factor_machines: hybrid takes the largest divisor
    # <= sqrt(num_machines) as feature_shards, voting defaults to pure
    # data-parallel); a nonzero value must divide num_machines.
    feature_shards: int = 0
    # voting-parallel top-k (tree_learner=voting; the reference family's
    # ``top_k``/PV-tree parameter, default 20): each data shard proposes
    # its top_k features by local split gain, and full histograms are
    # exchanged only for the <= 2*top_k globally-voted features per
    # owned block.  Voting is exact whenever the voted set covers the
    # true best feature — guaranteed when 2*top_k >= features-per-block,
    # the reference's own accuracy argument otherwise.
    top_k: int = 20
    # int8 rounding mode: "nearest" (default) or "stochastic" — unbiased
    # floor(y+u) with deterministic value-keyed uniform bits
    # (ops/hist_pallas.stochastic_bits); preserves the serial==distributed
    # bit-identity because the key is the row's (grad, hess) values, not
    # its position
    quant_rounding: str = "nearest"

    def set(self, params: Dict[str, str]) -> None:
        self.min_data_in_leaf = _get_int(params, "min_data_in_leaf", self.min_data_in_leaf)
        self.min_sum_hessian_in_leaf = _get_float(params, "min_sum_hessian_in_leaf",
                                                  self.min_sum_hessian_in_leaf)
        log.check(self.min_sum_hessian_in_leaf > 1.0 or self.min_data_in_leaf > 0,
                  "min_sum_hessian_in_leaf/min_data_in_leaf check failed")
        self.num_leaves = _get_int(params, "num_leaves", self.num_leaves)
        log.check(self.num_leaves > 1, "num_leaves should be > 1")
        self.feature_fraction_seed = _get_int(params, "feature_fraction_seed",
                                              self.feature_fraction_seed)
        self.feature_fraction = _get_float(params, "feature_fraction", self.feature_fraction)
        log.check(0.0 < self.feature_fraction <= 1.0,
                  "feature_fraction should be in (0, 1]")
        self.histogram_pool_size = _get_float(params, "histogram_pool_size",
                                              self.histogram_pool_size)
        self.max_depth = _get_int(params, "max_depth", self.max_depth)
        log.check(self.max_depth > 1 or self.max_depth < 0,
                  "max_depth should be > 1 or < 0")
        if "grow_policy" in params:
            value = params["grow_policy"].lower()
            log.check(value in ("leafwise", "depthwise"),
                      "grow_policy must be leafwise or depthwise")
            self.grow_policy = value
        self.hist_chunk = _get_int(params, "hist_chunk", self.hist_chunk)
        log.check(self.hist_chunk >= 0, "hist_chunk should be >= 0")
        if "hist_dtype" in params:
            value = params["hist_dtype"].lower()
            log.check(value in ("float32", "bfloat16", "int8"),
                      "hist_dtype must be float32, bfloat16 or int8")
            self.hist_dtype = value
        self.leafwise_segments = _get_int(params, "leafwise_segments",
                                          self.leafwise_segments)
        log.check(self.leafwise_segments >= 1,
                  "leafwise_segments should be >= 1")
        if "leafwise_compact" in params:
            value = params["leafwise_compact"].lower()
            log.check(value in ("auto", "true", "false"),
                      "leafwise_compact must be auto, true or false")
            self.leafwise_compact = value
        if "dp_schedule" in params:
            value = params["dp_schedule"].lower()
            log.check(value in ("auto", "psum", "reduce_scatter"),
                      "dp_schedule must be auto, psum or reduce_scatter")
            self.dp_schedule = value
        if "mixed_bin" in params:
            value = params["mixed_bin"].lower()
            log.check(value in ("auto", "true", "false"),
                      "mixed_bin must be auto, true or false")
            self.mixed_bin = value
        self.feature_shards = _get_int(params, "feature_shards",
                                       self.feature_shards)
        log.check(self.feature_shards >= 0,
                  "feature_shards should be >= 0")
        self.top_k = _get_int(params, "top_k", self.top_k)
        log.check(self.top_k >= 1, "top_k should be >= 1")
        if "quant_rounding" in params:
            value = params["quant_rounding"].lower()
            log.check(value in ("nearest", "stochastic"),
                      "quant_rounding must be nearest or stochastic")
            self.quant_rounding = value
            if value == "stochastic" and self.hist_dtype != "int8":
                log.warning("quant_rounding=stochastic only applies to "
                            "hist_dtype=int8; ignored for %s"
                            % self.hist_dtype)


@dataclasses.dataclass
class BoostingConfig:
    """Reference config.h:173-199 (BoostingConfig + GBDTConfig)."""
    output_freq: int = 1
    is_provide_training_metric: bool = False
    num_iterations: int = 10
    learning_rate: float = 0.1
    bagging_fraction: float = 1.0
    bagging_seed: int = 3
    bagging_freq: int = 0
    early_stopping_round: int = 0
    num_class: int = 1
    tree_learner: str = "serial"
    # Training-health monitor (ISSUE 2, lightgbm_tpu/health.py): an
    # in-program health vector (NaN/Inf counts in gradients/hessians/raw
    # scores, int8 quantization saturation, score-magnitude watermark)
    # plus tree-derived counts (zero-gain splits, empty leaves), fetched
    # once per iteration and emitted as a ``health`` block in the JSONL
    # sink.  "auto" (default) = on whenever telemetry is armed
    # (metrics_out=); "true"/"false" force it.
    health: str = "auto"
    # policy on health anomalies (nonzero NaN/Inf counts, eval
    # divergence): "warn" logs once per anomaly kind, "halt" raises a
    # clean TrainingHealthError, "record" only writes the sink block
    on_anomaly: str = "warn"
    # eval-metric divergence detection: k consecutive worsening
    # iterations of any tracked metric flag an anomaly (0 = disabled)
    health_divergence_rounds: int = 0
    # pipelined boosting (ISSUE 6): "readback" double-buffers the next
    # iteration's (or chunk's) gradient/histogram dispatch against the
    # current model readback — the device math is dispatched in exactly
    # the per-iteration order, only HOST WAITS move, so trees/scores/
    # metric values are exact-identical (tests/test_pipeline.py).  "off"
    # keeps the strictly synchronous loop.  "auto" = readback inside
    # run_training for single-process runs without an in-loop checkpoint
    # callback (a save_fn must see every finished tree, so the CLI's
    # incremental output_model saves keep auto synchronous; direct
    # train_one_iter / train_chunk callers keep synchronous semantics
    # unless they opt in explicitly); multi-process runs stay off.
    # LGBM_TPU_PIPELINE overrides for A/B timing.
    pipeline: str = "auto"
    # Device-side bagging (ISSUE 8, lightgbm_tpu/ops/sampling.py): draw
    # the in-bag mask on-device (one threefry key per redraw) instead of
    # a host numpy draw plus a full-N mask upload every bagging_freq
    # iterations.  Exact in-bag count like the host path; the RNG STREAM
    # differs (threefry vs MT19937), so trained trees differ from the
    # host path by the sampling draw only.  "auto" = on for accelerator
    # backends in single-process, no-query runs; "true"/"false" force
    # (true still falls back — with a warning — where the device draw
    # cannot apply: multi-process shards, per-query bagging).
    # LGBM_TPU_HOST_BAGGING=1 is the env A/B hatch back to the host path.
    bagging_device: str = "auto"
    # GOSS — gradient-based one-side sampling (ISSUE 8; the headline
    # trick of the later LightGBM paper): each iteration keeps the
    # top_rate fraction of rows by gradient magnitude plus an other_rate
    # fraction of the remainder sampled uniformly, amplifying the
    # sampled remainder's gradients AND hessians by
    # (1-top_rate)/other_rate.  The selection runs entirely on device
    # and feeds the histogram kernels through the row-mask seam.
    # Incompatible with bagging (the reference family's rule) and with
    # multi-process training in this revision.
    goss: bool = False
    top_rate: float = 0.2
    other_rate: float = 0.1
    # Preemption-safe training (ISSUE 14, lightgbm_tpu/checkpoint.py):
    # checkpoint_interval > 0 makes run_training write an atomic
    # checkpoint file (model + sampler/RNG counters + iteration +
    # best_score/best_iter + config fingerprint) every that-many
    # consumed iterations, on a background writer thread OFF the
    # pipelined readback path — plus one synchronous final checkpoint.
    # A task=train restart with the same checkpoint_dir resumes from the
    # latest checkpoint: bit-identically on the same topology, at the
    # documented cross-schedule budget on a different one (elastic
    # restart re-runs factor_machines on the surviving machine count).
    # 0 disables.  checkpoint_dir must be set when the interval is;
    # checkpoint_keep (>= 1) bounds how many finished checkpoint files
    # are retained (the atomic write-temp+rename discipline means a
    # crash mid-write always leaves the previous one loadable).
    checkpoint_interval: int = 0
    checkpoint_dir: str = ""
    checkpoint_keep: int = 2
    # Live straggler mitigation (ISSUE 14, lightgbm_tpu/elastic.py):
    # elastic_shrink=true arms the drain-at-iteration-boundary mesh
    # shrink — when the persistent-straggler rule (the SAME
    # strictly-slowest->=straggler_k-consecutive-iterations logic
    # scripts/timeline_report.py flags post-mortem) fires, the trainer
    # checkpoints, drops the flagged slot, re-runs factor_machines on
    # the surviving machine count and resumes.  Requires a parallel
    # tree_learner (there is no mesh to shrink under serial).
    elastic_shrink: bool = False
    straggler_k: int = 3
    tree_config: TreeConfig = dataclasses.field(default_factory=TreeConfig)

    def set(self, params: Dict[str, str]) -> None:
        self.num_iterations = _get_int(params, "num_iterations", self.num_iterations)
        log.check(self.num_iterations >= 0, "num_iterations should be >= 0")
        self.bagging_seed = _get_int(params, "bagging_seed", self.bagging_seed)
        self.bagging_freq = _get_int(params, "bagging_freq", self.bagging_freq)
        log.check(self.bagging_freq >= 0, "bagging_freq should be >= 0")
        self.bagging_fraction = _get_float(params, "bagging_fraction", self.bagging_fraction)
        log.check(0.0 < self.bagging_fraction <= 1.0,
                  "bagging_fraction should be in (0, 1]")
        self.learning_rate = _get_float(params, "learning_rate", self.learning_rate)
        log.check(self.learning_rate > 0.0, "learning_rate should be > 0")
        self.early_stopping_round = _get_int(params, "early_stopping_round",
                                             self.early_stopping_round)
        log.check(self.early_stopping_round >= 0, "early_stopping_round should be >= 0")
        self.output_freq = _get_int(params, "metric_freq", self.output_freq)
        log.check(self.output_freq >= 0, "metric_freq should be >= 0")
        self.is_provide_training_metric = _get_bool(params, "is_training_metric",
                                                    self.is_provide_training_metric)
        self.num_class = _get_int(params, "num_class", self.num_class)
        log.check(self.num_class >= 1, "num_class should be >= 1")
        if "health" in params:
            value = params["health"].lower()
            log.check(value in ("auto", "true", "false"),
                      "health must be auto, true or false")
            self.health = value
        if "on_anomaly" in params:
            value = params["on_anomaly"].lower()
            log.check(value in ("warn", "halt", "record"),
                      "on_anomaly must be warn, halt or record")
            self.on_anomaly = value
        self.health_divergence_rounds = _get_int(
            params, "health_divergence_rounds", self.health_divergence_rounds)
        log.check(self.health_divergence_rounds >= 0,
                  "health_divergence_rounds should be >= 0")
        if "pipeline" in params:
            value = params["pipeline"].lower()
            log.check(value in ("auto", "off", "readback"),
                      "pipeline must be auto, off or readback")
            self.pipeline = value
        if "bagging_device" in params:
            value = params["bagging_device"].lower()
            log.check(value in ("auto", "true", "false"),
                      "bagging_device must be auto, true or false")
            self.bagging_device = value
        self.goss = _get_bool(params, "goss", self.goss)
        self.top_rate = _get_float(params, "top_rate", self.top_rate)
        self.other_rate = _get_float(params, "other_rate", self.other_rate)
        if self.goss:
            log.check(0.0 <= self.top_rate < 1.0,
                      "top_rate should be in [0, 1)")
            log.check(0.0 < self.other_rate <= 1.0,
                      "other_rate should be in (0, 1]")
            log.check(self.top_rate + self.other_rate <= 1.0,
                      "top_rate + other_rate should be <= 1")
            if self.bagging_fraction < 1.0 and self.bagging_freq > 0:
                log.fatal("Cannot use bagging in GOSS mode "
                          "(goss=true with bagging_fraction < 1)")
        self.checkpoint_interval = _get_int(params, "checkpoint_interval",
                                            self.checkpoint_interval)
        log.check(self.checkpoint_interval >= 0,
                  "checkpoint_interval should be >= 0 (0 disables)")
        self.checkpoint_dir = _get_str(params, "checkpoint_dir",
                                       self.checkpoint_dir)
        if self.checkpoint_interval > 0 and not self.checkpoint_dir:
            log.fatal("checkpoint_interval > 0 requires checkpoint_dir "
                      "(where should the checkpoints go?)")
        self.checkpoint_keep = _get_int(params, "checkpoint_keep",
                                        self.checkpoint_keep)
        log.check(self.checkpoint_keep >= 1,
                  "checkpoint_keep should be >= 1 (the latest checkpoint "
                  "must survive)")
        self.elastic_shrink = _get_bool(params, "elastic_shrink",
                                        self.elastic_shrink)
        self.straggler_k = _get_int(params, "straggler_k", self.straggler_k)
        log.check(self.straggler_k >= 1, "straggler_k should be >= 1")
        if "tree_learner" in params:
            value = params["tree_learner"].lower()
            if value == "serial":
                self.tree_learner = "serial"
            elif value in ("feature", "feature_parallel"):
                self.tree_learner = "feature"
            elif value in ("data", "data_parallel"):
                self.tree_learner = "data"
            elif value == "hybrid":
                # 2-D (data, feature) mesh: rows sharded on ``data``,
                # feature-block ownership on ``feature`` (ISSUE 9)
                self.tree_learner = "hybrid"
            elif value in ("voting", "voting_parallel"):
                # the reference NAMES voting but Fatals on it
                # (src/io/config.cpp:311-313); here it is realized: top-k
                # per-shard split voting, full histograms exchanged only
                # for the voted features (ISSUE 9)
                self.tree_learner = "voting"
            else:
                log.fatal("Tree learner type error")
        self.tree_config.set(params)


@dataclasses.dataclass
class NetworkConfig:
    """Reference config.h:201-209.

    On TPU the machine list / listen port map to ``jax.distributed`` process
    bootstrap; ``num_machines`` becomes the size of the mesh axis used by the
    parallel tree learners.
    """
    num_machines: int = 1
    local_listen_port: int = 12400
    time_out: int = 120
    machine_list_filename: str = ""

    def set(self, params: Dict[str, str]) -> None:
        self.num_machines = _get_int(params, "num_machines", self.num_machines)
        log.check(self.num_machines >= 1, "num_machines should be >= 1")
        self.local_listen_port = _get_int(params, "local_listen_port", self.local_listen_port)
        log.check(self.local_listen_port > 0, "local_listen_port should be > 0")
        self.time_out = _get_int(params, "time_out", self.time_out)
        log.check(self.time_out > 0, "time_out should be > 0")
        self.machine_list_filename = _get_str(params, "machine_list_file",
                                              self.machine_list_filename)


@dataclasses.dataclass
class OverallConfig:
    """Reference config.h:212-243 + config.cpp:33-182."""
    task_type: str = "train"
    num_threads: int = 0
    is_parallel: bool = False
    is_parallel_find_bin: bool = False
    predict_leaf_index: bool = False
    boosting_type: str = "gbdt"
    objective_type: str = "regression"
    metric_types: List[str] = dataclasses.field(default_factory=list)
    network_config: NetworkConfig = dataclasses.field(default_factory=NetworkConfig)
    io_config: IOConfig = dataclasses.field(default_factory=IOConfig)
    boosting_config: BoostingConfig = dataclasses.field(default_factory=BoostingConfig)
    objective_config: ObjectiveConfig = dataclasses.field(default_factory=ObjectiveConfig)
    metric_config: MetricConfig = dataclasses.field(default_factory=MetricConfig)
    # TPU addition: device placement for the tree learner ("tpu"/"cpu"; any
    # value accepted, resolved against jax.devices()).
    device_type: str = ""

    def set(self, params: Dict[str, str], require_data: bool = True) -> None:
        params = apply_aliases(params)
        self.num_threads = _get_int(params, "num_threads", self.num_threads)
        if "task" in params:
            value = params["task"].lower()
            if value in ("train", "training"):
                self.task_type = "train"
            elif value in ("predict", "prediction", "test"):
                self.task_type = "predict"
            else:
                log.fatal("Task type error")
        self.predict_leaf_index = _get_bool(params, "predict_leaf_index",
                                            self.predict_leaf_index)
        if "boosting_type" in params:
            value = params["boosting_type"].lower()
            if value in ("gbdt", "gbrt"):
                self.boosting_type = "gbdt"
            else:
                log.fatal("Boosting type %s error" % value)
        if "objective" in params:
            self.objective_type = params["objective"].lower()
        if "metric" in params:
            seen = []
            for m in params["metric"].lower().split(","):
                m = m.strip()
                if m and m not in seen:
                    seen.append(m)
            self.metric_types = seen
        self.device_type = _get_str(params, "device_type", self.device_type)
        self.network_config.set(params)
        self.io_config.set(params, require_data=require_data)
        self.boosting_config.set(params)
        self.objective_config.set(params)
        self.metric_config.set(params)
        self._check_param_conflict()
        # verbosity → log level (config.cpp:59-70); the mapping lives in
        # utils/log so the CLI and library entries share one rule
        log.set_level_from_verbosity(self.io_config.verbosity)

    def _check_param_conflict(self) -> None:
        """Reference config.cpp:133-182."""
        objective_multiclass = self.objective_type == "multiclass"
        num_class = self.boosting_config.num_class
        if objective_multiclass:
            if num_class <= 1:
                log.fatal("You should specify number of class(>=2) for multiclass training.")
        else:
            if self.task_type == "train" and num_class != 1:
                log.fatal("Number of class must be 1 for non-multiclass training.")
        for metric_type in self.metric_types:
            metric_multiclass = metric_type in ("multi_logloss", "multi_error")
            if objective_multiclass != metric_multiclass:
                log.fatal("Objective and metrics don't match.")
        if self.network_config.num_machines > 1:
            self.is_parallel = True
        else:
            self.is_parallel = False
            self.boosting_config.tree_learner = "serial"
        if self.boosting_config.tree_learner == "serial":
            self.is_parallel = False
            self.network_config.num_machines = 1
        if self.boosting_config.elastic_shrink and not self.is_parallel:
            log.fatal("elastic_shrink=true requires a parallel "
                      "tree_learner and num_machines > 1 (there is no "
                      "mesh to shrink under serial training)")
        if self.io_config.slo_p99_us > 0 and self.task_type != "predict":
            log.fatal("slo_p99_us > 0 requires task=predict (the SLO "
                      "watches the serving front's serve_wall_us "
                      "family; a training run has no serving latency "
                      "to burn)")
        if self.boosting_config.tree_learner in ("serial", "feature"):
            self.is_parallel_find_bin = False
        elif self.boosting_config.tree_learner in ("data", "hybrid",
                                                   "voting"):
            # hybrid/voting shard rows over the data axis exactly like
            # tree_learner=data, so they take the same distributed bin
            # finding + LRU-queue-off treatment
            self.is_parallel_find_bin = True
            if self.boosting_config.tree_config.histogram_pool_size >= 0:
                log.warning(
                    "Histogram LRU queue was enabled (histogram_pool_size=%f). "
                    "Will disable this for reducing communication cost."
                    % self.boosting_config.tree_config.histogram_pool_size)
                self.boosting_config.tree_config.histogram_pool_size = -1


def parse_config_file(path: str) -> Dict[str, str]:
    """Parse a .conf file: ``key = value`` lines, ``#`` comments
    (application.cpp:78-113)."""
    params: Dict[str, str] = {}
    with open(path, "r") as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            if "=" not in line:
                continue
            key, value = line.split("=", 1)
            key = key.strip().strip('"').strip("'")
            value = value.strip().strip('"').strip("'")
            if key:
                params[key] = value
    return params


def parse_argv(args: List[str]) -> Dict[str, str]:
    """Parse CLI ``key=value`` tokens (application.cpp:59-76)."""
    params: Dict[str, str] = {}
    for arg in args:
        if "=" not in arg:
            log.warning("Unknown parameter %s" % arg)
            continue
        key, value = arg.split("=", 1)
        key = key.strip().strip('"').strip("'")
        value = value.strip().strip('"').strip("'")
        if key:
            params[key] = value
    return params


def load_config(argv: List[str]) -> OverallConfig:
    """argv pairs + optional config file; argv wins (application.cpp:98)."""
    cli_params = parse_argv(argv)
    cli_params = apply_aliases(cli_params)
    params: Dict[str, str] = {}
    if "config_file" in cli_params:
        params.update(parse_config_file(cli_params["config_file"]))
    # argv has higher priority
    params.update(cli_params)
    config = OverallConfig()
    config.set(params)
    return config
