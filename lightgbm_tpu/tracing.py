"""Flight recorder + per-request latency attribution (ISSUE 16).

The telemetry registry (telemetry.py) answers "how much, cumulatively":
``serve/linger_wait_us`` and friends are monotone sums, and percentiles
exist only inside bench runs.  Nobody could say whether a slow request
spent its time in the queue, in linger, in pad waste or in the device
walk.  This module is the missing *per-event* tier, layered UNDER the
telemetry session (armed/disarmed around it, mirrored into its
counters), with three hard properties:

1. **Exact attribution.**  Every ``ServingFront`` request gets a trace
   id and a monotonic event timeline — enqueue → queue-wait →
   linger-wait → coalesce (batch id, bucket, pad-waste rows) → dispatch
   → device walk (fenced) → scatter → complete.  All boundaries are
   integer ``time.perf_counter_ns()`` stamps; :func:`attribute` clamps
   the batch-level boundaries into each request's [enqueue, complete]
   window and takes consecutive differences, so the six named components
   telescope to EXACTLY the observed wall time — an identity, not an
   approximation (tests/test_tracing.py pins it per request, including
   across a mid-load ``swap_engine``).  Backpressure-block and
   swap/drain events ride the same timeline.

2. **Bounded overhead, crash-safe.**  The recorder is a PREALLOCATED
   ring (``trace_ring_events`` slots; drops oldest, counts
   ``trace/dropped`` exactly).  ``trace_dump_dir=`` flushes the ring to
   JSONL atomically (tmp + rename) on clean close AND from the faults.py
   raise hatch / ``run_training``'s crash-flush path, so a
   SIGKILL-adjacent failure leaves a readable last-N-events timeline
   next to the checkpoint.  ``scripts/trace_report.py`` renders dumps
   and ``--check``-validates the identity and event ordering.

3. **Streaming percentiles.**  :class:`LatencySketch` is a fixed-memory
   log-bucket (HDR-style) histogram: bucket ``i`` holds values in
   ``[g**i, g**(i+1))`` for growth factor ``g`` (``trace_sketch_growth``,
   default 1.05), so any quantile is available LIVE within a factor
   ``sqrt(g)`` of the true sample quantile, and merge across
   threads/hosts is plain count addition (associative — test-pinned).
   bench.py computes ``serve_p99_us`` from the sketch and A/B-pins
   sketch-vs-sorted agreement within bucket resolution.

Training events land in the same ring: per-iteration records
(``record_train_iteration`` from ``telemetry.emit_iteration``, sharing
the timeline-shard record keys ``iter``/``phase_times``/``t``), chunk
boundaries, checkpoint write/drop, GOSS/bagging draws and elastic
shrinks — so one dump explains both a slow request and a stalled
training loop.

Pod scope (ISSUE 17): every dump header carries the recording host's
identity — hostname, (process_index, process_count) when known, and the
operator-assigned ``run_id`` (:func:`set_identity`) — so
``lightgbm_tpu/podtrace.py`` can align per-host clocks on matched
``collective_sync`` events (:func:`record_collective_sync` stamps both
edges of a blocking collective, the honest offset bound) and merge the
rings into one global timeline; sketches merge via the associative
bucket addition above.  Ingest attribution
(:func:`record_ingest_chunk` / :func:`record_ingest_pass`) and the
small monotone :func:`bump` counters (serialized in the header) ride
the same ring.

Counter contract (censused by graftlint D1): the recorder mirrors
``trace/dropped`` (ring overwrites) and ``trace/dumps`` (dump files
written) into the telemetry registry; the dump writer runs under the
``trace_dump`` telemetry span.  Pure stdlib — no JAX imports, safe from
fault/crash paths and import-order hazards.  The armed recorder is
process-global state: a lifecycle probe (``trace-recorder``) makes the
conftest leak guard fail any test that leaves it armed.
"""
from __future__ import annotations

import json
import math
import os
import socket
import threading
import time
from typing import Dict, List, Optional

from . import lifecycle, telemetry

DEFAULT_RING_EVENTS = 65536
DEFAULT_SKETCH_GROWTH = 1.05
# growth-factor bounds: below the floor the bucket table stops being
# "fixed-memory" in any useful sense (~1.4M buckets over a ns..hour
# range); above 2.0 a "percentile" is off by up to 2x — useless
SKETCH_GROWTH_MIN = 1.0005
SKETCH_GROWTH_MAX = 2.0

# the six per-request latency components, in timeline order; attribute()
# guarantees their sum telescopes exactly to the request wall time
COMPONENTS = ("queue", "linger", "coalesce", "dispatch", "walk", "scatter")


# ------------------------------------------------------------------ sketches

class LatencySketch:
    """Fixed-memory log-bucket histogram (HDR-style).

    ``record(v)`` lands ``v`` in bucket ``floor(log(v)/log(g))``; the
    representative of a bucket is its geometric midpoint ``g**(i+0.5)``,
    so any reported quantile is within a factor ``sqrt(g)`` of the true
    sample value at the same rank (relative error <= g - 1).  Values
    <= 0 land in a dedicated zero bucket and report as 0.0.  ``merge``
    is bucket-count addition — associative and commutative, the
    cross-thread / cross-host fold."""

    __slots__ = ("growth", "_log_g", "zero", "buckets")

    def __init__(self, growth: float = DEFAULT_SKETCH_GROWTH):
        growth = float(growth)
        if not (SKETCH_GROWTH_MIN <= growth <= SKETCH_GROWTH_MAX):
            raise ValueError(
                "sketch growth must be in [%g, %g], got %g"
                % (SKETCH_GROWTH_MIN, SKETCH_GROWTH_MAX, growth))
        self.growth = growth
        self._log_g = math.log(growth)
        self.zero = 0
        self.buckets: Dict[int, int] = {}

    def record(self, value: float, n: int = 1) -> None:
        if value <= 0:
            self.zero += n
            return
        idx = int(math.floor(math.log(value) / self._log_g))
        self.buckets[idx] = self.buckets.get(idx, 0) + n

    @property
    def count(self) -> int:
        return self.zero + sum(self.buckets.values())

    def merge(self, other: "LatencySketch") -> "LatencySketch":
        if abs(other.growth - self.growth) > 1e-12:
            raise ValueError("cannot merge sketches with different growth "
                             "factors (%g vs %g)"
                             % (self.growth, other.growth))
        self.zero += other.zero
        for i, c in other.buckets.items():
            self.buckets[i] = self.buckets.get(i, 0) + c
        return self

    def quantile(self, q: float) -> Optional[float]:
        """The value at rank ``ceil(q * count) - 1`` of the sorted sample
        (the "nearest-rank" convention), to bucket resolution.  None on
        an empty sketch."""
        total = self.count
        if total == 0:
            return None
        rank = min(total - 1, max(0, int(math.ceil(q * total)) - 1))
        if rank < self.zero:
            return 0.0
        seen = self.zero
        for i in sorted(self.buckets):
            seen += self.buckets[i]
            if rank < seen:
                return self.growth ** (i + 0.5)
        return self.growth ** (max(self.buckets) + 0.5)  # pragma: no cover

    def mean(self) -> Optional[float]:
        """Approximate mean (each bucket at its representative) — same
        sqrt(growth) relative-resolution contract as the quantiles."""
        total = self.count
        if total == 0:
            return None
        s = sum(c * self.growth ** (i + 0.5)
                for i, c in self.buckets.items())
        return s / total

    def percentiles(self) -> dict:
        return {
            "count": self.count,
            "mean": self.mean(),
            "p50": self.quantile(0.50),
            "p99": self.quantile(0.99),
            "p999": self.quantile(0.999),
        }

    def to_dict(self) -> dict:
        return {"growth": self.growth, "zero": self.zero,
                "buckets": {str(i): c for i, c in self.buckets.items()}}

    @classmethod
    def from_dict(cls, d: dict) -> "LatencySketch":
        sk = cls(d.get("growth", DEFAULT_SKETCH_GROWTH))
        sk.zero = int(d.get("zero", 0))
        sk.buckets = {int(i): int(c)
                      for i, c in d.get("buckets", {}).items()}
        return sk


# ------------------------------------------------------------ recorder state

_lock = threading.Lock()
_armed = False
_ring: List[Optional[dict]] = []
_cap = 0
_appended = 0                 # events ever appended since arm (monotone)
_dropped_synced = 0           # portion already mirrored into telemetry
_dump_dir = ""
_default_ring = True          # armed at DEFAULT_RING_EVENTS (perf_gate's
#                               trace_dropped_at_default reads this)
_growth = DEFAULT_SKETCH_GROWTH
_sketches: Dict[str, LatencySketch] = {}
_trace_seq = 0
_batch_seq = 0
_dumps = 0
_counters: Dict[str, int] = {}
_tls = threading.local()

# host/process identity stamped into every dump header (pod-scope merge
# key).  Survives arm/disarm — it describes the PROCESS, not the session
# — and is overwritten, never merged: latest set_identity() wins.
_host = socket.gethostname()
_process_index: Optional[int] = None
_process_count: Optional[int] = None
_run_id = ""
_UNSET = object()


def set_identity(process_index=_UNSET, process_count=_UNSET,
                 run_id=_UNSET) -> None:
    """Install the recorder's pod identity: ``(process_index,
    process_count)`` from the distributed runtime (telemetry pushes it
    when shard identity resolves) and the operator-assigned ``run_id``
    (``trace_run_id`` knob) that marks which dumps belong to one run.
    Omitted arguments keep their current value; pass ``None`` (or ``""``
    for run_id) to clear.  Callable before or after :func:`arm`."""
    global _process_index, _process_count, _run_id
    with _lock:
        if process_index is not _UNSET:
            _process_index = (None if process_index is None
                              else int(process_index))
        if process_count is not _UNSET:
            _process_count = (None if process_count is None
                              else int(process_count))
        if run_id is not _UNSET:
            _run_id = str(run_id or "")


def identity() -> dict:
    """The header identity block as it would be dumped right now."""
    with _lock:
        return {"host": _host, "pid": os.getpid(),
                "process_index": _process_index,
                "process_count": _process_count, "run_id": _run_id}


def active() -> bool:
    """True while the recorder is armed — the hot-path gate every
    instrumentation site checks first (one module-global read)."""
    return _armed


def default_ring() -> bool:
    """True when the armed ring is at DEFAULT_RING_EVENTS — drops at the
    default size are an absolute perf_gate finding; drops at a
    deliberately tiny test ring are not."""
    return _default_ring


def arm(ring_events: int = DEFAULT_RING_EVENTS, dump_dir: str = "",
        sketch_growth: float = DEFAULT_SKETCH_GROWTH) -> None:
    """Arm (or re-arm, resetting ring/sketches/ids) the recorder.

    ``ring_events`` is the preallocated event capacity (> 0);
    ``dump_dir`` (optional) is where disarm/fault dumps land;
    ``sketch_growth`` the log-bucket factor.  Invalid values raise —
    config.py rejects them loudly before they ever reach here."""
    global _armed, _ring, _cap, _appended, _dropped_synced, _dump_dir
    global _growth, _trace_seq, _batch_seq, _dumps, _default_ring
    ring_events = int(ring_events)
    if ring_events <= 0:
        raise ValueError("trace_ring_events must be > 0, got %d"
                         % ring_events)
    if not (SKETCH_GROWTH_MIN <= float(sketch_growth) <= SKETCH_GROWTH_MAX):
        raise ValueError("trace_sketch_growth must be in [%g, %g], got %g"
                         % (SKETCH_GROWTH_MIN, SKETCH_GROWTH_MAX,
                            float(sketch_growth)))
    with _lock:
        _cap = ring_events
        _default_ring = ring_events == DEFAULT_RING_EVENTS
        _ring = [None] * _cap
        _appended = 0
        _dropped_synced = 0
        _dump_dir = str(dump_dir or "")
        _growth = float(sketch_growth)
        _sketches.clear()
        _counters.clear()
        _trace_seq = 0
        _batch_seq = 0
        _dumps = 0
        _armed = True


def disarm() -> Optional[str]:
    """Disarm and clear the recorder.  When a dump dir is configured and
    any event was recorded, the ring is flushed first (reason "close") —
    the clean-shutdown half of the crash-safety contract.  Returns the
    dump path (or None).  Idempotent."""
    global _armed, _ring, _cap, _appended, _dump_dir
    if not _armed:
        return None
    path = None
    if _dump_dir and _appended > 0:
        path = dump(reason="close")
    with _lock:
        _sync_dropped_locked()
        _armed = False
        _ring = []
        _cap = 0
        _appended = 0
        _dump_dir = ""
        _sketches.clear()
        _counters.clear()
    _tls.batch = None
    return path


# the armed recorder is process-global state like the fault hatch: ONE
# registry feeds the conftest leak guard and graftlint's C1 census
lifecycle.probe("trace-recorder", active, disarm)


def _append_locked(ev: dict) -> None:
    global _appended
    _ring[_appended % _cap] = ev
    _appended += 1


def _events_locked() -> List[dict]:
    """Ring contents oldest-first (the deterministic oldest-drop
    contract the overflow test pins)."""
    if _appended <= _cap:
        return [e for e in _ring[:_appended]]
    start = _appended % _cap
    return _ring[start:] + _ring[:start]


def _sync_dropped_locked() -> None:
    """Mirror ring overwrites into the telemetry counter as a delta, so
    ``trace/dropped`` is exact however often snapshots/dumps run."""
    global _dropped_synced
    d = max(0, _appended - _cap)
    if d > _dropped_synced:
        telemetry.count("trace/dropped", d - _dropped_synced)
        _dropped_synced = d


def _observe_locked(family: str, value_us: float) -> None:
    sk = _sketches.get(family)
    if sk is None:
        sk = _sketches[family] = LatencySketch(_growth)
    sk.record(value_us)


def event(kind: str, **fields) -> None:
    """Append one timeline event.  No-op while disarmed; hot-path cost
    is one dict build + one locked list store."""
    if not _armed:
        return
    ev = {"kind": str(kind), "t": round(time.time(), 6)}
    ev.update(fields)
    with _lock:
        if _armed:
            _append_locked(ev)


def observe(family: str, value_us: float) -> None:
    """Record one latency observation (microseconds) into the family's
    streaming sketch.  No-op while disarmed."""
    if not _armed:
        return
    with _lock:
        if _armed:
            _observe_locked(family, value_us)


def bump(name: str, n: int = 1) -> None:
    """Increment a small monotone per-session counter (serialized into
    the dump header's ``counters`` block — per-bucket dispatch counts
    and other SLO-prep tallies too cheap and too many for the telemetry
    registry's censused families).  No-op while disarmed."""
    if not _armed:
        return
    with _lock:
        if _armed:
            _counters[name] = _counters.get(name, 0) + int(n)


def counter(name: str) -> int:
    with _lock:
        return _counters.get(name, 0)


def record_collective_sync(site: str, iteration: int,
                           t_begin_s: float, t_end_s: float,
                           pod: bool = False) -> None:
    """File one executed blocking collective: both wall-clock edges of
    the host-side block (NOT the trace-time record telemetry keeps).

    Every participant exits a collective within its own blocked window
    of the last arrival, so matched ``(site, iter)`` exit stamps across
    hosts estimate the inter-host clock offset with error bounded by
    ``max(duration_a, duration_b)`` — podtrace records that bound, never
    pretending better.  ``pod=True`` marks a collective that actually
    spanned processes (process_count > 1); only those are valid
    alignment sync points — a process-local collective is a seam timing
    sample but says nothing about another host's clock."""
    if not _armed:
        return
    t0, t1 = float(t_begin_s), float(t_end_s)
    dur_us = max(t1 - t0, 0.0) * 1e6
    ev = {"kind": "collective_sync", "t": round(t1, 6),
          "site": str(site), "iter": int(iteration),
          "t0": round(t0, 6), "t1": round(t1, 6),
          "dur_us": round(dur_us, 1), "pod": bool(pod)}
    with _lock:
        if _armed:
            _append_locked(ev)
            _observe_locked("collective_sync_us", dur_us)


def record_ingest_pass(pass_no: int, seconds: float, rows: int) -> None:
    """File one completed ingest pass (0 = row count, 1 = feature/label
    scan, 2 = tokenize+bin+H2D) — the coarse lane of the ingest
    attribution story."""
    if not _armed:
        return
    ev = {"kind": "ingest_pass", "t": round(time.time(), 6),
          "pass": int(pass_no), "seconds": round(float(seconds), 6),
          "rows": int(rows)}
    with _lock:
        if _armed:
            _append_locked(ev)


def record_ingest_chunk(pass_no: int, chunk: int, rows: int,
                        parse_us: float, bin_us: float,
                        h2d_us: float, worker: int = None) -> None:
    """File one streamed chunk's phase split — tokenizer (parse) vs
    value->bin mapping vs H2D handoff (device_put + row-writer append;
    the async tail is priced by the ``ingest_h2d`` span at finish).
    Sketches accumulate each phase so a dump explains WHERE the
    declining ingest_rows_per_sec lane spends its time.  ``worker``
    tags events from the parallel byte-range loader with the worker
    process id, so per-worker parse spans are reconstructable from the
    ring."""
    if not _armed:
        return
    ev = {"kind": "ingest_chunk", "t": round(time.time(), 6),
          "pass": int(pass_no), "chunk": int(chunk), "rows": int(rows),
          "parse_us": round(float(parse_us), 1),
          "bin_us": round(float(bin_us), 1),
          "h2d_us": round(float(h2d_us), 1)}
    if worker is not None:
        ev["worker"] = int(worker)
    with _lock:
        if _armed:
            _append_locked(ev)
            _observe_locked("ingest_parse_us", float(parse_us))
            _observe_locked("ingest_bin_us", float(bin_us))
            _observe_locked("ingest_h2d_us", float(h2d_us))


def next_trace_id() -> int:
    """Fresh per-request trace id (0 while disarmed — requests are not
    traced, and 0 marks them so)."""
    global _trace_seq
    if not _armed:
        return 0
    with _lock:
        _trace_seq += 1
        return _trace_seq


def dropped() -> int:
    return max(0, _appended - _cap) if _armed else 0


def ring_events() -> int:
    return _cap if _armed else 0


def sketch(family: str) -> Optional[LatencySketch]:
    with _lock:
        return _sketches.get(family)


def cumulative_state() -> Optional[dict]:
    """One consistent copy of the recorder's cumulative tallies —
    per-family sketches (deep-copied, caller-owned) and the small bump
    counters — read under a SINGLE lock acquisition, so a windowed
    consumer (monitor.py) can subtract two calls and get exact interval
    deltas: no counter can advance between the sketch copy and the
    counter copy.  None while disarmed."""
    if not _armed:
        return None
    with _lock:
        if not _armed:
            return None
        sketches = {}
        for fam, sk in _sketches.items():
            cp = LatencySketch(sk.growth)
            cp.zero = sk.zero
            cp.buckets = dict(sk.buckets)
            sketches[fam] = cp
        return {
            "sketches": sketches,
            "counters": dict(_counters),
            "appended": _appended,
            "dropped": max(0, _appended - _cap),
            "sketch_growth": _growth,
        }


# ------------------------------------------------------- batch trace (TLS)

class BatchTrace:
    """Per-coalesced-batch marks the engine fills in while scoring on
    the worker thread.  Installed thread-locally by the front
    (``begin_batch``) and consulted by ``ServingEngine._bucketed`` via
    ``current_batch()`` — direct engine calls see None and skip."""

    __slots__ = ("batch_id", "bucket", "pad_rows", "run_begin_ns",
                 "dispatched_ns", "run_end_ns")

    def __init__(self, batch_id: int):
        self.batch_id = batch_id
        self.bucket = 0
        self.pad_rows = 0
        self.run_begin_ns: Optional[int] = None
        self.dispatched_ns: Optional[int] = None
        self.run_end_ns: Optional[int] = None

    def mark_run_begin(self) -> None:
        if self.run_begin_ns is None:
            self.run_begin_ns = time.perf_counter_ns()

    def mark_dispatched(self) -> None:
        self.dispatched_ns = time.perf_counter_ns()

    def mark_run_end(self) -> None:
        self.run_end_ns = time.perf_counter_ns()

    def add_pad(self, rows: int) -> None:
        self.pad_rows += int(rows)

    def set_bucket(self, bucket: int) -> None:
        self.bucket = max(self.bucket, int(bucket))


def begin_batch() -> BatchTrace:
    global _batch_seq
    with _lock:
        _batch_seq += 1
        bid = _batch_seq
    bt = BatchTrace(bid)
    _tls.batch = bt
    return bt


def current_batch() -> Optional[BatchTrace]:
    return getattr(_tls, "batch", None)


def end_batch() -> None:
    _tls.batch = None


# ------------------------------------------------------------- attribution

def attribute(t_enq_ns: int, t_done_ns: int,
              bounds_ns) -> Dict[str, int]:
    """Decompose one request's wall time into the six COMPONENTS.

    ``bounds_ns`` is the five batch-level boundary stamps
    (linger_begin, batch_formed, run_begin, dispatched, scores_returned)
    — any may be None (a missing mark inherits its predecessor).  Each
    boundary is clamped monotonically into [t_enq_ns, t_done_ns]; the
    components are consecutive INTEGER differences of the clamped
    edges, so ``sum(components) == t_done_ns - t_enq_ns`` holds exactly
    — the identity trace_report --check and the tests pin."""
    ts = int(t_enq_ns)
    td = max(int(t_done_ns), ts)
    prev = ts
    edges = [ts]
    for b in bounds_ns:
        b = prev if b is None else int(b)
        b = min(max(b, prev), td)
        edges.append(b)
        prev = b
    edges.append(td)
    return {name: edges[i + 1] - edges[i]
            for i, name in enumerate(COMPONENTS)}


def record_serve_request(trace_id: int, batch: Optional[BatchTrace],
                         t_enq_ns: int, t_done_ns: int, bounds_ns,
                         rows: int, block_ns: int = 0) -> Dict[str, int]:
    """File one completed request: the ``serve_complete`` timeline event
    plus sketch observations for the wall and every component.  Returns
    the component dict (the tests' identity probe).  Safe to call while
    disarmed (pure computation, nothing recorded)."""
    comps = attribute(t_enq_ns, t_done_ns, bounds_ns)
    if not _armed:
        return comps
    wall_ns = max(int(t_done_ns) - int(t_enq_ns), 0)
    ev = {"kind": "serve_complete", "t": round(time.time(), 6),
          "trace": int(trace_id), "rows": int(rows),
          "t_enq_ns": int(t_enq_ns), "wall_ns": wall_ns,
          "components_ns": comps}
    if batch is not None:
        ev["batch"] = batch.batch_id
        ev["bucket"] = batch.bucket
        ev["pad_rows"] = batch.pad_rows
    if block_ns > 0:
        ev["block_ns"] = int(block_ns)
    with _lock:
        if not _armed:
            return comps
        _append_locked(ev)
        _observe_locked("serve_wall_us", wall_ns / 1e3)
        for name in COMPONENTS:
            _observe_locked("serve_%s_us" % name, comps[name] / 1e3)
    return comps


def record_train_iteration(iteration: int,
                           phase_times: Dict[str, float]) -> None:
    """File one boosting iteration into the ring (same record keys as
    the timeline shards: iter / phase_times / t) and its total phase
    seconds into the ``train_iter_us`` sketch.  Called from
    ``telemetry.emit_iteration``."""
    if not _armed:
        return
    total_us = 1e6 * float(sum(phase_times.values()))
    ev = {"kind": "train_iter", "t": round(time.time(), 6),
          "iter": int(iteration), "phase_times": dict(phase_times)}
    with _lock:
        if not _armed:
            return
        _append_locked(ev)
        _observe_locked("train_iter_us", total_us)


# ------------------------------------------------------------------ output

def snapshot() -> dict:
    """Live recorder state: ring occupancy, exact drop count, per-family
    sketch percentiles.  {} while disarmed."""
    if not _armed:
        return {}
    with _lock:
        if not _armed:
            return {}
        _sync_dropped_locked()
        return {
            "ring_events": _cap,
            "events": min(_appended, _cap),
            "appended": _appended,
            "dropped": max(0, _appended - _cap),
            "dumps": _dumps,
            "default_ring": _default_ring,
            "sketch_growth": _growth,
            "sketches": {f: sk.percentiles()
                         for f, sk in sorted(_sketches.items())},
            "counters": dict(sorted(_counters.items())),
        }


def dump(path: Optional[str] = None, reason: str = "close"
         ) -> Optional[str]:
    """Flush the ring to JSONL atomically (tmp + rename): one
    ``trace_header`` line (reason, counts, serialized sketches), then
    every retained event oldest-first.  ``path`` defaults to a fresh
    ``trace-<pid>-<k>.jsonl`` under the armed dump dir.  Never raises —
    an unwritable target warns and returns None (telemetry's
    failure-disables contract)."""
    global _dumps
    with _lock:
        if not _armed:
            return None
        _sync_dropped_locked()
        events = _events_locked()
        _dumps += 1
        seq = _dumps
        header = {"trace_header": {
            "reason": str(reason),
            "pid": os.getpid(),
            "host": _host,
            "process_index": _process_index,
            "process_count": _process_count,
            "run_id": _run_id,
            "t": round(time.time(), 6),
            "ring_events": _cap,
            "events": len(events),
            "appended": _appended,
            "dropped": max(0, _appended - _cap),
            "sketch_growth": _growth,
            "sketches": {f: sk.to_dict()
                         for f, sk in sorted(_sketches.items())},
            "counters": dict(sorted(_counters.items())),
        }}
        dump_dir = _dump_dir
    if path is None:
        if not dump_dir:
            return None
        path = os.path.join(dump_dir,
                            "trace-%d-%03d.jsonl" % (os.getpid(), seq))
    tmp = "%s.tmp-%d" % (path, os.getpid())
    try:
        with telemetry.span("trace_dump"):
            with open(tmp, "w") as f:
                f.write(json.dumps(header) + "\n")
                for ev in events:
                    f.write(json.dumps(ev) + "\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
    except OSError as e:
        from .utils import log
        log.warning("tracing: dump to %s failed (%s); dump skipped"
                    % (path, e))
        try:
            os.remove(tmp)
        except OSError:
            pass
        return None
    telemetry.count("trace/dumps")
    return path


def dump_on_fault(reason: str) -> Optional[str]:
    """Best-effort crash dump — the faults.py raise hatch and
    ``run_training``'s crash-flush path call this with the exception
    kind.  Never raises (a broken dump must not mask the real fault)."""
    try:
        if _armed and _dump_dir:
            return dump(reason="fault:%s" % reason)
    except Exception:  # pragma: no cover - absolute last resort
        pass
    return None
