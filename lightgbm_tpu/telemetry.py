"""Process-wide telemetry: phase timers, kernel-route counters, JSONL sink.

The repo's previous observability was three ad-hoc hacks: ``time.time()``
prints in cli.py, hist-stubbed A/B differencing in scripts/profile_phases.py
(PROFILE.md), and hand-assembled counter tables in BENCH rounds.  This module
replaces them with one registry, designed around two JAX realities:

1. **Route decisions are trace-time events.**  Kernel routing (Pallas int8 /
   bf16 / f32 hit, XLA einsum fallback, ``LGBM_TPU_NO_PALLAS`` trips,
   partition-kernel eligibility — ops/histogram.py, ops/compact.py) happens
   while a program is being *traced*; the compiled program then replays the
   chosen route forever.  Counters therefore increment once per traced
   decision — exactly the record of "which route did this program actually
   bake in" that the mixed-backend hardening episodes (commit e7ff0d9)
   lacked.  Recompiles are counted via a ``jax.monitoring`` backend-compile
   listener (cache hits fire nothing, so the count is true recompiles).

2. **Spans are host-side wall timers.**  ``span("histogram")`` times the
   enclosed *host* call with ``time.perf_counter``.  A span entered while
   JAX is tracing is recorded under ``trace_times`` (it measured tracing,
   not execution); a span entered with concrete arrays (the boosting loop's
   host phases, or any op under ``jax.disable_jit()``) is recorded under
   ``phase_times``.  The optional **fence mode** (``set_fence(True)`` /
   ``enable(fence=True)``) calls ``jax.block_until_ready`` on a value the
   caller hands to ``Span.fence(x)`` before stopping the timer, so async
   dispatch does not attribute device time to the wrong phase.  Fencing
   only *waits* on already-dispatched work — it never issues device
   computation — so it cannot trip the environment's ~60 s per-dispatch
   execution watchdog (BASELINE.md).

Zero overhead when disabled: every public entry checks one module flag and
returns a no-op singleton; nothing is ever inserted into traced programs,
so enabling/disabling telemetry perturbs neither numerics nor jit caching
(tests/test_telemetry.py locks this in).

JSONL sink: ``enable(jsonl_path)`` (the ``metrics_out=...`` config/CLI
option) arms a per-iteration record stream; the boosting loop emits one
line per iteration::

    {"iter": 3, "phase_times": {...}, "trace_times": {...},
     "counters": {...}, "eval_metrics": {...}}

``phase_times`` are seconds spent per phase *in that iteration* (chunked
training amortizes the fused k-iteration program evenly across its kept
iterations and marks ``"amortized_over": k``); ``counters`` are cumulative.
The canonical phase keys ``histogram``, ``split_find``, ``partition``,
``eval`` are always present.  In multi-process runs only process 0 opens
the sink (decided lazily at first write, after jax.distributed init);
``parallel.learners.aggregate_telemetry`` folds every host's counters into
the leader before the final summary record.  Library users who want the
data without a file call ``snapshot()``.

ISSUE 2 additions — the device-side observability triad:

3. **Memory gauges** (``set_memory(True)`` / ``enable(memory=True)``, the
   ``memory_stats=`` config option): spans additionally sample the device
   allocator (``device.memory_stats()``; host-RSS fallback on backends
   that return None, e.g. CPU) at their boundaries, recording per-phase
   byte deltas and a process-peak ``bytes_in_use`` watermark.  Iteration
   records gain a ``memory`` block (``take_memory_record``), the summary
   and ``snapshot()`` a cumulative one, and ``set_residency`` files the
   one-shot dataset-residency report (bin matrix / metadata / histogram
   scratch) at train start.  Sampling is a host-side stats read — it
   never dispatches device work.

4. **Profiler alignment**: every span body runs under
   ``jax.named_scope(name)`` + ``jax.profiler.TraceAnnotation(name)``, so
   a Perfetto trace captured via ``profile_dir=`` carries the SAME phase
   names as the JSONL records — device rows (HLO op metadata) and host
   timeline rows line up with ``phase_times`` keys.  Health events (NaN
   counts, saturation, divergence — lightgbm_tpu/health.py) ride the
   iteration records as a ``health`` block via ``emit_iteration``.

ISSUE 4 — roofline attribution and compile observability
(lightgbm_tpu/costmodel.py rides this registry's lifecycle):

5. **Roofline + compile blocks**: enable()/disable()/reset() arm the
   compiled-program cost registry alongside the spans, so the summary
   record and ``snapshot()`` carry a ``roofline`` block (per-phase static
   flops/bytes from ``compiled.cost_analysis()`` joined to the measured
   spans → attained FLOP/s, HBM GB/s, fraction-of-peak) and a ``compile``
   block (program inventory, cold compile seconds, persistent-cache
   hits, mid-run recompiles).  ``emit_iteration`` watches the
   backend-compile counter: a compile AFTER the first iteration record
   is a mid-run recompile — counted (``jit/midrun_recompile``) and
   warned once, because it means a program cache key failed to capture
   something that changed.

ISSUE 5 — distributed observability (per-collective wire metrics,
cross-host span shards, hung-collective flight recorder):

6. **Collective sites** (``collective_span`` / ``record_collective``):
   the parallel learners' collective seams (psum / psum_scatter /
   SplitInfo allgather — parallel/learners.py, and the growers' own
   in-program collectives) are wrapped so every TRACED collective files
   a site record: collective kind, mesh axis, logical payload bytes
   (from the traced shapes/dtypes) and an executed-calls estimate
   (traced occurrences x the caller-supplied loop factor — fori_loop
   bodies trace once but execute per split).  The wrapper calls the
   underlying collective unchanged, so the traced program — and
   therefore scores — are bit-identical with the layer on or off.  The
   summary/``snapshot()`` gain an ``interconnect`` block joining each
   site's estimated bytes to its phase's measured (fenced) span time →
   attained GB/s per collective site, beside the PR 4 HBM roofline.

7. **Per-process span shards** (``timeline=`` config option): with
   timeline mode on, EVERY process opens its own JSONL shard
   (``<metrics_out>.shard-<i>of<n>.jsonl``; atomic deterministic
   naming, line-buffered + per-record flush so a killed process leaves
   at worst one truncated FINAL line) headed by a ``shard`` record
   (host fingerprint, pid, process index, and the clock-offset
   handshake parallel/mesh.clock_handshake records at setup).
   Iteration/summary records gain a local wall-clock ``t``;
   scripts/timeline_report.py merges shards into one job timeline and
   computes per-phase cross-host skew.

8. **Hung-collective flight recorder** (``stall_timeout=`` config
   option): a ring buffer of the last N span/collective/iteration
   events plus a host-side watchdog thread armed around training
   (gbdt.run_training).  If no event lands for ``stall_timeout``
   seconds the watchdog dumps the ring buffer, the in-flight
   phase/iteration/collective and every thread's stack to the sink —
   BEFORE the environment's opaque ~60 s dispatch watchdog kills the
   job with no record of what was in flight.  The clock is injectable
   (tests stall without real waits); the thread only ever reads state
   and writes the dump, never touching device APIs.

ISSUE 8 — streaming ingestion (io/streaming.py):

9. **Ingest spans + the ``ingest/*`` counter family**: a streamed
   dataset load runs under an ``ingest`` span with sub-spans
   ``ingest_count`` (pass-0 raw row count), ``ingest_pass1``
   (label/side-column collection + pinned-index binning sample),
   ``ingest_bin`` (per-chunk parse + quantize) and ``ingest_h2d``
   (final transfer drain).  Counters: ``ingest/chunks`` and
   ``ingest/rows`` (pass-2 progress), ``ingest/h2d_bytes`` (host→device
   payload), ``ingest/h2d_wait_us`` (host time actually BLOCKED on
   transfers) and ``ingest/overlap_hidden_us`` (upper-bound estimate of
   wire time hidden behind host parse/bin work — the double buffer's
   win; ``LGBM_TPU_INGEST_SYNC=1`` forces depth-0 transfers for the
   bench A/B) and ``ingest/worker_wait_us`` (parallel-parse pool time
   the coordinator spent blocked on the bounded in-flight window —
   io/parallel_ingest.py, ISSUE 18).  Routes:
   ``ingest/double_buffer_on|off``.  Device-side
   sampling rides the same registry: ``bagging/device`` vs
   ``bagging/host`` routes (ops/sampling.py draws vs the legacy host
   RNG + full-N upload) and the ``goss/iterations`` counter under a
   ``goss`` span.  scripts/telemetry_report.py renders the family with
   derived H2D GB/s.

ISSUE 14 — preemption-safe elastic training (checkpoint.py, elastic.py):

10. **Checkpoint counters (``ckpt/*``)**: ``ckpt/snapshots`` (raw
    snapshots enqueued at iteration boundaries), ``ckpt/written``
    (atomic files landed — async AND sync), ``ckpt/dropped`` (a pending
    snapshot replaced by a newer one before the writer thread got to it
    — latest-wins backpressure, never a training stall),
    ``ckpt/async_write_us`` (cumulative writer-thread serialize+write
    time, all OFF the hot loop), ``ckpt/pruned`` (old files removed
    past ``checkpoint_keep``), ``ckpt/restored`` (restores executed).

11. **Elastic span + wire sites**: the per-iteration cross-host time
    exchange and the mesh-shrink survivor agreement run under an
    ``elastic`` span and file the ``elastic/times_allgather``
    (all_gather of per-host iteration seconds over the ``data`` axis)
    and ``elastic/survivor_pmin`` (elementwise keep/drop vote minimum)
    collective sites — both censused by graftlint J2
    (analysis/programs.elastic_programs).  ``elastic/shrinks`` counts
    executed drain-at-boundary mesh shrinks.

ISSUE 16 — flight recorder + per-request latency attribution
(lightgbm_tpu/tracing.py rides this registry's lifecycle):

12. **The ``trace/*`` family contract**: the flight recorder mirrors
    exactly two counters into this registry — ``trace/dropped`` (ring
    events overwritten before being read; ANY nonzero at the default
    ``trace_ring_events`` is an absolute perf_gate finding) and
    ``trace/dumps`` (JSONL dump files written: clean close, watchdog
    and fault/crash paths alike).  The dump writer runs under the
    ``trace_dump`` span.  Everything else the recorder knows —
    per-request component attribution (queue/linger/coalesce/dispatch/
    walk/scatter, summing EXACTLY to each request's wall time), the
    event ring, and the fixed-memory log-bucket percentile sketches per
    latency family (``serve_wall_us``, ``serve_<component>_us``,
    ``train_iter_us``) — stays in tracing.py and reaches records as the
    summary's ``trace`` block (``tracing.snapshot()``) and the
    ``trace_dump_dir=`` JSONL dumps (``scripts/trace_report.py``).
    ``disable()`` disarms the recorder (dumping first when configured);
    ``emit_iteration`` files one ``train_iter`` ring event per
    iteration sharing the timeline-shard record keys.
"""
from __future__ import annotations

import collections
import json
import os
import threading
import time
import traceback
from typing import Dict, List, Optional

from . import lifecycle

# Canonical per-iteration phase keys — always present in iteration records
# (ISSUE 1 acceptance schema), whether or not the phase ran this iteration.
CANONICAL_PHASES = ("histogram", "split_find", "partition", "eval")

# --------------------------------------------------------------------------
# Telemetry name inventory (ISSUE 15) — THE machine-checked family
# documentation, regenerated from the graftlint D1 census
# (analysis/drift_rules.collect_telemetry_usage; ``python
# scripts/graftlint.py --drift-only`` reports any drift).  The prose
# docstring above explains each family's semantics; THESE tuples are the
# name contract: a counter/span/wire-site the code emits but this
# inventory omits — or an entry here no code emits — fails the pre-merge
# gate.  Entries ending in ``*`` are prefix families whose suffix is
# computed at runtime (bucket sizes, kernel widths, per-host keys).

COUNTER_FAMILIES = (
    "allhosts/*",                 # cross-host sums (aggregate_telemetry)
    "bagging/device",
    "bagging/host",
    "ckpt/async_write_us",
    "ckpt/dropped",
    "ckpt/pruned",
    "ckpt/restored",
    "ckpt/snapshots",
    "ckpt/written",
    "costmodel/aot_call_fallback",
    "costmodel/capture_failed",
    "elastic/shrinks",
    "goss/iterations",
    "health/*",                   # per-anomaly-kind counters (health.py)
    "health/anomalous_iterations",
    "hist/env_force_einsum",
    "hist/env_no_pallas",
    "hist/mixedbin_blocked",
    "hist/mixedbin_leafbatch",
    "hist/mixedbin_matmul",
    "hist/mixedbin_off",
    "hist/mixedbin_on",
    "hist/mixedbin_pallas_float",
    "hist/mixedbin_pallas_int",
    "hist/mixedbin_xla_int",
    "hist/pallas_*",              # per-dtype kernel hits
    "hist/pallas_eligible",
    "hist/pallas_ineligible",
    "hist/pallas_int8",
    "hist/pallas_kernel_*",       # per-width kernel-class hits
    "hist/xla_einsum",
    "hist/xla_int8",
    "hist/xla_int_kernel",
    "hist/xla_matmul",
    "ingest/bin_us",
    "ingest/chunks",
    "ingest/double_buffer_off",
    "ingest/double_buffer_on",
    "ingest/h2d_bytes",
    "ingest/h2d_us",
    "ingest/h2d_wait_us",
    "ingest/overlap_hidden_us",
    "ingest/parse_us",
    "ingest/rows",
    "ingest/worker_wait_us",
    "jit/backend_compile",
    "jit/midrun_recompile",
    "jit/persistent_cache_hit",
    "learner/fp_*",               # feature-parallel ownership routes
    "monitor/drift_scores",
    "monitor/slo_breaches",
    "monitor/windows",
    "partition/dma_overlap",
    "partition/dma_serial",
    "partition/env_no_pallas",
    "partition/pallas",
    "partition/pallas_eligible",
    "partition/pallas_ineligible",
    "partition/wide_f_fallback",
    "partition/xla",
    "serve/bucket_*",             # per-ladder-bucket dispatch counts
    "serve/coalesced_batches",
    "serve/coalesced_requests",
    "serve/coalesced_rows",
    "serve/ensemble_flatten",
    "serve/front_requests",
    "serve/front_rows",
    "serve/linger_wait_us",
    "serve/pad_rows",
    "serve/predict_calls",
    "serve/queue_depth_rows",
    "serve/queue_depth_samples",
    "serve/queue_peak_rows",
    "serve/rows",
    "serve/swap_drain_us",
    "serve/swaps",
    "serve/warmups",
    "trace/dropped",
    "trace/dumps",
)

SPAN_FAMILIES = (
    "bagging",
    "elastic",
    "eval",
    "goss",
    "gradient",
    "grow",
    "histogram",
    "ingest",
    "ingest_bin",
    "ingest_count",
    "ingest_h2d",
    "ingest_pass1",
    "model_readback",
    "partition",
    "predict",
    "predict_encode",
    "predict_warmup",
    "score_update",
    "split_find",
    "trace_dump",
    "train_chunk",
    "valid_update",
)

WIRE_SITE_FAMILIES = (
    "dp/grad_score_allgather",
    "elastic/survivor_pmin",
    "elastic/times_allgather",
    "health/quant_sat_reduce",
    "health/score_pmax",
    "health/vector_psum",
    "hist/int8_pallas_psum",
    "hist/int8_segsum_psum",
    "hist/int8_xla_psum",
    "hist/quant_scale_pmax",
    "leafcompact/tier_pmax",
    "serve/tree_carry",
    "serve/tree_psum",
)

# Wire sites whose full names are built at RUNTIME (variable site labels
# threaded through the learners' seam wrappers) — documented here, exempt
# from the stale-doc half of the D1 census the static AST pass cannot
# decide.  The J2 census and tests/test_graftlint.EXPECTED_SITES pin the
# concrete (2,2)-mesh instances.
DYNAMIC_WIRE_SITES = (
    "dp_psum/*",                  # pure-DP psum schedule seams
    "dp_rs/*",                    # DP reduce_scatter ownership seams
    "dp/goss_score_allgather",    # fused-chunk GOSS score gather
    "hybrid/*",                   # 2-D mesh owned-block seams
    "voting/*",                   # PV-tree voted-exchange seams
    "fp/*",                       # feature-parallel ownership seams
    "leafwise/*",                 # schedule-policy seam wrap (grower)
    "depthwise/*",
    "leafcompact/*",
)

_enabled = False
_fence = False
_sink_path: Optional[str] = None
_sink_file = None
_sink_error = False

_counters: Dict[str, int] = {}
_phase_times: Dict[str, float] = {}
_phase_counts: Dict[str, int] = {}
_trace_times: Dict[str, float] = {}
# span re-entrancy stack (host-side, single-threaded boosting loop): a span
# whose name is already active is suppressed so recursive helpers
# (histogram_leafbatch's width-grouped self-calls, build_histogram →
# leafbatch) don't double-count wall time under one name
_span_stack: List[str] = []
# marks for per-iteration deltas
_mark_phase: Dict[str, float] = {}
_mark_trace: Dict[str, float] = {}
# last outcome per host-evaluated routing rule (count_route dedup)
_route_state: Dict[str, str] = {}

# memory gauges (ISSUE 2): armed separately from the base registry so hot
# spans pay the allocator-stats read only when asked for
_memory = False
_mem_device = None            # cached jax device handle
_mem_source: Optional[str] = None
_mem_peak = 0                 # this run's bytes_in_use watermark
# the allocator's LIFETIME peak at the first post-reset sample: the device
# stat is monotonic since allocator creation, so a fresh run must baseline
# it or it would report the previous run's (possibly much larger) peak
_mem_dev_peak_base: Optional[int] = None
_mem_phase_delta: Dict[str, int] = {}   # cumulative per-phase byte deltas
_mem_phase_peak: Dict[str, int] = {}    # per-phase bytes_in_use watermark
_mark_mem: Dict[str, int] = {}          # per-iteration delta marks
_residency: Optional[dict] = None       # one-shot dataset-residency report
_allhosts_mem_peak: Optional[int] = None

_compile_listener_installed = False

# mid-run recompile watch (ISSUE 4): backend-compile count at the first
# iteration record; growth past it after that is a mid-run recompile
_compile_base: "Optional[int]" = None
_midrun_warned = False

# ---- distributed observability state (ISSUE 5) ----
# collective-site registry: site -> {kind, axis, bytes_per_call,
# traced_calls, loop, phase} (record_collective)
_collectives: Dict[str, dict] = {}
# timeline mode: per-process JSONL shards + wall-clock "t" on records
_timeline = False
_shard_path_used: Optional[str] = None
# clock-offset handshake result (parallel/mesh.clock_handshake): seconds
# to ADD to this host's time.time() to land on the leader's clock
_clock_offset = 0.0
_clock_rtt: Optional[float] = None
# flight recorder: ring buffer of recent events + stall watchdog thread
_RING_CAP = 256
_ring: "collections.deque" = collections.deque(maxlen=_RING_CAP)
_ring_armed = False           # cheap hot-path gate (timeline or watchdog)
_wd_timeout_cfg = 0.0         # configure_watchdog (config stall_timeout=)
_wd_thread: Optional[threading.Thread] = None
_wd_stop: Optional[threading.Event] = None
_wd_clock = time.monotonic
_wd_timeout = 0.0
_wd_last = 0.0
_wd_context: Dict[str, object] = {}
_wd_dump: Optional[dict] = None   # last flight-recorder dump (tests)


# --------------------------------------------------------------- life cycle

def enabled() -> bool:
    return _enabled


def enable(jsonl_path: Optional[str] = None, fence: bool = False,
           memory: Optional[bool] = None,
           timeline: Optional[bool] = None) -> None:
    """Arm the registry (and optionally a JSONL sink at ``jsonl_path``).

    Idempotent; a second call can attach a sink or toggle fence mode.  The
    sink file is opened lazily at first record — after jax.distributed
    initialization — so only process 0 writes in multi-process runs,
    UNLESS timeline mode is on, in which case every process writes its
    own shard (``<path>.shard-<i>of<n>.jsonl``).  ``memory`` arms/disarms
    the span-boundary memory gauges, ``timeline`` the per-process shard
    mode (None leaves the current mode unchanged).
    """
    global _enabled, _fence, _sink_path, _sink_error, _sink_file, _memory
    _enabled = True
    _fence = bool(fence)
    if memory is not None:
        _memory = bool(memory)
    if timeline is not None:
        set_timeline(timeline)
    if jsonl_path:
        if _sink_file is not None and jsonl_path != _sink_path:
            # re-targeting an open sink: close the old handle or records
            # would keep landing in the previous file
            try:
                _sink_file.close()
            except OSError:
                pass
            _sink_file = None
        _sink_path = jsonl_path
        _sink_error = False
    _install_compile_listener()
    try:
        from . import costmodel
        costmodel.enable()
    except Exception:
        pass


def disable() -> None:
    """Stop recording and close the sink (pending data is flushed).
    Also disarms the stall watchdog, leaves timeline mode and disarms
    the flight recorder (tracing.py — which dumps its ring first when a
    dump dir is configured) — the registry returns to its process-global
    resting state."""
    global _enabled, _fence, _sink_file, _sink_path, _memory
    global _timeline, _shard_path_used, _wd_timeout_cfg
    disarm_watchdog()
    try:
        # flush the live monitor FIRST: its tail window files
        # monitor_window / slo_breach events into the trace ring, so
        # they must land before the recorder's close dump below
        from . import monitor
        monitor.disarm()
    except Exception:
        pass
    try:
        from . import tracing
        # stamp the session's per-site wire byte model into the ring
        # before the close dump: podtrace's seam roofline joins measured
        # collective_sync spans against exactly this model, and a dump
        # that carries it is self-contained on crash-forensics hosts
        snap = interconnect_snapshot()
        if snap and tracing.active():
            tracing.event("wire_model", sites={
                s: {"est_bytes": rec.get("est_bytes", 0),
                    "bytes_per_call": rec.get("bytes_per_call", 0),
                    "est_calls": rec.get("est_calls", 0),
                    "kind": rec.get("kind"), "axis": rec.get("axis")}
                for s, rec in snap.get("sites", {}).items()})
        tracing.disarm()
    except Exception:
        pass
    _timeline = False
    _shard_path_used = None
    _wd_timeout_cfg = 0.0
    set_shard_identity(None)
    _update_ring_armed()
    _enabled = False
    _fence = False
    _memory = False
    if _sink_file is not None:
        try:
            _sink_file.close()
        except OSError:
            pass
    _sink_file = None
    _sink_path = None
    try:
        from . import costmodel
        costmodel.disable()
    except Exception:
        pass


def reset() -> None:
    """Zero all counters/timers/gauges (sink and enabled state are
    untouched)."""
    global _mem_peak, _residency, _allhosts_mem_peak, _mem_dev_peak_base
    global _compile_base, _midrun_warned
    _compile_base = None
    _midrun_warned = False
    try:
        from . import costmodel
        costmodel.reset()
    except Exception:
        pass
    _counters.clear()
    _phase_times.clear()
    _phase_counts.clear()
    _trace_times.clear()
    _mark_phase.clear()
    _mark_trace.clear()
    _route_state.clear()
    _mem_phase_delta.clear()
    _mem_phase_peak.clear()
    _mark_mem.clear()
    _mem_peak = 0
    _mem_dev_peak_base = None    # re-baselined at the next sample
    _residency = None
    _allhosts_mem_peak = None
    _collectives.clear()
    _ring.clear()
    del _span_stack[:]


def set_fence(on: bool) -> None:
    global _fence
    _fence = bool(on)


def fence_enabled() -> bool:
    return _fence


def set_memory(on: bool) -> None:
    """Arm/disarm the span-boundary memory gauges."""
    global _memory
    _memory = bool(on)


def memory_enabled() -> bool:
    return _memory


def sink_active() -> bool:
    """True when iteration records have somewhere to go (a sink path is
    configured) — the boosting loop's cheap guard around record assembly."""
    return _enabled and _sink_path is not None


def sink_open() -> bool:
    """True when a sink is configured or a file handle is still open —
    the test-suite leak guard's check (tests/conftest.py)."""
    return _sink_file is not None or (_enabled and _sink_path is not None)


# ---------------------------------------------------------- memory sampling

def _mem_sample() -> int:
    """Current memory footprint in bytes, updating the process watermark.

    Prefers the device allocator (``device.memory_stats()["bytes_in_use"]``
    — real HBM occupancy on TPU/GPU, including its own peak watermark);
    backends that return None (CPU) fall back to the process RSS from
    /proc/self/statm, so CPU runs still carry a meaningful gauge.  A pure
    stats read: never allocates or dispatches device work."""
    global _mem_device, _mem_source, _mem_peak, _mem_dev_peak_base
    try:
        if _mem_device is None:
            import jax
            _mem_device = jax.local_devices()[0]
        ms = _mem_device.memory_stats()
        if ms and "bytes_in_use" in ms:
            b = int(ms["bytes_in_use"])
            # the allocator's peak stat is monotonic over the PROCESS: only
            # growth past the post-reset baseline belongs to this run (it
            # catches transient spikes between our samples); a larger
            # previous run's peak must not leak into this run's watermark
            dev_peak = int(ms.get("peak_bytes_in_use", 0))
            if _mem_dev_peak_base is None:
                _mem_dev_peak_base = dev_peak
            if dev_peak > _mem_dev_peak_base:
                _mem_peak = max(_mem_peak, dev_peak)
            _mem_peak = max(_mem_peak, b)
            _mem_source = "device"
            return b
    except Exception:
        pass
    try:
        with open("/proc/self/statm") as f:
            b = int(f.read().split()[1]) * (os.sysconf("SC_PAGE_SIZE")
                                            if hasattr(os, "sysconf")
                                            else 4096)
        _mem_peak = max(_mem_peak, b)
        _mem_source = "host_rss"
        return b
    except Exception:
        if _mem_source is None:
            _mem_source = "unavailable"
        return 0


def take_memory_record() -> Optional[dict]:
    """Per-iteration ``memory`` block: current and peak bytes plus the
    per-phase byte deltas accumulated since the previous call (re-marks,
    mirroring take_phase_deltas).  None while memory gauges are off."""
    if not _memory:
        return None
    b = _mem_sample()
    deltas = {k: v - _mark_mem.get(k, 0)
              for k, v in _mem_phase_delta.items()
              if v - _mark_mem.get(k, 0) != 0}
    _mark_mem.clear()
    _mark_mem.update(_mem_phase_delta)
    rec = {"bytes_in_use": int(b), "peak_bytes_in_use": int(_mem_peak),
           "source": _mem_source or "unavailable"}
    if deltas:
        rec["phase_delta_bytes"] = {k: int(v)
                                    for k, v in sorted(deltas.items())}
    return rec


def memory_snapshot() -> Optional[dict]:
    """Cumulative memory block (summary record / ``snapshot()``): peak
    watermark, cumulative per-phase deltas and per-phase peaks, the
    dataset-residency report, and the cross-host peak when aggregated."""
    if not (_memory or _mem_phase_delta or _residency is not None):
        return None
    out = {"bytes_in_use": int(_mem_sample()) if _memory else 0,
           "peak_bytes_in_use": int(_mem_peak),
           "source": _mem_source or "unavailable"}
    if _mem_phase_delta:
        out["phase_delta_bytes"] = {k: int(v) for k, v
                                    in sorted(_mem_phase_delta.items())}
        out["phase_peak_bytes"] = {k: int(v) for k, v
                                   in sorted(_mem_phase_peak.items())}
    if _residency is not None:
        out["residency"] = _residency
    if _allhosts_mem_peak is not None:
        out["allhosts_peak_bytes_in_use"] = int(_allhosts_mem_peak)
    return out


def mem_peak_bytes() -> int:
    return int(_mem_peak)


def merge_host_memory(peak: int) -> None:
    """Install the cross-host peak-bytes maximum (parallel.learners.
    aggregate_telemetry) on this process."""
    global _allhosts_mem_peak
    _allhosts_mem_peak = int(peak)


def set_residency(report: dict) -> None:
    """File the one-shot dataset-residency report (bin matrix / metadata /
    histogram scratch footprint, computed at train start by gbdt.init): it
    rides ``memory_snapshot()`` and is written to the sink immediately as
    a standalone ``{"residency": ...}`` record."""
    global _residency
    _residency = dict(report)
    if sink_active():
        write_record({"residency": _residency})


# ----------------------------------------------------- collective sites

def _tree_nbytes(args) -> int:
    """Logical payload bytes of a collective's operands, from the traced
    shapes/dtypes (tracers carry .size/.dtype like concrete arrays)."""
    total = 0
    try:
        import jax
        for leaf in jax.tree.leaves(args):
            size = getattr(leaf, "size", None)
            dt = getattr(leaf, "dtype", None)
            if size is not None and dt is not None:
                total += int(size) * int(getattr(dt, "itemsize", 4))
    except Exception:
        pass
    return total


def record_collective(site: str, kind: str, axis: Optional[str],
                      nbytes: int, loop: int = 1,
                      phase: Optional[str] = None) -> None:
    """File one traced collective occurrence at ``site``.

    Collectives are trace-time events like the kernel-route counters: the
    compiled program replays the traced collective forever, so one record
    per trace occurrence IS the inventory of what the program moves.
    ``loop`` is the caller's executed-calls-per-trace estimate (a seam
    invoked inside a fori_loop body traces once but runs once per split);
    ``phase`` names the telemetry span whose measured time prices this
    site's wire seconds in the ``interconnect`` block."""
    if not _enabled:
        return
    if phase is None and _span_stack:
        # default attribution: the OUTERMOST active span is the host-side
        # phase the compiled program executes under ("grow"/"train_chunk")
        # — inner spans at trace time are trace-time spans
        phase = _span_stack[0]
    rec = _collectives.get(site)
    if rec is None:
        rec = _collectives[site] = {
            "kind": kind, "axis": axis, "bytes_per_call": int(nbytes),
            "traced_calls": 0, "loop": max(int(loop), 1), "phase": phase}
    rec["traced_calls"] += 1
    # shapes can differ between traces (re-trace at a new shape): keep the
    # largest payload as the representative per-call cost
    rec["bytes_per_call"] = max(rec["bytes_per_call"], int(nbytes))
    if _ring_armed:
        _ring_event("collective", site)


def collective_span(site: str, fn, *, kind: str, axis: Optional[str] = None,
                    loop: int = 1, phase: Optional[str] = None):
    """Wrap a collective seam callable so each TRACED invocation files a
    site record (kind, mesh axis, payload bytes from the traced avals).

    The wrapper calls ``fn`` unchanged — nothing is inserted into the
    traced program, so enabling/disabling the layer perturbs neither
    numerics nor jit caching.  ``None`` passes through (optional seams);
    an already-wrapped fn is returned as-is (the first wrap, closest to
    the collective, keeps the most precise kind/loop metadata)."""
    if fn is None:
        return None
    if getattr(fn, "_tl_collective_site", None) is not None:
        return fn

    def wrapped(*args, **kwargs):
        record_collective(site, kind, axis, _tree_nbytes((args, kwargs)),
                          loop=loop, phase=phase)
        return fn(*args, **kwargs)

    wrapped._tl_collective_site = site
    return wrapped


def collectives() -> Dict[str, dict]:
    return {k: dict(v) for k, v in _collectives.items()}


def interconnect_snapshot() -> Optional[dict]:
    """The ``interconnect`` block: per-site estimated bytes moved joined
    to the owning phase's measured span seconds → attained GB/s per
    collective site and per phase.  Estimates: executed calls =
    traced_calls x loop x the phase's span count (the cached program
    replays its collectives on every execution); byte counts are the
    LOGICAL payload (shapes x dtypes) — on-wire bytes depend on the
    collective algorithm (a psum moves ~2x(S-1)/S of the payload per
    hop).  None while no collective site was traced."""
    if not _collectives:
        return None
    sites = {}
    phase_bytes: Dict[str, int] = {}
    for site, rec in sorted(_collectives.items()):
        phase = rec.get("phase")
        # collectives are recorded once per TRACE, but the cached program
        # replays them on every execution of its phase span — scale by
        # the phase's span count so the bytes (and therefore the attained
        # rate against the phase's ACCUMULATED seconds) cover the whole
        # run, mirroring costmodel's per-execution call counter.  A
        # re-trace (new shapes) double-counts both traced_calls and one
        # execution — an estimate, as documented in the block's note.
        execs = max(_phase_counts.get(phase, 1), 1) if phase else 1
        est_calls = rec["traced_calls"] * rec["loop"] * execs
        est_bytes = rec["bytes_per_call"] * est_calls
        entry = {
            "kind": rec["kind"], "axis": rec["axis"],
            "bytes_per_call": int(rec["bytes_per_call"]),
            "traced_calls": int(rec["traced_calls"]),
            "phase_executions": int(execs),
            "est_calls": int(est_calls),
            "est_bytes": int(est_bytes),
        }
        if phase:
            entry["phase"] = phase
            phase_bytes[phase] = phase_bytes.get(phase, 0) + est_bytes
            secs = _phase_times.get(phase, 0.0)
            if secs > 0:
                entry["attained_gb_per_s"] = round(est_bytes / secs / 1e9, 6)
        sites[site] = entry
    phases = {}
    for phase, nbytes in sorted(phase_bytes.items()):
        secs = _phase_times.get(phase, 0.0)
        phases[phase] = {
            "est_bytes": int(nbytes),
            "span_seconds": round(secs, 6),
            "attained_gb_per_s": (round(nbytes / secs / 1e9, 6)
                                  if secs > 0 else None),
        }
    return {"sites": sites, "phases": phases, "fenced_spans": _fence,
            "note": "logical payload bytes; est_calls = traced x loop x "
                    "phase executions"}


# ------------------------------------------------- timeline / clock offset

def set_timeline(on: bool) -> None:
    """Arm/disarm per-process shard mode (the ``timeline=`` option).
    Takes effect at the next sink open; an already-open sink keeps its
    target (retarget via enable(jsonl_path=...))."""
    global _timeline
    _timeline = bool(on)
    _update_ring_armed()


def timeline_enabled() -> bool:
    return _timeline


def set_clock_offset(offset_s: float, rtt_s: Optional[float] = None) -> None:
    """Install the leader-relative clock offset measured by
    parallel/mesh.clock_handshake: seconds to ADD to this host's
    time.time() to land on the leader's clock (recorded in the shard
    header; scripts/timeline_report.py applies it when merging)."""
    global _clock_offset, _clock_rtt
    _clock_offset = float(offset_s)
    _clock_rtt = None if rtt_s is None else float(rtt_s)


def clock_offset() -> float:
    return _clock_offset


_shard_identity: "Optional[tuple[int, int]]" = None


def set_shard_identity(index: Optional[int] = None,
                       count: Optional[int] = None) -> None:
    """Override the (process_index, process_count) shard identity —
    dryrun_multichip and tests use it to exercise the multi-shard merge
    path from a single process (simulated hosts).  ``None`` resets to
    the real jax.process_index()/count()."""
    global _shard_identity
    _shard_identity = (None if index is None or count is None
                       else (int(index), int(count)))
    # keep the flight recorder's pod identity in lockstep — dumps and
    # timeline shards must agree on who "p<i>" is (podtrace merge key)
    try:
        from . import tracing
        if _shard_identity is None:
            tracing.set_identity(process_index=None, process_count=None)
        else:
            tracing.set_identity(process_index=_shard_identity[0],
                                 process_count=_shard_identity[1])
    except Exception:
        pass


def _shard_suffix() -> "tuple[int, int]":
    if _shard_identity is not None:
        return _shard_identity
    try:
        import jax
        return jax.process_index(), jax.process_count()
    except Exception:
        return 0, 1


def shard_path(base: str, index: int, count: int) -> str:
    """Deterministic per-process shard name: each process owns exactly
    one file for the run (no appends to another process's half-written
    shard), and scripts/timeline_report.py can glob
    ``<base>.shard-*.jsonl``."""
    return "%s.shard-%05dof%05d.jsonl" % (base, index, count)


def sink_path() -> Optional[str]:
    """The path records actually land in (the shard path in timeline
    mode) — test/report helper."""
    return _shard_path_used if _timeline else _sink_path


# ------------------------------------------ flight recorder + stall watchdog

def _update_ring_armed() -> None:
    global _ring_armed
    _ring_armed = _timeline or _wd_thread is not None


def _ring_event(kind: str, name: str) -> None:
    """Append one event to the flight-recorder ring (and feed the stall
    watchdog's progress clock).  Hot-path cost: one deque append."""
    global _wd_last
    _ring.append((time.time(), kind, name,
                  _wd_context.get("iteration")))
    if _wd_thread is not None:
        _wd_last = _wd_clock()


def configure_watchdog(timeout_s: float) -> None:
    """Store the ``stall_timeout=`` setting; gbdt.run_training arms the
    watchdog around training when this is > 0."""
    global _wd_timeout_cfg
    _wd_timeout_cfg = max(float(timeout_s), 0.0)


def watchdog_configured() -> float:
    return _wd_timeout_cfg


def watchdog_checkin(phase: Optional[str] = None,
                     iteration: Optional[int] = None,
                     detail: Optional[str] = None) -> None:
    """Mark forward progress (and the in-flight context the dump will
    name).  Called by the boosting loop at phase boundaries; span
    enter/exit events check in implicitly via the ring."""
    global _wd_last
    if phase is not None:
        _wd_context["phase"] = phase
    if iteration is not None:
        _wd_context["iteration"] = int(iteration)
    if detail is not None:
        _wd_context["detail"] = detail
    if _wd_thread is not None:
        _wd_last = _wd_clock()


def arm_watchdog(timeout_s: Optional[float] = None, clock=None,
                 poll_s: float = 0.05) -> bool:
    """Start the stall-watchdog thread (idempotent).  ``clock`` is
    injectable — tests drive a fake clock and never wait out a real
    stall.  The thread polls a monotonic clock and, once no ring
    event/checkin lands for ``timeout_s``, writes a flight-recorder
    dump to the sink (the opaque runtime watchdog is expected to kill a
    truly hung job shortly after; the dump is the record it never
    leaves).  If progress RESUMES after a dump — e.g. the stall was a
    long backend compile, which blocks the host with no events — the
    watchdog re-arms, up to ``_WD_MAX_DUMPS`` dumps per arming."""
    global _wd_thread, _wd_stop, _wd_clock, _wd_timeout, _wd_last, _wd_dump
    timeout = _wd_timeout_cfg if timeout_s is None else float(timeout_s)
    if timeout <= 0 or _wd_thread is not None:
        return False
    _wd_clock = clock or time.monotonic
    _wd_timeout = timeout
    _wd_last = _wd_clock()
    _wd_dump = None
    _wd_stop = threading.Event()
    _wd_thread = threading.Thread(
        target=_wd_run, args=(_wd_stop, poll_s), name="lgbm-tpu-watchdog",
        daemon=True)
    # shared live-object inventory (ISSUE 15): the guard and graftlint C1
    # see the watchdog like every other thread-owning subsystem
    lifecycle.track("watchdog", _wd_thread, disarm_watchdog)
    _wd_thread.start()
    _update_ring_armed()
    return True


def disarm_watchdog(join_s: float = 2.0) -> None:
    global _wd_thread, _wd_stop
    t, ev = _wd_thread, _wd_stop
    _wd_thread, _wd_stop = None, None
    _update_ring_armed()
    if ev is not None:
        ev.set()
    if t is not None:
        if t.is_alive():
            t.join(join_s)
        if not t.is_alive():
            lifecycle.untrack(t)


def watchdog_active() -> bool:
    """True while the watchdog thread is running (tests/conftest.py leak
    guard)."""
    return _wd_thread is not None and _wd_thread.is_alive()


def last_flight_record() -> Optional[dict]:
    return _wd_dump


# a long backend compile blocks the host with no Python events and can
# fire a spurious dump; the watchdog therefore RE-ARMS when progress
# resumes (capped, so a genuinely hung run can't spam the sink) instead
# of retiring on its first dump — a later real hang still gets recorded
_WD_MAX_DUMPS = 3


def _wd_run(stop: "threading.Event", poll_s: float) -> None:
    dumps = 0
    dumped_at: Optional[float] = None   # _wd_last value at the last dump
    while not stop.is_set():
        stop.wait(poll_s)
        try:
            if dumped_at is not None:
                if _wd_last > dumped_at:
                    dumped_at = None    # progress resumed: re-arm
                else:
                    continue
            if _wd_clock() - _wd_last >= _wd_timeout > 0:
                _flight_dump(_wd_clock() - _wd_last, dumps + 1)
                dumps += 1
                dumped_at = _wd_last
                if dumps >= _WD_MAX_DUMPS:
                    return
        except Exception:  # pragma: no cover - never kill the host loop
            return


def _flight_dump(stalled_s: float, dump_index: int = 1) -> None:
    """Assemble and write the flight-recorder dump: in-flight
    phase/iteration/collective, the event ring, and every thread's
    stack.  Pure host-side state reads — never touches device APIs (the
    device is exactly what's presumed hung)."""
    global _wd_dump
    import sys
    events = [{"t": round(t, 6), "kind": k, "name": n,
               "iter": it} for (t, k, n, it) in list(_ring)]
    in_flight_phase = (_span_stack[-1] if _span_stack
                       else _wd_context.get("phase"))
    last_coll = next((e["name"] for e in reversed(events)
                      if e["kind"] == "collective"), None)
    threads = {}
    try:
        names = {t.ident: t.name for t in threading.enumerate()}
        for tid, frame in sys._current_frames().items():
            name = names.get(tid, str(tid))
            if name == "lgbm-tpu-watchdog":
                continue
            threads[name] = [ln.rstrip() for ln in
                             traceback.format_stack(frame)[-8:]]
    except Exception:
        pass
    dump = {
        "flight_recorder": {
            "dump_index": int(dump_index),
            "stalled_for_s": round(float(stalled_s), 3),
            "stall_timeout_s": _wd_timeout,
            "phase": in_flight_phase,
            "iteration": _wd_context.get("iteration"),
            "detail": _wd_context.get("detail"),
            "last_collective": last_coll,
            "open_spans": list(_span_stack),
            "ring": events[-_RING_CAP:],
            "threads": threads,
        }
    }
    _wd_dump = dump
    try:
        from .utils import log
        log.warning(
            "telemetry watchdog: no progress for %.1fs (stall_timeout=%.1fs)"
            " — in-flight phase=%s iter=%s collective=%s; flight-recorder "
            "dump written"
            % (stalled_s, _wd_timeout, in_flight_phase,
               _wd_context.get("iteration"), last_coll))
    except Exception:
        pass
    try:
        write_record(dump)
    except Exception:
        pass


# ------------------------------------------------------------------- spans

class _NullSpan:
    """No-op span returned while telemetry is disabled (or re-entrant)."""
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def fence(self, value):
        return value


_NULL_SPAN = _NullSpan()


def _tracing() -> bool:
    try:
        import jax.core
        return not jax.core.trace_state_clean()
    except Exception:
        return False


class Span:
    """Context-managed phase timer.  ``fence(x)`` hands the span a value to
    ``jax.block_until_ready`` at exit when fence mode is on (execution-time
    spans only; trace-time spans never block).

    Profiler alignment (ISSUE 2): the span body runs under
    ``jax.named_scope(name)`` (ops traced inside carry the phase name in
    HLO metadata → Perfetto device rows) and
    ``jax.profiler.TraceAnnotation(name)`` (a host-timeline trace event),
    so ``profile_dir=`` traces line up with the JSONL phase keys.  With
    memory gauges armed, the span also samples the allocator at its
    boundaries (per-phase byte delta + watermark)."""
    __slots__ = ("name", "_t0", "_fence_val", "_is_trace", "_scope",
                 "_ann", "_mem0")

    def __init__(self, name: str):
        self.name = name
        self._fence_val = None
        self._is_trace = False
        self._t0 = 0.0
        self._scope = None
        self._ann = None
        self._mem0 = None

    def __enter__(self):
        self._is_trace = _tracing()
        # two independent try blocks: if the annotation fails AFTER the
        # named scope entered, the scope must still be tracked (and later
        # exited) or the global name stack would grow one entry per span
        try:
            import jax
            self._scope = jax.named_scope(self.name)
            self._scope.__enter__()
        except Exception:
            self._scope = None
        try:
            import jax
            self._ann = jax.profiler.TraceAnnotation(self.name)
            self._ann.__enter__()
        except Exception:
            self._ann = None
        if _memory and not self._is_trace:
            self._mem0 = _mem_sample()
        _span_stack.append(self.name)
        if _ring_armed:
            _ring_event("span_enter", self.name)
        self._t0 = time.perf_counter()
        return self

    def fence(self, value):
        self._fence_val = value
        return value

    def __exit__(self, exc_type, exc, tb):
        if (_fence and not self._is_trace and exc_type is None
                and self._fence_val is not None):
            try:
                import jax
                jax.block_until_ready(self._fence_val)
            except Exception:
                pass
        dt = time.perf_counter() - self._t0
        self._fence_val = None
        if self._ann is not None:
            try:
                self._ann.__exit__(exc_type, exc, tb)
            except Exception:
                pass
            self._ann = None
        if self._scope is not None:
            try:
                self._scope.__exit__(exc_type, exc, tb)
            except Exception:
                pass
            self._scope = None
        if self._mem0 is not None:
            b1 = _mem_sample()
            _mem_phase_delta[self.name] = (
                _mem_phase_delta.get(self.name, 0) + (b1 - self._mem0))
            _mem_phase_peak[self.name] = max(
                _mem_phase_peak.get(self.name, 0), b1, self._mem0)
            self._mem0 = None
        if _span_stack and _span_stack[-1] == self.name:
            _span_stack.pop()
        if _ring_armed:
            _ring_event("span_exit", self.name)
        if self._is_trace:
            _trace_times[self.name] = _trace_times.get(self.name, 0.0) + dt
        else:
            _phase_times[self.name] = _phase_times.get(self.name, 0.0) + dt
            _phase_counts[self.name] = _phase_counts.get(self.name, 0) + 1
        return False


def span(name: str):
    """Phase timer: ``with telemetry.span("histogram") as sp: ...``.

    Returns a shared no-op when telemetry is disabled or a span of the same
    name is already open (re-entrant helper calls)."""
    if not _enabled or name in _span_stack:
        return _NULL_SPAN
    return Span(name)


# ----------------------------------------------------------------- counters
#
# Mixed-bin packing counters (ISSUE 6): the histogram routing layer files
# ``hist/mixedbin_*`` trace-time counters (``_leafbatch`` = a packed
# leaf-batched dispatch; ``_pallas_int``/``_pallas_float``/``_xla_int``/
# ``_matmul`` = which kernel route ran the per-class passes) and
# gbdt.init records the layout decision once per booster via
# ``count_route("hist_layout", "hist/mixedbin_on"|"hist/mixedbin_off")``
# — the runtime answer to "did this run actually pack, and on which
# kernels".  The BLOCK-LOCAL layout (ISSUE 12, hybrid/voting ownership
# meshes) additionally files ``hist/mixedbin_blocked`` once per booster,
# and in-chunk GOSS bumps ``goss/iterations`` by the chunk length at
# dispatch (the same counter the per-iteration path bumps per draw) —
# the fused DP selection's score allgather records on the
# ``dp/goss_score_allgather`` wire-metrics site.  Pipelined boosting deliberately adds NO counters: it changes
# host wait order only, and the phase spans (model_readback migrating off
# the critical path) are the observable.
#
# Serving counters (ISSUE 7, lightgbm_tpu/serving.py):
# ``serve/ensemble_flatten`` = once per FlatEnsemble build (the
# encode-once contract: predict_file must read 1 for the whole file);
# ``serve/predict_calls`` / ``serve/rows`` / ``serve/pad_rows`` = engine
# call volume and the pad overhead the bucket ladder costs;
# ``serve/bucket_<B>`` = which compiled batch shape served each call.
# The engine's device programs are costmodel-instrumented under phase
# "predict" (span of the same name wraps the device walk;
# "predict_encode" times the host rank-encode), so the roofline and
# compile blocks attribute serving alongside training.
#
# Distributed elastic serving (ISSUE 13) extends the family:
# ``serve/front_requests`` / ``serve/front_rows`` = ServingFront intake;
# ``serve/coalesced_batches`` / ``serve/coalesced_rows`` /
# ``serve/coalesced_requests`` = the cross-request batching outcome (the
# coalesced batch SIZE histogram is the engine's existing
# ``serve/bucket_<B>`` counters — each coalesced batch lands on exactly
# one ladder bucket); ``serve/linger_wait_us`` = cumulative
# first-arrival→dispatch wait (mean = /coalesced_batches);
# ``serve/queue_depth_rows`` + ``serve/queue_depth_samples`` = queue
# depth sampled at each batch formation (mean = rows/samples) with
# ``serve/queue_peak_rows`` filed once at front close; ``serve/swaps`` /
# ``serve/swap_drain_us`` = hot-swap count and drain-and-flip latency;
# ``serve/warmups`` = double-buffered engine warmups (the compile the
# swap keeps OUT of the request path).  The tree-sharded engine's
# cross-shard exchange files wire-metrics sites ``serve/tree_carry``
# (the [C, N] carry-chain ppermute hops, shards-1 per trace) and
# ``serve/tree_psum`` (the final masked broadcast psum), so the
# interconnect block prices tree_psum wire bytes per phase beside the
# training seams — and graftlint J2's census covers the same two sites.

def count(name: str, n: int = 1) -> None:
    """Bump a monotonic counter (kernel-route decisions, env-var trips,
    recompiles).  No-op while disabled."""
    if _enabled:
        _counters[name] = _counters.get(name, 0) + n


def count_route(group: str, name: str) -> None:
    """Record a routing-decision OUTCOME for a rule that host code
    re-evaluates every call (e.g. ops/compact.pallas_partition_ok, once
    per tree): counts once per outcome change within ``group``, so the
    counter reads as decisions, not evaluations — matching the trace-time
    counters' per-decision magnitude."""
    if not _enabled:
        return
    if _route_state.get(group) != name:
        _route_state[group] = name
        count(name)


def counters() -> Dict[str, int]:
    return dict(_counters)


def merge_host_counters(totals: Dict[str, int]) -> None:
    """Install cross-host counter sums (parallel.learners.
    aggregate_telemetry) under ``allhosts/`` keys on this process."""
    for k, v in totals.items():
        _counters["allhosts/" + k] = int(v)


def _install_compile_listener() -> None:
    """Count true recompiles via jax.monitoring: the backend-compile
    duration event fires once per compilation-cache miss and never on a
    hit, so the counter is exactly the number of XLA compiles this process
    paid.  Registered once; increments are gated on the enabled flag
    (jax.monitoring has no unregister)."""
    global _compile_listener_installed
    if _compile_listener_installed:
        return
    try:
        from jax import monitoring

        def _on_duration(name: str, dur: float, **kw) -> None:
            if _enabled and name.endswith("backend_compile_duration"):
                _counters["jit/backend_compile"] = (
                    _counters.get("jit/backend_compile", 0) + 1)
                _trace_times["backend_compile"] = (
                    _trace_times.get("backend_compile", 0.0) + dur)

        monitoring.register_event_duration_secs_listener(_on_duration)
        # the duration listener is registered: mark installed NOW —
        # jax.monitoring has no unregister, so a failure in the second
        # (optional) registration below must not cause a later enable()
        # to stack a duplicate _on_duration listener
        _compile_listener_installed = True

        def _on_event(name: str, **kw) -> None:
            # persistent-compilation-cache hits (ISSUE 4): jax records
            # '/jax/compilation_cache/cache_hits' once per executable
            # served from the on-disk cache — together with
            # jit/backend_compile this decomposes "programs built" into
            # paid-compiles vs cache-served
            if _enabled and "cache_hit" in name:
                _counters["jit/persistent_cache_hit"] = (
                    _counters.get("jit/persistent_cache_hit", 0) + 1)

        try:
            monitoring.register_event_listener(_on_event)
        except Exception:
            pass
    except Exception:
        pass


def _watch_midrun_recompiles() -> None:
    """Called at each iteration record: backend compiles AFTER the first
    record mean a chunk/grower program cache key missed something that
    changed mid-run (the exact failure mode the PR-3 cache-key hardening
    fixed) — count them and warn once."""
    global _compile_base, _midrun_warned
    n = _counters.get("jit/backend_compile", 0)
    if _compile_base is None:
        _compile_base = n
        return
    if n > _compile_base:
        _counters["jit/midrun_recompile"] = (
            _counters.get("jit/midrun_recompile", 0) + (n - _compile_base))
        _compile_base = n
        if not _midrun_warned:
            _midrun_warned = True
            from .utils import log
            log.warning(
                "telemetry: %d backend compile(s) happened after the first "
                "iteration record (mid-run recompile) — a program cache "
                "key may not capture everything that changed"
                % _counters["jit/midrun_recompile"])


# ---------------------------------------------------------------- snapshots

def snapshot() -> dict:
    """Cumulative registry state for library users (no sink required)."""
    out = {
        "phase_times": dict(_phase_times),
        "phase_counts": dict(_phase_counts),
        "trace_times": dict(_trace_times),
        "counters": dict(_counters),
    }
    mem = memory_snapshot()
    if mem is not None:
        out["memory"] = mem
    ic = interconnect_snapshot()
    if ic is not None:
        out["interconnect"] = ic
    _attach_cost_blocks(out)
    return out


def _attach_cost_blocks(record: dict) -> None:
    """Add the ``roofline`` and ``compile`` blocks (costmodel registry
    joined to the cumulative phase spans) to a summary-shaped record.
    Absent entirely while the cost registry has nothing — disabled-mode
    snapshots stay empty — and never raises (reporting must not crash
    training)."""
    try:
        from . import costmodel
        if costmodel.active():
            record["roofline"] = costmodel.roofline(dict(_phase_times),
                                                    fenced=_fence)
            record["compile"] = costmodel.compile_block()
    except Exception:
        pass


def take_phase_deltas() -> "tuple[Dict[str, float], Dict[str, float]]":
    """(phase_times, trace_times) accumulated since the previous call, and
    re-mark.  The boosting loop calls this once per iteration (or once per
    fused chunk) to scope the per-record timings."""
    dp = {k: v - _mark_phase.get(k, 0.0) for k, v in _phase_times.items()
          if v - _mark_phase.get(k, 0.0) > 0.0}
    dt = {k: v - _mark_trace.get(k, 0.0) for k, v in _trace_times.items()
          if v - _mark_trace.get(k, 0.0) > 0.0}
    _mark_phase.clear()
    _mark_phase.update(_phase_times)
    _mark_trace.clear()
    _mark_trace.update(_trace_times)
    return dp, dt


# -------------------------------------------------------------------- sink

def _ensure_sink():
    """Open the sink on first write.  Deferred so jax.process_index() is
    consulted AFTER distributed init: only the leader writes — unless
    timeline mode is on, in which case EVERY process opens its own shard
    (deterministic per-process name; line-buffered, so a killed process
    leaves at worst one truncated final line) and writes a ``shard``
    header record first."""
    global _sink_file, _sink_error, _shard_path_used
    if _sink_file is not None or _sink_path is None or _sink_error:
        return _sink_file
    path = _sink_path
    header = None
    if _timeline:
        idx, count = _shard_suffix()
        path = _shard_path_used = shard_path(_sink_path, idx, count)
        header = _shard_header(idx, count)
    else:
        try:
            import jax
            if jax.process_count() > 1 and jax.process_index() != 0:
                _sink_error = True   # non-leader: never write
                return None
        except Exception:
            pass
    try:
        # line-buffered: each record reaches the OS at its newline, so a
        # crashed peer's shard is readable up to its last whole record
        _sink_file = open(path, "w", buffering=1)
    except OSError:
        from .utils import log
        log.warning("telemetry: cannot open metrics_out=%s; sink disabled"
                    % path)
        _sink_error = True
        return None
    if header is not None:
        try:
            _sink_file.write(json.dumps(header) + "\n")
            _sink_file.flush()
        except OSError:
            pass
    return _sink_file


def _shard_header(idx: int, count: int) -> dict:
    """The shard's self-describing first record: which host/process wrote
    it, and the clock offset that maps its local ``t`` stamps onto the
    leader's clock."""
    import socket
    info = {
        "process_index": int(idx),
        "process_count": int(count),
        "pid": os.getpid(),
        "clock_offset_s": round(_clock_offset, 6),
        "started_unix": round(time.time(), 6),
    }
    if _clock_rtt is not None:
        info["clock_rtt_s"] = round(_clock_rtt, 6)
    try:
        info["host"] = socket.gethostname()
    except Exception:
        info["host"] = "unknown"
    try:
        from . import costmodel
        info["fingerprint"] = costmodel.host_fingerprint()
    except Exception:
        pass
    return {"shard": info}


def _round_times(d: Dict[str, float]) -> Dict[str, float]:
    return {k: round(v, 6) for k, v in sorted(d.items())}


def write_record(record: dict) -> None:
    """Append one raw JSON line to the sink (no-op without a sink).

    Telemetry must never crash training: an I/O failure (disk full, stale
    mount) disables the sink with a warning, mirroring _ensure_sink's
    open-failure contract."""
    global _sink_error, _sink_file
    f = _ensure_sink()
    if f is None:
        return
    try:
        f.write(json.dumps(record) + "\n")
        f.flush()
    except OSError as e:
        from .utils import log
        log.warning("telemetry: write to metrics_out failed (%s); "
                    "sink disabled" % e)
        _sink_error = True
        try:
            f.close()
        except OSError:
            pass
        _sink_file = None


def emit_iteration(iteration: int, phase_times: Dict[str, float],
                   trace_times: Optional[Dict[str, float]] = None,
                   eval_metrics: Optional[dict] = None,
                   health: Optional[dict] = None,
                   memory: Optional[dict] = None,
                   extra: Optional[dict] = None) -> dict:
    """Build and write one per-iteration record.  Canonical phase keys are
    always present; counters ride cumulatively.  ``health`` is the
    iteration's training-health block (lightgbm_tpu/health.py),
    ``memory`` the per-iteration gauge block (take_memory_record).
    Returns the record."""
    _watch_midrun_recompiles()
    pt = {k: 0.0 for k in CANONICAL_PHASES}
    pt.update(phase_times)
    record = {
        "iter": int(iteration),
        "phase_times": _round_times(pt),
        "counters": dict(sorted(_counters.items())),
        "eval_metrics": eval_metrics or {},
    }
    if _timeline:
        # local wall clock; the shard header's clock_offset_s maps it
        # onto the leader's clock when timeline_report merges shards
        record["t"] = round(time.time(), 6)
    if _ring_armed:
        _ring_event("iteration", str(iteration))
    try:
        from . import tracing
        if tracing.active():
            # the flight recorder's training timeline (ISSUE 16): one
            # train_iter ring event per iteration, same record keys as
            # the timeline shards (iter / phase_times / t)
            tracing.record_train_iteration(iteration,
                                           record["phase_times"])
    except Exception:
        pass
    watchdog_checkin(iteration=iteration)
    if trace_times:
        record["trace_times"] = _round_times(trace_times)
    if health is not None:
        record["health"] = health
    if memory is not None:
        record["memory"] = memory
    if extra:
        record.update(extra)
    write_record(record)
    return record


def emit_summary(extra: Optional[dict] = None) -> dict:
    """Write the end-of-run totals record (cumulative phase/trace times,
    counters and memory gauges — after cross-host aggregation in
    multi-process runs)."""
    record = {
        "summary": True,
        "phase_times": _round_times(_phase_times),
        "phase_counts": dict(sorted(_phase_counts.items())),
        "trace_times": _round_times(_trace_times),
        "counters": dict(sorted(_counters.items())),
    }
    if _timeline:
        record["t"] = round(time.time(), 6)
    mem = memory_snapshot()
    if mem is not None:
        record["memory"] = mem
    ic = interconnect_snapshot()
    if ic is not None:
        record["interconnect"] = ic
    _attach_cost_blocks(record)
    try:
        from . import tracing
        trace = tracing.snapshot()
        if trace:
            # flight-recorder close-out (ISSUE 16): ring occupancy,
            # exact drop count and the live sketch percentiles ride the
            # summary record — percentiles at close without a bench run
            record["trace"] = trace
    except Exception:
        pass
    if extra:
        record.update(extra)
    write_record(record)
    return record
