"""Process-wide telemetry: phase timers, kernel-route counters, JSONL sink.

The repo's previous observability was three ad-hoc hacks: ``time.time()``
prints in cli.py, hist-stubbed A/B differencing in scripts/profile_phases.py
(PROFILE.md), and hand-assembled counter tables in BENCH rounds.  This module
replaces them with one registry, designed around two JAX realities:

1. **Route decisions are trace-time events.**  Kernel routing (Pallas int8 /
   bf16 / f32 hit, XLA einsum fallback, ``LGBM_TPU_NO_PALLAS`` trips,
   partition-kernel eligibility — ops/histogram.py, ops/compact.py) happens
   while a program is being *traced*; the compiled program then replays the
   chosen route forever.  Counters therefore increment once per traced
   decision — exactly the record of "which route did this program actually
   bake in" that the mixed-backend hardening episodes (commit e7ff0d9)
   lacked.  Recompiles are counted via a ``jax.monitoring`` backend-compile
   listener (cache hits fire nothing, so the count is true recompiles).

2. **Spans are host-side wall timers.**  ``span("histogram")`` times the
   enclosed *host* call with ``time.perf_counter``.  A span entered while
   JAX is tracing is recorded under ``trace_times`` (it measured tracing,
   not execution); a span entered with concrete arrays (the boosting loop's
   host phases, or any op under ``jax.disable_jit()``) is recorded under
   ``phase_times``.  The optional **fence mode** (``set_fence(True)`` /
   ``enable(fence=True)``) calls ``jax.block_until_ready`` on a value the
   caller hands to ``Span.fence(x)`` before stopping the timer, so async
   dispatch does not attribute device time to the wrong phase.  Fencing
   only *waits* on already-dispatched work — it never issues device
   computation — so it cannot trip the environment's ~60 s per-dispatch
   execution watchdog (BASELINE.md).

Zero overhead when disabled: every public entry checks one module flag and
returns a no-op singleton; nothing is ever inserted into traced programs,
so enabling/disabling telemetry perturbs neither numerics nor jit caching
(tests/test_telemetry.py locks this in).

JSONL sink: ``enable(jsonl_path)`` (the ``metrics_out=...`` config/CLI
option) arms a per-iteration record stream; the boosting loop emits one
line per iteration::

    {"iter": 3, "phase_times": {...}, "trace_times": {...},
     "counters": {...}, "eval_metrics": {...}}

``phase_times`` are seconds spent per phase *in that iteration* (chunked
training amortizes the fused k-iteration program evenly across its kept
iterations and marks ``"amortized_over": k``); ``counters`` are cumulative.
The canonical phase keys ``histogram``, ``split_find``, ``partition``,
``eval`` are always present.  In multi-process runs only process 0 opens
the sink (decided lazily at first write, after jax.distributed init);
``parallel.learners.aggregate_telemetry`` folds every host's counters into
the leader before the final summary record.  Library users who want the
data without a file call ``snapshot()``.
"""
from __future__ import annotations

import json
import time
from typing import Dict, List, Optional

# Canonical per-iteration phase keys — always present in iteration records
# (ISSUE 1 acceptance schema), whether or not the phase ran this iteration.
CANONICAL_PHASES = ("histogram", "split_find", "partition", "eval")

_enabled = False
_fence = False
_sink_path: Optional[str] = None
_sink_file = None
_sink_error = False

_counters: Dict[str, int] = {}
_phase_times: Dict[str, float] = {}
_phase_counts: Dict[str, int] = {}
_trace_times: Dict[str, float] = {}
# span re-entrancy stack (host-side, single-threaded boosting loop): a span
# whose name is already active is suppressed so recursive helpers
# (histogram_leafbatch's width-grouped self-calls, build_histogram →
# leafbatch) don't double-count wall time under one name
_span_stack: List[str] = []
# marks for per-iteration deltas
_mark_phase: Dict[str, float] = {}
_mark_trace: Dict[str, float] = {}
# last outcome per host-evaluated routing rule (count_route dedup)
_route_state: Dict[str, str] = {}

_compile_listener_installed = False


# --------------------------------------------------------------- life cycle

def enabled() -> bool:
    return _enabled


def enable(jsonl_path: Optional[str] = None, fence: bool = False) -> None:
    """Arm the registry (and optionally a JSONL sink at ``jsonl_path``).

    Idempotent; a second call can attach a sink or toggle fence mode.  The
    sink file is opened lazily at first record — after jax.distributed
    initialization — so only process 0 writes in multi-process runs.
    """
    global _enabled, _fence, _sink_path, _sink_error, _sink_file
    _enabled = True
    _fence = bool(fence)
    if jsonl_path:
        if _sink_file is not None and jsonl_path != _sink_path:
            # re-targeting an open sink: close the old handle or records
            # would keep landing in the previous file
            try:
                _sink_file.close()
            except OSError:
                pass
            _sink_file = None
        _sink_path = jsonl_path
        _sink_error = False
    _install_compile_listener()


def disable() -> None:
    """Stop recording and close the sink (pending data is flushed)."""
    global _enabled, _fence, _sink_file, _sink_path
    _enabled = False
    _fence = False
    if _sink_file is not None:
        try:
            _sink_file.close()
        except OSError:
            pass
    _sink_file = None
    _sink_path = None


def reset() -> None:
    """Zero all counters/timers (sink and enabled state are untouched)."""
    _counters.clear()
    _phase_times.clear()
    _phase_counts.clear()
    _trace_times.clear()
    _mark_phase.clear()
    _mark_trace.clear()
    _route_state.clear()
    del _span_stack[:]


def set_fence(on: bool) -> None:
    global _fence
    _fence = bool(on)


def fence_enabled() -> bool:
    return _fence


def sink_active() -> bool:
    """True when iteration records have somewhere to go (a sink path is
    configured) — the boosting loop's cheap guard around record assembly."""
    return _enabled and _sink_path is not None


# ------------------------------------------------------------------- spans

class _NullSpan:
    """No-op span returned while telemetry is disabled (or re-entrant)."""
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def fence(self, value):
        return value


_NULL_SPAN = _NullSpan()


def _tracing() -> bool:
    try:
        import jax.core
        return not jax.core.trace_state_clean()
    except Exception:
        return False


class Span:
    """Context-managed phase timer.  ``fence(x)`` hands the span a value to
    ``jax.block_until_ready`` at exit when fence mode is on (execution-time
    spans only; trace-time spans never block)."""
    __slots__ = ("name", "_t0", "_fence_val", "_is_trace")

    def __init__(self, name: str):
        self.name = name
        self._fence_val = None
        self._is_trace = False
        self._t0 = 0.0

    def __enter__(self):
        self._is_trace = _tracing()
        _span_stack.append(self.name)
        self._t0 = time.perf_counter()
        return self

    def fence(self, value):
        self._fence_val = value
        return value

    def __exit__(self, exc_type, exc, tb):
        if (_fence and not self._is_trace and exc_type is None
                and self._fence_val is not None):
            try:
                import jax
                jax.block_until_ready(self._fence_val)
            except Exception:
                pass
        dt = time.perf_counter() - self._t0
        self._fence_val = None
        if _span_stack and _span_stack[-1] == self.name:
            _span_stack.pop()
        if self._is_trace:
            _trace_times[self.name] = _trace_times.get(self.name, 0.0) + dt
        else:
            _phase_times[self.name] = _phase_times.get(self.name, 0.0) + dt
            _phase_counts[self.name] = _phase_counts.get(self.name, 0) + 1
        return False


def span(name: str):
    """Phase timer: ``with telemetry.span("histogram") as sp: ...``.

    Returns a shared no-op when telemetry is disabled or a span of the same
    name is already open (re-entrant helper calls)."""
    if not _enabled or name in _span_stack:
        return _NULL_SPAN
    return Span(name)


# ----------------------------------------------------------------- counters

def count(name: str, n: int = 1) -> None:
    """Bump a monotonic counter (kernel-route decisions, env-var trips,
    recompiles).  No-op while disabled."""
    if _enabled:
        _counters[name] = _counters.get(name, 0) + n


def count_route(group: str, name: str) -> None:
    """Record a routing-decision OUTCOME for a rule that host code
    re-evaluates every call (e.g. ops/compact.pallas_partition_ok, once
    per tree): counts once per outcome change within ``group``, so the
    counter reads as decisions, not evaluations — matching the trace-time
    counters' per-decision magnitude."""
    if not _enabled:
        return
    if _route_state.get(group) != name:
        _route_state[group] = name
        count(name)


def counters() -> Dict[str, int]:
    return dict(_counters)


def merge_host_counters(totals: Dict[str, int]) -> None:
    """Install cross-host counter sums (parallel.learners.
    aggregate_telemetry) under ``allhosts/`` keys on this process."""
    for k, v in totals.items():
        _counters["allhosts/" + k] = int(v)


def _install_compile_listener() -> None:
    """Count true recompiles via jax.monitoring: the backend-compile
    duration event fires once per compilation-cache miss and never on a
    hit, so the counter is exactly the number of XLA compiles this process
    paid.  Registered once; increments are gated on the enabled flag
    (jax.monitoring has no unregister)."""
    global _compile_listener_installed
    if _compile_listener_installed:
        return
    try:
        from jax import monitoring

        def _on_duration(name: str, dur: float, **kw) -> None:
            if _enabled and name.endswith("backend_compile_duration"):
                _counters["jit/backend_compile"] = (
                    _counters.get("jit/backend_compile", 0) + 1)
                _trace_times["backend_compile"] = (
                    _trace_times.get("backend_compile", 0.0) + dur)

        monitoring.register_event_duration_secs_listener(_on_duration)
        _compile_listener_installed = True
    except Exception:
        pass


# ---------------------------------------------------------------- snapshots

def snapshot() -> dict:
    """Cumulative registry state for library users (no sink required)."""
    return {
        "phase_times": dict(_phase_times),
        "phase_counts": dict(_phase_counts),
        "trace_times": dict(_trace_times),
        "counters": dict(_counters),
    }


def take_phase_deltas() -> "tuple[Dict[str, float], Dict[str, float]]":
    """(phase_times, trace_times) accumulated since the previous call, and
    re-mark.  The boosting loop calls this once per iteration (or once per
    fused chunk) to scope the per-record timings."""
    dp = {k: v - _mark_phase.get(k, 0.0) for k, v in _phase_times.items()
          if v - _mark_phase.get(k, 0.0) > 0.0}
    dt = {k: v - _mark_trace.get(k, 0.0) for k, v in _trace_times.items()
          if v - _mark_trace.get(k, 0.0) > 0.0}
    _mark_phase.clear()
    _mark_phase.update(_phase_times)
    _mark_trace.clear()
    _mark_trace.update(_trace_times)
    return dp, dt


# -------------------------------------------------------------------- sink

def _ensure_sink():
    """Open the sink on first write.  Deferred so jax.process_index() is
    consulted AFTER distributed init: only the leader writes."""
    global _sink_file, _sink_error
    if _sink_file is not None or _sink_path is None or _sink_error:
        return _sink_file
    try:
        import jax
        if jax.process_count() > 1 and jax.process_index() != 0:
            _sink_error = True   # non-leader: never write
            return None
    except Exception:
        pass
    try:
        _sink_file = open(_sink_path, "w")
    except OSError:
        from .utils import log
        log.warning("telemetry: cannot open metrics_out=%s; sink disabled"
                    % _sink_path)
        _sink_error = True
    return _sink_file


def _round_times(d: Dict[str, float]) -> Dict[str, float]:
    return {k: round(v, 6) for k, v in sorted(d.items())}


def write_record(record: dict) -> None:
    """Append one raw JSON line to the sink (no-op without a sink).

    Telemetry must never crash training: an I/O failure (disk full, stale
    mount) disables the sink with a warning, mirroring _ensure_sink's
    open-failure contract."""
    global _sink_error, _sink_file
    f = _ensure_sink()
    if f is None:
        return
    try:
        f.write(json.dumps(record) + "\n")
        f.flush()
    except OSError as e:
        from .utils import log
        log.warning("telemetry: write to metrics_out failed (%s); "
                    "sink disabled" % e)
        _sink_error = True
        try:
            f.close()
        except OSError:
            pass
        _sink_file = None


def emit_iteration(iteration: int, phase_times: Dict[str, float],
                   trace_times: Optional[Dict[str, float]] = None,
                   eval_metrics: Optional[dict] = None,
                   extra: Optional[dict] = None) -> dict:
    """Build and write one per-iteration record.  Canonical phase keys are
    always present; counters ride cumulatively.  Returns the record."""
    pt = {k: 0.0 for k in CANONICAL_PHASES}
    pt.update(phase_times)
    record = {
        "iter": int(iteration),
        "phase_times": _round_times(pt),
        "counters": dict(sorted(_counters.items())),
        "eval_metrics": eval_metrics or {},
    }
    if trace_times:
        record["trace_times"] = _round_times(trace_times)
    if extra:
        record.update(extra)
    write_record(record)
    return record


def emit_summary(extra: Optional[dict] = None) -> dict:
    """Write the end-of-run totals record (cumulative phase/trace times and
    counters — after cross-host aggregation in multi-process runs)."""
    record = {
        "summary": True,
        "phase_times": _round_times(_phase_times),
        "phase_counts": dict(sorted(_phase_counts.items())),
        "trace_times": _round_times(_trace_times),
        "counters": dict(sorted(_counters.items())),
    }
    if extra:
        record.update(extra)
    write_record(record)
    return record
