"""Process-wide telemetry: phase timers, kernel-route counters, JSONL sink.

The repo's previous observability was three ad-hoc hacks: ``time.time()``
prints in cli.py, hist-stubbed A/B differencing in scripts/profile_phases.py
(PROFILE.md), and hand-assembled counter tables in BENCH rounds.  This module
replaces them with one registry, designed around two JAX realities:

1. **Route decisions are trace-time events.**  Kernel routing (Pallas int8 /
   bf16 / f32 hit, XLA einsum fallback, ``LGBM_TPU_NO_PALLAS`` trips,
   partition-kernel eligibility — ops/histogram.py, ops/compact.py) happens
   while a program is being *traced*; the compiled program then replays the
   chosen route forever.  Counters therefore increment once per traced
   decision — exactly the record of "which route did this program actually
   bake in" that the mixed-backend hardening episodes (commit e7ff0d9)
   lacked.  Recompiles are counted via a ``jax.monitoring`` backend-compile
   listener (cache hits fire nothing, so the count is true recompiles).

2. **Spans are host-side wall timers.**  ``span("histogram")`` times the
   enclosed *host* call with ``time.perf_counter``.  A span entered while
   JAX is tracing is recorded under ``trace_times`` (it measured tracing,
   not execution); a span entered with concrete arrays (the boosting loop's
   host phases, or any op under ``jax.disable_jit()``) is recorded under
   ``phase_times``.  The optional **fence mode** (``set_fence(True)`` /
   ``enable(fence=True)``) calls ``jax.block_until_ready`` on a value the
   caller hands to ``Span.fence(x)`` before stopping the timer, so async
   dispatch does not attribute device time to the wrong phase.  Fencing
   only *waits* on already-dispatched work — it never issues device
   computation — so it cannot trip the environment's ~60 s per-dispatch
   execution watchdog (BASELINE.md).

Zero overhead when disabled: every public entry checks one module flag and
returns a no-op singleton; nothing is ever inserted into traced programs,
so enabling/disabling telemetry perturbs neither numerics nor jit caching
(tests/test_telemetry.py locks this in).

JSONL sink: ``enable(jsonl_path)`` (the ``metrics_out=...`` config/CLI
option) arms a per-iteration record stream; the boosting loop emits one
line per iteration::

    {"iter": 3, "phase_times": {...}, "trace_times": {...},
     "counters": {...}, "eval_metrics": {...}}

``phase_times`` are seconds spent per phase *in that iteration* (chunked
training amortizes the fused k-iteration program evenly across its kept
iterations and marks ``"amortized_over": k``); ``counters`` are cumulative.
The canonical phase keys ``histogram``, ``split_find``, ``partition``,
``eval`` are always present.  In multi-process runs only process 0 opens
the sink (decided lazily at first write, after jax.distributed init);
``parallel.learners.aggregate_telemetry`` folds every host's counters into
the leader before the final summary record.  Library users who want the
data without a file call ``snapshot()``.

ISSUE 2 additions — the device-side observability triad:

3. **Memory gauges** (``set_memory(True)`` / ``enable(memory=True)``, the
   ``memory_stats=`` config option): spans additionally sample the device
   allocator (``device.memory_stats()``; host-RSS fallback on backends
   that return None, e.g. CPU) at their boundaries, recording per-phase
   byte deltas and a process-peak ``bytes_in_use`` watermark.  Iteration
   records gain a ``memory`` block (``take_memory_record``), the summary
   and ``snapshot()`` a cumulative one, and ``set_residency`` files the
   one-shot dataset-residency report (bin matrix / metadata / histogram
   scratch) at train start.  Sampling is a host-side stats read — it
   never dispatches device work.

4. **Profiler alignment**: every span body runs under
   ``jax.named_scope(name)`` + ``jax.profiler.TraceAnnotation(name)``, so
   a Perfetto trace captured via ``profile_dir=`` carries the SAME phase
   names as the JSONL records — device rows (HLO op metadata) and host
   timeline rows line up with ``phase_times`` keys.  Health events (NaN
   counts, saturation, divergence — lightgbm_tpu/health.py) ride the
   iteration records as a ``health`` block via ``emit_iteration``.

ISSUE 4 — roofline attribution and compile observability
(lightgbm_tpu/costmodel.py rides this registry's lifecycle):

5. **Roofline + compile blocks**: enable()/disable()/reset() arm the
   compiled-program cost registry alongside the spans, so the summary
   record and ``snapshot()`` carry a ``roofline`` block (per-phase static
   flops/bytes from ``compiled.cost_analysis()`` joined to the measured
   spans → attained FLOP/s, HBM GB/s, fraction-of-peak) and a ``compile``
   block (program inventory, cold compile seconds, persistent-cache
   hits, mid-run recompiles).  ``emit_iteration`` watches the
   backend-compile counter: a compile AFTER the first iteration record
   is a mid-run recompile — counted (``jit/midrun_recompile``) and
   warned once, because it means a program cache key failed to capture
   something that changed.
"""
from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional

# Canonical per-iteration phase keys — always present in iteration records
# (ISSUE 1 acceptance schema), whether or not the phase ran this iteration.
CANONICAL_PHASES = ("histogram", "split_find", "partition", "eval")

_enabled = False
_fence = False
_sink_path: Optional[str] = None
_sink_file = None
_sink_error = False

_counters: Dict[str, int] = {}
_phase_times: Dict[str, float] = {}
_phase_counts: Dict[str, int] = {}
_trace_times: Dict[str, float] = {}
# span re-entrancy stack (host-side, single-threaded boosting loop): a span
# whose name is already active is suppressed so recursive helpers
# (histogram_leafbatch's width-grouped self-calls, build_histogram →
# leafbatch) don't double-count wall time under one name
_span_stack: List[str] = []
# marks for per-iteration deltas
_mark_phase: Dict[str, float] = {}
_mark_trace: Dict[str, float] = {}
# last outcome per host-evaluated routing rule (count_route dedup)
_route_state: Dict[str, str] = {}

# memory gauges (ISSUE 2): armed separately from the base registry so hot
# spans pay the allocator-stats read only when asked for
_memory = False
_mem_device = None            # cached jax device handle
_mem_source: Optional[str] = None
_mem_peak = 0                 # this run's bytes_in_use watermark
# the allocator's LIFETIME peak at the first post-reset sample: the device
# stat is monotonic since allocator creation, so a fresh run must baseline
# it or it would report the previous run's (possibly much larger) peak
_mem_dev_peak_base: Optional[int] = None
_mem_phase_delta: Dict[str, int] = {}   # cumulative per-phase byte deltas
_mem_phase_peak: Dict[str, int] = {}    # per-phase bytes_in_use watermark
_mark_mem: Dict[str, int] = {}          # per-iteration delta marks
_residency: Optional[dict] = None       # one-shot dataset-residency report
_allhosts_mem_peak: Optional[int] = None

_compile_listener_installed = False

# mid-run recompile watch (ISSUE 4): backend-compile count at the first
# iteration record; growth past it after that is a mid-run recompile
_compile_base: "Optional[int]" = None
_midrun_warned = False


# --------------------------------------------------------------- life cycle

def enabled() -> bool:
    return _enabled


def enable(jsonl_path: Optional[str] = None, fence: bool = False,
           memory: Optional[bool] = None) -> None:
    """Arm the registry (and optionally a JSONL sink at ``jsonl_path``).

    Idempotent; a second call can attach a sink or toggle fence mode.  The
    sink file is opened lazily at first record — after jax.distributed
    initialization — so only process 0 writes in multi-process runs.
    ``memory`` arms/disarms the span-boundary memory gauges (None leaves
    the current mode unchanged).
    """
    global _enabled, _fence, _sink_path, _sink_error, _sink_file, _memory
    _enabled = True
    _fence = bool(fence)
    if memory is not None:
        _memory = bool(memory)
    if jsonl_path:
        if _sink_file is not None and jsonl_path != _sink_path:
            # re-targeting an open sink: close the old handle or records
            # would keep landing in the previous file
            try:
                _sink_file.close()
            except OSError:
                pass
            _sink_file = None
        _sink_path = jsonl_path
        _sink_error = False
    _install_compile_listener()
    try:
        from . import costmodel
        costmodel.enable()
    except Exception:
        pass


def disable() -> None:
    """Stop recording and close the sink (pending data is flushed)."""
    global _enabled, _fence, _sink_file, _sink_path, _memory
    _enabled = False
    _fence = False
    _memory = False
    if _sink_file is not None:
        try:
            _sink_file.close()
        except OSError:
            pass
    _sink_file = None
    _sink_path = None
    try:
        from . import costmodel
        costmodel.disable()
    except Exception:
        pass


def reset() -> None:
    """Zero all counters/timers/gauges (sink and enabled state are
    untouched)."""
    global _mem_peak, _residency, _allhosts_mem_peak, _mem_dev_peak_base
    global _compile_base, _midrun_warned
    _compile_base = None
    _midrun_warned = False
    try:
        from . import costmodel
        costmodel.reset()
    except Exception:
        pass
    _counters.clear()
    _phase_times.clear()
    _phase_counts.clear()
    _trace_times.clear()
    _mark_phase.clear()
    _mark_trace.clear()
    _route_state.clear()
    _mem_phase_delta.clear()
    _mem_phase_peak.clear()
    _mark_mem.clear()
    _mem_peak = 0
    _mem_dev_peak_base = None    # re-baselined at the next sample
    _residency = None
    _allhosts_mem_peak = None
    del _span_stack[:]


def set_fence(on: bool) -> None:
    global _fence
    _fence = bool(on)


def fence_enabled() -> bool:
    return _fence


def set_memory(on: bool) -> None:
    """Arm/disarm the span-boundary memory gauges."""
    global _memory
    _memory = bool(on)


def memory_enabled() -> bool:
    return _memory


def sink_active() -> bool:
    """True when iteration records have somewhere to go (a sink path is
    configured) — the boosting loop's cheap guard around record assembly."""
    return _enabled and _sink_path is not None


def sink_open() -> bool:
    """True when a sink is configured or a file handle is still open —
    the test-suite leak guard's check (tests/conftest.py)."""
    return _sink_file is not None or (_enabled and _sink_path is not None)


# ---------------------------------------------------------- memory sampling

def _mem_sample() -> int:
    """Current memory footprint in bytes, updating the process watermark.

    Prefers the device allocator (``device.memory_stats()["bytes_in_use"]``
    — real HBM occupancy on TPU/GPU, including its own peak watermark);
    backends that return None (CPU) fall back to the process RSS from
    /proc/self/statm, so CPU runs still carry a meaningful gauge.  A pure
    stats read: never allocates or dispatches device work."""
    global _mem_device, _mem_source, _mem_peak, _mem_dev_peak_base
    try:
        if _mem_device is None:
            import jax
            _mem_device = jax.local_devices()[0]
        ms = _mem_device.memory_stats()
        if ms and "bytes_in_use" in ms:
            b = int(ms["bytes_in_use"])
            # the allocator's peak stat is monotonic over the PROCESS: only
            # growth past the post-reset baseline belongs to this run (it
            # catches transient spikes between our samples); a larger
            # previous run's peak must not leak into this run's watermark
            dev_peak = int(ms.get("peak_bytes_in_use", 0))
            if _mem_dev_peak_base is None:
                _mem_dev_peak_base = dev_peak
            if dev_peak > _mem_dev_peak_base:
                _mem_peak = max(_mem_peak, dev_peak)
            _mem_peak = max(_mem_peak, b)
            _mem_source = "device"
            return b
    except Exception:
        pass
    try:
        with open("/proc/self/statm") as f:
            b = int(f.read().split()[1]) * (os.sysconf("SC_PAGE_SIZE")
                                            if hasattr(os, "sysconf")
                                            else 4096)
        _mem_peak = max(_mem_peak, b)
        _mem_source = "host_rss"
        return b
    except Exception:
        if _mem_source is None:
            _mem_source = "unavailable"
        return 0


def take_memory_record() -> Optional[dict]:
    """Per-iteration ``memory`` block: current and peak bytes plus the
    per-phase byte deltas accumulated since the previous call (re-marks,
    mirroring take_phase_deltas).  None while memory gauges are off."""
    if not _memory:
        return None
    b = _mem_sample()
    deltas = {k: v - _mark_mem.get(k, 0)
              for k, v in _mem_phase_delta.items()
              if v - _mark_mem.get(k, 0) != 0}
    _mark_mem.clear()
    _mark_mem.update(_mem_phase_delta)
    rec = {"bytes_in_use": int(b), "peak_bytes_in_use": int(_mem_peak),
           "source": _mem_source or "unavailable"}
    if deltas:
        rec["phase_delta_bytes"] = {k: int(v)
                                    for k, v in sorted(deltas.items())}
    return rec


def memory_snapshot() -> Optional[dict]:
    """Cumulative memory block (summary record / ``snapshot()``): peak
    watermark, cumulative per-phase deltas and per-phase peaks, the
    dataset-residency report, and the cross-host peak when aggregated."""
    if not (_memory or _mem_phase_delta or _residency is not None):
        return None
    out = {"bytes_in_use": int(_mem_sample()) if _memory else 0,
           "peak_bytes_in_use": int(_mem_peak),
           "source": _mem_source or "unavailable"}
    if _mem_phase_delta:
        out["phase_delta_bytes"] = {k: int(v) for k, v
                                    in sorted(_mem_phase_delta.items())}
        out["phase_peak_bytes"] = {k: int(v) for k, v
                                   in sorted(_mem_phase_peak.items())}
    if _residency is not None:
        out["residency"] = _residency
    if _allhosts_mem_peak is not None:
        out["allhosts_peak_bytes_in_use"] = int(_allhosts_mem_peak)
    return out


def mem_peak_bytes() -> int:
    return int(_mem_peak)


def merge_host_memory(peak: int) -> None:
    """Install the cross-host peak-bytes maximum (parallel.learners.
    aggregate_telemetry) on this process."""
    global _allhosts_mem_peak
    _allhosts_mem_peak = int(peak)


def set_residency(report: dict) -> None:
    """File the one-shot dataset-residency report (bin matrix / metadata /
    histogram scratch footprint, computed at train start by gbdt.init): it
    rides ``memory_snapshot()`` and is written to the sink immediately as
    a standalone ``{"residency": ...}`` record."""
    global _residency
    _residency = dict(report)
    if sink_active():
        write_record({"residency": _residency})


# ------------------------------------------------------------------- spans

class _NullSpan:
    """No-op span returned while telemetry is disabled (or re-entrant)."""
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def fence(self, value):
        return value


_NULL_SPAN = _NullSpan()


def _tracing() -> bool:
    try:
        import jax.core
        return not jax.core.trace_state_clean()
    except Exception:
        return False


class Span:
    """Context-managed phase timer.  ``fence(x)`` hands the span a value to
    ``jax.block_until_ready`` at exit when fence mode is on (execution-time
    spans only; trace-time spans never block).

    Profiler alignment (ISSUE 2): the span body runs under
    ``jax.named_scope(name)`` (ops traced inside carry the phase name in
    HLO metadata → Perfetto device rows) and
    ``jax.profiler.TraceAnnotation(name)`` (a host-timeline trace event),
    so ``profile_dir=`` traces line up with the JSONL phase keys.  With
    memory gauges armed, the span also samples the allocator at its
    boundaries (per-phase byte delta + watermark)."""
    __slots__ = ("name", "_t0", "_fence_val", "_is_trace", "_scope",
                 "_ann", "_mem0")

    def __init__(self, name: str):
        self.name = name
        self._fence_val = None
        self._is_trace = False
        self._t0 = 0.0
        self._scope = None
        self._ann = None
        self._mem0 = None

    def __enter__(self):
        self._is_trace = _tracing()
        # two independent try blocks: if the annotation fails AFTER the
        # named scope entered, the scope must still be tracked (and later
        # exited) or the global name stack would grow one entry per span
        try:
            import jax
            self._scope = jax.named_scope(self.name)
            self._scope.__enter__()
        except Exception:
            self._scope = None
        try:
            import jax
            self._ann = jax.profiler.TraceAnnotation(self.name)
            self._ann.__enter__()
        except Exception:
            self._ann = None
        if _memory and not self._is_trace:
            self._mem0 = _mem_sample()
        _span_stack.append(self.name)
        self._t0 = time.perf_counter()
        return self

    def fence(self, value):
        self._fence_val = value
        return value

    def __exit__(self, exc_type, exc, tb):
        if (_fence and not self._is_trace and exc_type is None
                and self._fence_val is not None):
            try:
                import jax
                jax.block_until_ready(self._fence_val)
            except Exception:
                pass
        dt = time.perf_counter() - self._t0
        self._fence_val = None
        if self._ann is not None:
            try:
                self._ann.__exit__(exc_type, exc, tb)
            except Exception:
                pass
            self._ann = None
        if self._scope is not None:
            try:
                self._scope.__exit__(exc_type, exc, tb)
            except Exception:
                pass
            self._scope = None
        if self._mem0 is not None:
            b1 = _mem_sample()
            _mem_phase_delta[self.name] = (
                _mem_phase_delta.get(self.name, 0) + (b1 - self._mem0))
            _mem_phase_peak[self.name] = max(
                _mem_phase_peak.get(self.name, 0), b1, self._mem0)
            self._mem0 = None
        if _span_stack and _span_stack[-1] == self.name:
            _span_stack.pop()
        if self._is_trace:
            _trace_times[self.name] = _trace_times.get(self.name, 0.0) + dt
        else:
            _phase_times[self.name] = _phase_times.get(self.name, 0.0) + dt
            _phase_counts[self.name] = _phase_counts.get(self.name, 0) + 1
        return False


def span(name: str):
    """Phase timer: ``with telemetry.span("histogram") as sp: ...``.

    Returns a shared no-op when telemetry is disabled or a span of the same
    name is already open (re-entrant helper calls)."""
    if not _enabled or name in _span_stack:
        return _NULL_SPAN
    return Span(name)


# ----------------------------------------------------------------- counters

def count(name: str, n: int = 1) -> None:
    """Bump a monotonic counter (kernel-route decisions, env-var trips,
    recompiles).  No-op while disabled."""
    if _enabled:
        _counters[name] = _counters.get(name, 0) + n


def count_route(group: str, name: str) -> None:
    """Record a routing-decision OUTCOME for a rule that host code
    re-evaluates every call (e.g. ops/compact.pallas_partition_ok, once
    per tree): counts once per outcome change within ``group``, so the
    counter reads as decisions, not evaluations — matching the trace-time
    counters' per-decision magnitude."""
    if not _enabled:
        return
    if _route_state.get(group) != name:
        _route_state[group] = name
        count(name)


def counters() -> Dict[str, int]:
    return dict(_counters)


def merge_host_counters(totals: Dict[str, int]) -> None:
    """Install cross-host counter sums (parallel.learners.
    aggregate_telemetry) under ``allhosts/`` keys on this process."""
    for k, v in totals.items():
        _counters["allhosts/" + k] = int(v)


def _install_compile_listener() -> None:
    """Count true recompiles via jax.monitoring: the backend-compile
    duration event fires once per compilation-cache miss and never on a
    hit, so the counter is exactly the number of XLA compiles this process
    paid.  Registered once; increments are gated on the enabled flag
    (jax.monitoring has no unregister)."""
    global _compile_listener_installed
    if _compile_listener_installed:
        return
    try:
        from jax import monitoring

        def _on_duration(name: str, dur: float, **kw) -> None:
            if _enabled and name.endswith("backend_compile_duration"):
                _counters["jit/backend_compile"] = (
                    _counters.get("jit/backend_compile", 0) + 1)
                _trace_times["backend_compile"] = (
                    _trace_times.get("backend_compile", 0.0) + dur)

        monitoring.register_event_duration_secs_listener(_on_duration)
        # the duration listener is registered: mark installed NOW —
        # jax.monitoring has no unregister, so a failure in the second
        # (optional) registration below must not cause a later enable()
        # to stack a duplicate _on_duration listener
        _compile_listener_installed = True

        def _on_event(name: str, **kw) -> None:
            # persistent-compilation-cache hits (ISSUE 4): jax records
            # '/jax/compilation_cache/cache_hits' once per executable
            # served from the on-disk cache — together with
            # jit/backend_compile this decomposes "programs built" into
            # paid-compiles vs cache-served
            if _enabled and "cache_hit" in name:
                _counters["jit/persistent_cache_hit"] = (
                    _counters.get("jit/persistent_cache_hit", 0) + 1)

        try:
            monitoring.register_event_listener(_on_event)
        except Exception:
            pass
    except Exception:
        pass


def _watch_midrun_recompiles() -> None:
    """Called at each iteration record: backend compiles AFTER the first
    record mean a chunk/grower program cache key missed something that
    changed mid-run (the exact failure mode the PR-3 cache-key hardening
    fixed) — count them and warn once."""
    global _compile_base, _midrun_warned
    n = _counters.get("jit/backend_compile", 0)
    if _compile_base is None:
        _compile_base = n
        return
    if n > _compile_base:
        _counters["jit/midrun_recompile"] = (
            _counters.get("jit/midrun_recompile", 0) + (n - _compile_base))
        _compile_base = n
        if not _midrun_warned:
            _midrun_warned = True
            from .utils import log
            log.warning(
                "telemetry: %d backend compile(s) happened after the first "
                "iteration record (mid-run recompile) — a program cache "
                "key may not capture everything that changed"
                % _counters["jit/midrun_recompile"])


# ---------------------------------------------------------------- snapshots

def snapshot() -> dict:
    """Cumulative registry state for library users (no sink required)."""
    out = {
        "phase_times": dict(_phase_times),
        "phase_counts": dict(_phase_counts),
        "trace_times": dict(_trace_times),
        "counters": dict(_counters),
    }
    mem = memory_snapshot()
    if mem is not None:
        out["memory"] = mem
    _attach_cost_blocks(out)
    return out


def _attach_cost_blocks(record: dict) -> None:
    """Add the ``roofline`` and ``compile`` blocks (costmodel registry
    joined to the cumulative phase spans) to a summary-shaped record.
    Absent entirely while the cost registry has nothing — disabled-mode
    snapshots stay empty — and never raises (reporting must not crash
    training)."""
    try:
        from . import costmodel
        if costmodel.active():
            record["roofline"] = costmodel.roofline(dict(_phase_times),
                                                    fenced=_fence)
            record["compile"] = costmodel.compile_block()
    except Exception:
        pass


def take_phase_deltas() -> "tuple[Dict[str, float], Dict[str, float]]":
    """(phase_times, trace_times) accumulated since the previous call, and
    re-mark.  The boosting loop calls this once per iteration (or once per
    fused chunk) to scope the per-record timings."""
    dp = {k: v - _mark_phase.get(k, 0.0) for k, v in _phase_times.items()
          if v - _mark_phase.get(k, 0.0) > 0.0}
    dt = {k: v - _mark_trace.get(k, 0.0) for k, v in _trace_times.items()
          if v - _mark_trace.get(k, 0.0) > 0.0}
    _mark_phase.clear()
    _mark_phase.update(_phase_times)
    _mark_trace.clear()
    _mark_trace.update(_trace_times)
    return dp, dt


# -------------------------------------------------------------------- sink

def _ensure_sink():
    """Open the sink on first write.  Deferred so jax.process_index() is
    consulted AFTER distributed init: only the leader writes."""
    global _sink_file, _sink_error
    if _sink_file is not None or _sink_path is None or _sink_error:
        return _sink_file
    try:
        import jax
        if jax.process_count() > 1 and jax.process_index() != 0:
            _sink_error = True   # non-leader: never write
            return None
    except Exception:
        pass
    try:
        _sink_file = open(_sink_path, "w")
    except OSError:
        from .utils import log
        log.warning("telemetry: cannot open metrics_out=%s; sink disabled"
                    % _sink_path)
        _sink_error = True
    return _sink_file


def _round_times(d: Dict[str, float]) -> Dict[str, float]:
    return {k: round(v, 6) for k, v in sorted(d.items())}


def write_record(record: dict) -> None:
    """Append one raw JSON line to the sink (no-op without a sink).

    Telemetry must never crash training: an I/O failure (disk full, stale
    mount) disables the sink with a warning, mirroring _ensure_sink's
    open-failure contract."""
    global _sink_error, _sink_file
    f = _ensure_sink()
    if f is None:
        return
    try:
        f.write(json.dumps(record) + "\n")
        f.flush()
    except OSError as e:
        from .utils import log
        log.warning("telemetry: write to metrics_out failed (%s); "
                    "sink disabled" % e)
        _sink_error = True
        try:
            f.close()
        except OSError:
            pass
        _sink_file = None


def emit_iteration(iteration: int, phase_times: Dict[str, float],
                   trace_times: Optional[Dict[str, float]] = None,
                   eval_metrics: Optional[dict] = None,
                   health: Optional[dict] = None,
                   memory: Optional[dict] = None,
                   extra: Optional[dict] = None) -> dict:
    """Build and write one per-iteration record.  Canonical phase keys are
    always present; counters ride cumulatively.  ``health`` is the
    iteration's training-health block (lightgbm_tpu/health.py),
    ``memory`` the per-iteration gauge block (take_memory_record).
    Returns the record."""
    _watch_midrun_recompiles()
    pt = {k: 0.0 for k in CANONICAL_PHASES}
    pt.update(phase_times)
    record = {
        "iter": int(iteration),
        "phase_times": _round_times(pt),
        "counters": dict(sorted(_counters.items())),
        "eval_metrics": eval_metrics or {},
    }
    if trace_times:
        record["trace_times"] = _round_times(trace_times)
    if health is not None:
        record["health"] = health
    if memory is not None:
        record["memory"] = memory
    if extra:
        record.update(extra)
    write_record(record)
    return record


def emit_summary(extra: Optional[dict] = None) -> dict:
    """Write the end-of-run totals record (cumulative phase/trace times,
    counters and memory gauges — after cross-host aggregation in
    multi-process runs)."""
    record = {
        "summary": True,
        "phase_times": _round_times(_phase_times),
        "phase_counts": dict(sorted(_phase_counts.items())),
        "trace_times": _round_times(_trace_times),
        "counters": dict(sorted(_counters.items())),
    }
    mem = memory_snapshot()
    if mem is not None:
        record["memory"] = mem
    _attach_cost_blocks(record)
    if extra:
        record.update(extra)
    write_record(record)
    return record
