"""LGBM_TPU_* environment hatches: one loud-reject parser, one inventory.

Every A/B and escape hatch this repo grew (Pallas kill switches, the
ingest double-buffer A/B, fault injection, distributed bootstrap) used
to be a bare ``os.environ.get("LGBM_TPU_...", "") == "1"`` at its point
of use — which meant (a) a typo'd VALUE (``LGBM_TPU_INGEST_SYNC=true``)
silently did nothing instead of rejecting, and (b) there was no single
place that could answer "which hatches exist" (the docstrings
hand-enumerated them, drifting).  This module is both fixes:

- :data:`HATCHES` is the generated hatch inventory — one entry per
  environment variable, with its value shape and one-line purpose.
  graftlint C4 (analysis/concurrency_rules.py) fails the pre-merge gate
  on any ``LGBM_TPU_*`` read that bypasses this module, and on any
  helper call naming a hatch missing from the inventory — so the
  inventory can never drift from the code again.
- The typed readers (:func:`flag`, :func:`choice`, :func:`raw`,
  :func:`int_value`, :func:`float_value`) reject malformed values with
  ``log.fatal`` (naming the variable and the accepted shape) instead of
  silently ignoring them, matching the config system's typed-getter
  contract (config.py ``_get_int``/``_get_bool``).

Readers consult the environment per call — the hatches are flipped
mid-process by the A/B harnesses (__graft_entry__ flips NO_PALLAS
between virtual meshes; bench.py flips INGEST_SYNC around the
double-buffer A/B), so nothing here may cache.
"""
from __future__ import annotations

import os
from typing import Optional, Sequence

from .utils import log

# The hatch inventory (graftlint C4's census anchor): every LGBM_TPU_*
# variable the package reads, its value shape, and what it does.
HATCHES = {
    "LGBM_TPU_NO_PALLAS":
        ("flag", "disable EVERY Pallas kernel (histogram + partition) — "
                 "the mixed-backend escape hatch dryrun_multichip sets"),
    "LGBM_TPU_HIST_EINSUM":
        ("flag", "force the XLA einsum histogram formulation for all "
                 "dtypes (A/B timing hatch)"),
    "LGBM_TPU_PARTITION_NO_OVERLAP":
        ("flag", "serialized partition-kernel DMA schedule (A/B against "
                 "the overlapped default; bit-identical)"),
    "LGBM_TPU_NO_MIXEDBIN":
        ("flag", "force the uniform feature layout — mixed-bin packing "
                 "A/B without touching configs"),
    "LGBM_TPU_INGEST_SYNC":
        ("flag", "depth-0 synchronous ingest transfers — the streaming "
                 "double-buffer A/B (bench.py --bench-ingest)"),
    "LGBM_TPU_HOST_BAGGING":
        ("flag", "host-side bagging draw + full-N mask upload — the "
                 "device-bagging A/B; beats the bagging_device config"),
    "LGBM_TPU_PIPELINE":
        ("choice:off|readback", "pipelined-boosting override — beats the "
                                "pipeline= config for A/B timing"),
    "LGBM_TPU_FAULT_AT":
        ("spec", "'<iter>[,<kind>]' one-shot fault injection at an "
                 "iteration boundary (faults.parse_spec loud-rejects)"),
    "LGBM_TPU_FAULT_PROC":
        ("int", "process index the armed fault fires on (default 0)"),
    "LGBM_TPU_FAULT_STALL_S":
        ("float", "stall duration in seconds for the 'stall' fault kind "
                  "(default 1.0)"),
    "LGBM_TPU_COORDINATOR":
        ("str", "jax.distributed coordinator address — presence engages "
                "multi-host bootstrap"),
    "LGBM_TPU_NUM_PROCS":
        ("int", "process count for jax.distributed bootstrap (default 1)"),
    "LGBM_TPU_PROC_ID":
        ("int", "this process's index for jax.distributed bootstrap "
                "(default 0)"),
}


def _require_registered(name: str) -> None:
    if name not in HATCHES:
        log.fatal("env hatch %s is not in the hatches.HATCHES inventory — "
                  "register it (graftlint C4 gates unregistered reads)"
                  % name)


def flag(name: str) -> bool:
    """Boolean hatch: unset/''/'0' -> False, '1' -> True, anything else
    is a loud reject (a typo'd value must never silently do nothing)."""
    _require_registered(name)
    value = os.environ.get(name, "")
    if value in ("", "0"):
        return False
    if value == "1":
        return True
    log.fatal("env hatch %s must be '1' or '0'/unset, got %r"
              % (name, value))


def choice(name: str, allowed: Sequence[str], default: str = "") -> str:
    """Enumerated hatch: unset -> ``default``; any other value must be in
    ``allowed``."""
    _require_registered(name)
    value = os.environ.get(name)
    if value is None or value == "":
        return default
    if value not in allowed:
        log.fatal("env hatch %s must be one of %s, got %r"
                  % (name, "/".join(allowed), value))
    return value


def raw(name: str, default: Optional[str] = None) -> Optional[str]:
    """Free-form hatch (addresses, fault specs) — registration is still
    required; value validation belongs to the consumer's own
    loud-reject parser (e.g. faults.parse_spec)."""
    _require_registered(name)
    return os.environ.get(name, default)


def int_value(name: str, default: int) -> int:
    _require_registered(name)
    value = os.environ.get(name)
    if value is None or value == "":
        return int(default)
    try:
        return int(value)
    except ValueError:
        log.fatal("env hatch %s must be an int, got %r" % (name, value))


def float_value(name: str, default: float) -> float:
    _require_registered(name)
    value = os.environ.get(name)
    if value is None or value == "":
        return float(default)
    try:
        return float(value)
    except ValueError:
        log.fatal("env hatch %s must be a float, got %r" % (name, value))
